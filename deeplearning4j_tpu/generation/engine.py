"""GenerationEngine: continuous-batching decode scheduler.

The serving loop for autoregressive decode. A fixed array of *slots*
holds in-flight sequences; every scheduler iteration dispatches ONE
jitted tick over the whole slot batch, then routes each active slot's
sampled token to its stream. Sequences join (taking the lowest free
slot, carries zeroed + PRNG reseeded inside the tick via the reset
mask) and retire (stop token, max length, cancel) mid-flight without
ever draining the batch — the continuous-batching property that keeps
the device busy at high sequence turnover.

Device residency: the (h, c) carries and per-slot PRNG keys live on
device across ticks and are never fetched. The per-tick host traffic is
the small int32/bool control arrays in and the sampled tokens out —
the tokens *are* the streamed response payload (pragma'd host
boundary); graftlint's host-sync rule polices everything else.

Compile discipline mirrors ``parallel/serving.py``: the tick is
AOT-lowered per slot-count bucket (pow2 ladder up to ``max_slots``)
and the bucket grow/shrink resize steps are AOT-warmed too, so after
``_warmup_sweep`` a recompile is a bug — ``assert_warm()`` and the
RecompileWatchdog both say so.

Telemetry: the ``dl4j_gen_*`` family (tokens, per-token p50/p99,
time-to-first-token, active slots, retired sequences by outcome,
stream errors, compiles by phase) — see OBSERVABILITY.md.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from deeplearning4j_tpu.generation import decode as D
from deeplearning4j_tpu.generation import speculative as SP
from deeplearning4j_tpu.generation.session import CarrySnapshot
from deeplearning4j_tpu.observe.latency import LatencyRing
from deeplearning4j_tpu.observe.recompile import RecompileWatchdog
from deeplearning4j_tpu.observe.registry import default_registry
from deeplearning4j_tpu.parallel.deadline import Deadline, DeadlineExceeded

log = logging.getLogger(__name__)

_QUANTILES = (0.5, 0.95, 0.99)


def _bucket_ladder(max_slots: int) -> List[int]:
    out, b = [], 1
    while b < max_slots:
        out.append(b)
        b <<= 1
    out.append(max_slots)
    return out


def _reachable_resize_pairs(ladder: List[int]) -> List[tuple]:
    """The (src, dst) resize pairs the scheduler can actually request,
    instead of the full quadratic ordered sweep. Grows jump to ANY
    higher rung (``_admit_locked`` targets the first rung covering
    demand, so a 1 -> 8 burst is one resize), but shrinks only ever
    step to the ADJACENT lower rung (``_maybe_shrink_locked``), so the
    downward pairs beyond distance one are unreachable dead warmup
    weight — roughly half the all-pairs sweep for real ladders."""
    pairs = [(src, dst)
             for i, src in enumerate(ladder) for dst in ladder[i + 1:]]
    pairs += [(ladder[i], ladder[i - 1])
              for i in range(1, len(ladder))]
    return pairs


class GenerationStream:
    """One sequence's token stream: the scheduler produces events, one
    consumer iterates them (the SSE writer, or ``result()``). Events
    are plain dicts so the UI layer can serialize them as-is:
    ``{"token": id, "text": ch, "i": n}`` per token, then a terminal
    ``{"done": True, "reason": ..., "n": ..., "ttft_ms": ...}`` or
    ``{"error": msg}``."""

    _END = object()

    def __init__(self, request: Dict[str, Any], buffer: int = 4096):
        self.request = request
        self.ids: List[int] = []
        self.reason: Optional[str] = None
        self.error: Optional[str] = None
        self.ttft_ms: Optional[float] = None
        self.session: Optional[str] = None
        self._q: "queue.Queue" = queue.Queue(maxsize=buffer)
        self._done = threading.Event()
        self._cancelled = threading.Event()
        self._cb_lock = threading.Lock()
        self._callbacks: List[Any] = []

    # -- consumer side -------------------------------------------------

    def __iter__(self):
        while True:
            ev = self._q.get()
            if ev is self._END:
                return
            yield ev

    def result(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        """Drain the stream and return the completed sequence."""
        deadline = None if timeout is None else time.time() + timeout
        while True:
            left = None if deadline is None else deadline - time.time()
            if left is not None and left <= 0:
                raise TimeoutError("generation stream timed out")
            try:
                ev = self._q.get(timeout=left)
            except queue.Empty:
                raise TimeoutError("generation stream timed out")
            if ev is self._END:
                break
        if self.error is not None:
            raise RuntimeError(self.error)
        out = {"ids": list(self.ids), "reason": self.reason,
               "n": len(self.ids), "ttft_ms": self.ttft_ms}
        if self.session is not None:
            out["session"] = self.session
        return out

    def cancel(self):
        """Ask the scheduler to retire this sequence early (client went
        away mid-stream). Safe from any thread; idempotent."""
        self._cancelled.set()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def add_done_callback(self, fn):
        with self._cb_lock:
            if not self._done.is_set():
                self._callbacks.append(fn)
                return
        try:
            fn(self)
        except Exception:
            log.exception("generation stream callback failed")

    # -- scheduler side ------------------------------------------------

    def _push(self, ev: Dict[str, Any]) -> bool:
        try:
            self._q.put_nowait(ev)
            return True
        except queue.Full:
            return False

    def _finish(self, reason: str):
        self.reason = reason
        ev = {"done": True, "reason": reason, "n": len(self.ids),
              "ttft_ms": self.ttft_ms}
        if self.session is not None:
            ev["session"] = self.session
        self._push(ev)
        self._seal()

    def _fail(self, msg: str):
        self.error = msg
        self.reason = "error"
        self._push({"error": msg})
        self._seal()

    def _seal(self):
        self._done.set()
        try:
            self._q.put_nowait(self._END)
        except queue.Full:
            # consumer is gone and the buffer is packed; drop one event
            # to guarantee the END marker lands, else iterators hang
            try:
                self._q.get_nowait()
            except queue.Empty:
                pass
            self._q.put_nowait(self._END)
        with self._cb_lock:
            cbs, self._callbacks = self._callbacks, []
        for fn in cbs:
            try:
                fn(self)
            except Exception:
                log.exception("generation stream callback failed")


class _Slot:
    """Scheduler-private per-slot state (host side only)."""

    __slots__ = ("stream", "prompt", "ppos", "next_input", "gen_count",
                 "max_new", "stop_id", "seed", "temperature", "top_k",
                 "greedy", "needs_reset", "t_join", "t_first",
                 "deadline", "session", "resume", "pos", "draft",
                 "prefill_mode")

    def __init__(self, stream: GenerationStream, prompt: List[int],
                 max_new: int, stop_id: Optional[int], seed: int,
                 temperature: float, top_k: int, greedy: bool,
                 deadline: Optional[Deadline] = None,
                 session: Optional[str] = None,
                 resume: Optional[CarrySnapshot] = None):
        self.stream = stream
        self.prompt = prompt
        self.ppos = 1
        self.next_input = prompt[0]
        self.gen_count = 0
        self.max_new = max_new
        self.stop_id = stop_id
        self.seed = seed
        self.temperature = temperature
        self.top_k = top_k
        self.greedy = greedy
        self.needs_reset = resume is None
        self.t_join = time.time()
        self.t_first: Optional[float] = None
        self.deadline = deadline
        self.session = session
        self.resume = resume
        # absolute sequence position = tokens fed so far, the counter
        # the splitmix64 sampling keys index (resumes continue it)
        self.pos = resume.pos if resume is not None else 0
        self.draft: Optional[SP.NGramDraft] = None
        self.prefill_mode = "tick"


class GenerationEngine:
    """Continuous-batching decode serving over one committed model.

    ``submit()`` returns a :class:`GenerationStream` immediately; the
    background scheduler thread packs waiting sequences into free
    slots, grows/shrinks the slot bucket along the AOT-warmed ladder,
    and pushes sampled tokens into each stream as they decode.
    """

    def __init__(self, model, *, max_slots: Optional[int] = None,
                 precision: Union[str, Any] = "f32",
                 vocab: Optional[D.Vocab] = None,
                 max_new_tokens: int = 256,
                 stop_text: Optional[str] = "\n",
                 queue_limit: int = 128,
                 stream_buffer: int = 4096,
                 int8_budget: float = 0.03,
                 calibration_text: str = "the quick brown fox jumps "
                                         "over the lazy dog\n",
                 registry=None, watchdog=None,
                 session_id: str = "generate",
                 prefill_chunk: Optional[int] = None,
                 speculative: int = 0,
                 sampling: Optional[str] = None,
                 session_store=None,
                 tuned_config=None):
        # explicit kwargs > TunedConfig (engine-local, else process) >
        # committed defaults — the measured slot geometry and prefill
        # chunk tune BOTH the runtime shape and the AOT warm set (slot
        # ladder, resize pairs, chunk ladder all derive from them)
        from deeplearning4j_tpu.optimize.autotune import resolve_tuned
        max_slots = int(resolve_tuned(max_slots, tuned_config,
                                      "generation.max_slots"))
        prefill_chunk = int(resolve_tuned(prefill_chunk, tuned_config,
                                          "generation.prefill_chunk"))
        self.tuned_config = tuned_config
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        self.model = model
        self.spec = D.extract_decode_spec(model)
        self.vocab = vocab if vocab is not None \
            else D.Vocab.default_for(self.spec.vocab_size)
        self.precision = getattr(precision, "mode", precision)
        if self.precision not in ("f32", "bf16", "int8"):
            raise ValueError(f"unknown precision {self.precision!r}")
        self.max_slots = int(max_slots)
        self.max_new_tokens = int(max_new_tokens)
        self.queue_limit = int(queue_limit)
        self.stream_buffer = int(stream_buffer)
        self.session_id = session_id
        # v2 serving modes (ISSUE 16): chunked prefill, speculative
        # decode, counter-based sampling keys, resumable sessions
        self._prefill_chunk = int(prefill_chunk)
        self._spec_k = int(speculative)
        if self._spec_k < 0 or self._prefill_chunk < 0:
            raise ValueError("prefill_chunk/speculative must be >= 0")
        # speculative acceptance needs position-addressable sampling
        # keys, so it defaults the engine into counter mode; chain is
        # the legacy split-chain default otherwise
        self.sampling = sampling if sampling is not None \
            else ("counter" if self._spec_k else "chain")
        if self.sampling not in ("chain", "counter"):
            raise ValueError(f"unknown sampling mode {self.sampling!r}")
        self.session_store = session_store
        self.chunk_ladder = (
            D.prefill_chunk_ladder(self._prefill_chunk)
            if self._prefill_chunk else [])
        self.stop_id: Optional[int] = None
        if stop_text:
            sid = self.vocab.stoi.get(stop_text)
            if sid is not None:
                self.stop_id = int(sid)

        self.registry = registry if registry is not None \
            else default_registry()
        self.watchdog = watchdog if watchdog is not None else \
            RecompileWatchdog(self.registry, session_id=session_id)

        # int8 head: calibrate + decode-level quant gate before commit
        self.gate_result = None
        x_scale = None
        if self.precision == "int8":
            probe = self.vocab.encode(calibration_text) or [0]
            x_scale, self.gate_result = D.int8_head_gate(
                model, self.spec, probe, top1_budget=int8_budget,
                model_name=session_id, registry=self.registry)
        self._dp = D.commit_decode_params(
            model, self.spec, self.precision, x_scale=x_scale)

        import jax
        self._tick_jit = jax.jit(D.build_tick(model, self.spec))
        self._prefill_jit = (jax.jit(D.build_prefill(model, self.spec))
                             if self._prefill_chunk else None)
        self._spec_jit = (jax.jit(SP.build_spec_tick(
            model, self.spec, self._spec_k)) if self._spec_k else None)
        self._extract_jit = (jax.jit(D.build_slot_extract(self.spec))
                             if session_store is not None else None)
        self._restore_jit_fn = (jax.jit(D.build_slot_restore(self.spec))
                                if session_store is not None else None)
        self._resize_jit: Dict[tuple, Any] = {}
        self.ladder = _bucket_ladder(self.max_slots)

        # executables: ("tick", S) and ("resize", src, dst)
        self._exe: Dict[tuple, Any] = {}
        self._exe_lock = threading.Lock()
        self._warmed = False
        self._post_warmup_compiles = 0

        # scheduler state — slot objects + device-resident carry/rng
        self._cv = threading.Condition()
        self._waiting: List[_Slot] = []
        self._slots: List[Optional[_Slot]] = [None] * self.max_slots
        self._bucket = self.ladder[0]
        self._h, self._c, self._rng = D.zero_carries(
            self.spec, self._bucket)
        self._shrink_streak = 0
        self._stop = threading.Event()

        # accounting
        self.token_ring = LatencyRing()
        self.ttft_ring = LatencyRing()
        # TTFT split by prefill mode: chunked dispatches vs the legacy
        # one-tick-per-prompt-char path — the A/B the chunked ladder
        # has to win
        self.ttft_rings = {"chunked": LatencyRing(),
                           "tick": LatencyRing()}
        self._submitted = 0
        self._tokens_out = 0
        self._prefill_ticks = 0
        self._prefill_chunks = 0
        self._prefill_chunk_tokens = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_dispatches = 0
        self._flush_mark = 0
        self._max_active = 0
        self._outcomes: Dict[str, int] = {}
        self._stream_errors = 0

        r = self.registry
        self._c_tokens = r.counter(
            "dl4j_gen_tokens_total", "generated tokens streamed")
        self._c_seqs = r.counter(
            "dl4j_gen_sequences_total",
            "retired sequences by outcome "
            "(stop|length|cancelled|error|deadline)")
        self._c_compiles = r.counter(
            "dl4j_gen_compiles_total",
            "decode executable compiles by phase (warmup|live)")
        self._c_stream_err = r.counter(
            "dl4j_gen_stream_errors_total",
            "streams dropped mid-flight (slow consumer / transport)")
        self._c_deadline = r.counter(
            "dl4j_gen_deadline_shed_total",
            "sequences shed because their deadline expired; stage="
            "ingress (refused at submit) | queue (dropped while "
            "waiting for a slot) | decode (retired mid-decode)")
        self._c_disconnect = r.counter(
            "dl4j_gen_client_disconnect_total",
            "sequences cancelled because the streaming client "
            "disconnected mid-generation")
        self._g_active = r.gauge(
            "dl4j_gen_active_slots", "sequences currently decoding")
        self._g_bucket = r.gauge(
            "dl4j_gen_slot_bucket", "current slot-count bucket")
        self._g_queue = r.gauge(
            "dl4j_gen_queue_depth", "sequences waiting for a slot")
        self._g_token_ms = r.gauge(
            "dl4j_gen_token_ms", "per-token decode latency quantiles")
        self._g_ttft = r.gauge(
            "dl4j_gen_ttft_ms", "time-to-first-token quantiles")
        self._c_prefill_chunks = r.counter(
            "dl4j_gen_prefill_chunks_total",
            "chunked-prefill dispatches (one jitted scan consuming up "
            "to prefill_chunk prompt tokens per in-prefill slot)")
        self._c_prefill_tokens = r.counter(
            "dl4j_gen_prefill_tokens_total",
            "prompt tokens consumed, by prefill mode: chunked (scan "
            "dispatches) vs tick (one batched tick per char)")
        self._g_prefill_ttft = r.gauge(
            "dl4j_gen_prefill_ttft_ms",
            "time-to-first-token quantiles split by the prefill mode "
            "the sequence took")
        self._c_spec_proposed = r.counter(
            "dl4j_gen_spec_proposed_total",
            "draft tokens proposed by the n-gram table and attached "
            "to speculative verify dispatches")
        self._c_spec_accepted = r.counter(
            "dl4j_gen_spec_accepted_total",
            "draft tokens accepted (bitwise-equal to what plain decode "
            "would have emitted at their position)")
        # pre-register healthy series so /metrics shows the family at 0
        self._c_prefill_chunks.inc(0.0, session=session_id)
        for mode in ("chunked", "tick"):
            self._c_prefill_tokens.inc(0.0, session=session_id,
                                       mode=mode)
        self._c_spec_proposed.inc(0.0, session=session_id)
        self._c_spec_accepted.inc(0.0, session=session_id)
        self._c_tokens.inc(0.0, session=session_id)
        self._c_compiles.inc(0.0, session=session_id, phase="live")
        self._c_stream_err.inc(0.0, session=session_id)
        for oc in ("stop", "length", "cancelled", "error", "deadline"):
            self._c_seqs.inc(0.0, session=session_id, outcome=oc)
        for stage in ("ingress", "queue", "decode"):
            self._c_deadline.inc(0.0, session=session_id, stage=stage)
        self._c_disconnect.inc(0.0, session=session_id)
        self._g_active.set(0.0, session=session_id)
        self._g_bucket.set(float(self._bucket), session=session_id)  # host-sync-ok: python int gauge, no device value
        self._g_queue.set(0.0, session=session_id)

        t0 = time.time()
        self._warmup_sweep()
        self.warmup_s = time.time() - t0
        self._warmed = True

        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"generation-scheduler-{session_id}")
        self._thread.start()

    # ---- executables -------------------------------------------------

    def _host_args(self, S: int):
        return (np.zeros(S, np.int32), np.zeros(S, bool),
                np.zeros(S, np.uint32), np.zeros(S, bool),
                np.ones(S, np.float32), np.zeros(S, np.int32),
                np.ones(S, bool), np.zeros((S, 2), np.uint32),
                np.zeros(S, bool))

    def _spec_args(self, S: int):
        K1 = self._spec_k + 1
        return (np.zeros((S, K1), np.int32), np.zeros(S, np.int32),
                np.zeros(S, bool), np.zeros(S, np.uint32),
                np.ones(S, bool), np.ones(S, np.float32),
                np.zeros(S, np.int32), np.ones(S, bool),
                np.zeros((S, K1, 2), np.uint32), np.zeros(S, bool))

    def _prefill_args(self, S: int, C: int):
        return (np.zeros((S, C), np.int32), np.zeros(S, np.int32),
                np.zeros(S, bool), np.zeros(S, np.uint32),
                np.ones(S, bool))

    def _compile(self, key: tuple):
        phase = "warmup" if not self._warmed else "live"
        if self._warmed:
            self._post_warmup_compiles += 1
            log.warning("generation: live compile for %s", key)
        self._c_compiles.inc(1.0, session=self.session_id, phase=phase)
        if key[0] == "tick":
            S = key[1]
            h, c, rng = D.zero_carries(self.spec, S)
            try:
                return self._tick_jit.lower(
                    self._dp, h, c, rng, *self._host_args(S)).compile()
            except Exception:
                log.exception("AOT lower failed for %s; using jit", key)
                return self._tick_jit
        if key[0] == "spec":
            S = key[1]
            h, c, rng = D.zero_carries(self.spec, S)
            try:
                return self._spec_jit.lower(
                    self._dp, h, c, rng, *self._spec_args(S)).compile()
            except Exception:
                log.exception("AOT lower failed for %s; using jit", key)
                return self._spec_jit
        if key[0] == "prefill":
            _, S, C = key
            h, c, rng = D.zero_carries(self.spec, S)
            try:
                return self._prefill_jit.lower(
                    self._dp, h, c, rng,
                    *self._prefill_args(S, C)).compile()
            except Exception:
                log.exception("AOT lower failed for %s; using jit", key)
                return self._prefill_jit
        if key[0] == "extract":
            S = key[1]
            h, c, rng = D.zero_carries(self.spec, S)
            try:
                return self._extract_jit.lower(
                    h, c, rng, np.int32(0)).compile()
            except Exception:
                log.exception("AOT lower failed for %s; using jit", key)
                return self._extract_jit
        if key[0] == "restore":
            S = key[1]
            h, c, rng = D.zero_carries(self.spec, S)
            hr = [np.zeros(hd, np.float32)
                  for hd in self.spec.hidden_sizes]
            cr = [np.zeros(hd, np.float32)
                  for hd in self.spec.hidden_sizes]
            rr = np.zeros(2, np.uint32)
            try:
                return self._restore_jit_fn.lower(
                    h, c, rng, hr, cr, rr, np.int32(0)).compile()
            except Exception:
                log.exception("AOT lower failed for %s; using jit", key)
                return self._restore_jit_fn
        _, src, dst = key
        rj = self._resize_jit.get((src, dst))
        if rj is None:
            import jax
            rj = jax.jit(D.build_resize(self.spec, src, dst))
            self._resize_jit[(src, dst)] = rj
        h, c, rng = D.zero_carries(self.spec, src)
        try:
            return rj.lower(h, c, rng).compile()
        except Exception:
            log.exception("AOT lower failed for %s; using jit", key)
            return rj

    def _get_exe(self, key: tuple):
        exe = self._exe.get(key)
        if exe is None:
            with self._exe_lock:
                exe = self._exe.get(key)
                if exe is None:
                    exe = self._compile(key)
                    self._exe[key] = exe
        return exe

    def _warmup_sweep(self):
        """Compile + run every executable a live request can reach, per
        ladder bucket: the decode dispatch (the speculative verify step
        when drafts are on — it subsumes the plain tick, since
        ``n_draft=0`` IS plain-tick semantics, so the tick itself never
        dispatches and never needs warming), the prefill chunk ladder,
        the session extract/restore pair, and the resize pairs the
        scheduler's policy can actually request
        (:func:`_reachable_resize_pairs` — grows jump rungs on demand
        bursts, shrinks only ever step to the adjacent lower rung)."""
        for S in self.ladder:
            h, c, rng = D.zero_carries(self.spec, S)
            if self._spec_k:
                exe = self._get_exe(("spec", S))
                out = exe(self._dp, h, c, rng, *self._spec_args(S))
                out[4].block_until_ready()  # host-sync-ok: warmup sweep is pre-traffic by design
            else:
                exe = self._get_exe(("tick", S))
                out = exe(self._dp, h, c, rng, *self._host_args(S))
                out[3].block_until_ready()  # host-sync-ok: warmup sweep is pre-traffic by design
            for C in self.chunk_ladder:
                exe = self._get_exe(("prefill", S, C))
                out = exe(self._dp, h, c, rng,
                          *self._prefill_args(S, C))
                out[2].block_until_ready()  # host-sync-ok: warmup sweep is pre-traffic by design
            if self.session_store is not None:
                exe = self._get_exe(("extract", S))
                out = exe(h, c, rng, np.int32(0))
                out[2].block_until_ready()  # host-sync-ok: warmup sweep is pre-traffic by design
                hr = [np.zeros(hd, np.float32)
                      for hd in self.spec.hidden_sizes]
                cr = [np.zeros(hd, np.float32)
                      for hd in self.spec.hidden_sizes]
                exe = self._get_exe(("restore", S))
                out = exe(h, c, rng, hr, cr,
                          np.zeros(2, np.uint32), np.int32(0))
                out[2].block_until_ready()  # host-sync-ok: warmup sweep is pre-traffic by design
        for src, dst in _reachable_resize_pairs(self.ladder):
            exe = self._get_exe(("resize", src, dst))
            h, c, rng = D.zero_carries(self.spec, src)
            out = exe(h, c, rng)
            out[2].block_until_ready()  # host-sync-ok: warmup sweep is pre-traffic by design

    # ---- public API --------------------------------------------------

    def submit(self, prompt: Union[str, Sequence[int]], *,
               max_new_tokens: Optional[int] = None, greedy: bool = True,
               temperature: float = 1.0, top_k: int = 0, seed: int = 0,
               stop: Optional[Union[str, int]] = None,
               deadline: Optional[Deadline] = None,
               session: Optional[str] = None
               ) -> GenerationStream:
        """Queue one sequence; returns its stream immediately. Raises
        RuntimeError when the waiting queue is at ``queue_limit`` —
        FleetRouter admission turns that into a shed upstream. An
        already-expired ``deadline`` raises ``DeadlineExceeded``
        synchronously — the sequence never queues, never decodes.

        ``session`` names a resumable carry in the engine's
        :class:`~deeplearning4j_tpu.generation.session.SessionStore`.
        On a hit the sequence continues from the stored (h, c)/PRNG
        state — the new prompt extends the old one without replaying
        the prefix, bitwise-equal to never having retired; on a miss it
        starts fresh. Either way the carry is re-captured when this
        sequence retires, so the token stays resumable turn after turn
        (and, via the write-through checkpoint, on other nodes)."""
        if self._stop.is_set():
            raise RuntimeError("generation engine is shut down")
        if session is not None and self.session_store is None:
            raise ValueError(
                "session= requires an engine with a session_store")
        if deadline is not None and deadline.expired:
            self._c_deadline.inc(1.0, session=self.session_id,
                                 stage="ingress")
            raise DeadlineExceeded(
                "generation: deadline expired at ingress")
        if isinstance(prompt, str):
            ids = self.vocab.encode(prompt)
        else:
            ids = [int(t) for t in prompt]
        resume = None
        if session is not None:
            resume = self.session_store.load(session)
        if resume is not None:
            # the resumed carry still owes the model its pending tokens
            # (last emitted, or the unconsumed prompt tail) — they lead
            # the new prompt through the normal prefill path
            ids = [int(t) for t in resume.pending] + ids
        elif not ids:
            ids = [self.stop_id if self.stop_id is not None else 0]
        if not ids:
            raise ValueError("resume produced an empty prompt")
        bad = [t for t in ids if not 0 <= t < self.spec.vocab_size]
        if bad:
            raise ValueError(f"prompt ids out of range: {bad[:5]}")
        stop_id = self.stop_id
        if isinstance(stop, str):
            stop_id = self.vocab.stoi.get(stop, stop_id)
        elif isinstance(stop, int):
            stop_id = stop
        req = {"prompt": list(ids), "greedy": bool(greedy),
               "temperature": float(temperature), "top_k": int(top_k),  # host-sync-ok: request parsing, host scalars
               "seed": int(seed), "stop_id": stop_id,
               "max_new_tokens": int(max_new_tokens
                                     if max_new_tokens is not None
                                     else self.max_new_tokens)}
        if session is not None:
            req["session"] = session
        stream = GenerationStream(req, buffer=self.stream_buffer)
        stream.session = session
        slot = _Slot(stream, req["prompt"], req["max_new_tokens"],
                     stop_id, req["seed"], req["temperature"],
                     req["top_k"], req["greedy"], deadline=deadline,
                     session=session, resume=resume)
        if self._prefill_chunk and len(slot.prompt) > 1:
            slot.prefill_mode = "chunked"
        if self._spec_k:
            slot.draft = SP.NGramDraft()
            if resume is not None:
                slot.draft.observe_many(resume.history)
                slot.draft.observe_many(
                    slot.prompt[len(resume.pending):])
            else:
                slot.draft.observe_many(slot.prompt)
        with self._cv:
            if len(self._waiting) >= self.queue_limit:
                raise RuntimeError("generation queue full")
            self._waiting.append(slot)
            self._submitted += 1
            self._cv.notify()
        return stream

    def generate(self, prompt, **kw) -> Dict[str, Any]:
        """Blocking convenience: submit and wait for the result."""
        timeout = kw.pop("timeout", None)
        res = self.submit(prompt, **kw).result(timeout=timeout)
        res["text"] = self.vocab.decode(res["ids"])
        return res

    def cancel(self, stream: GenerationStream, *,
               disconnect: bool = False) -> bool:
        """Retire a submitted sequence early and free its slot. A
        sequence still in the waiting queue is finished ``cancelled``
        immediately; one decoding in a slot is flagged and the
        scheduler retires it on its next pass over the slot (prefill
        or decode — it never runs the sequence to completion first).
        ``disconnect=True`` marks the cancel as a client disconnect
        (the SSE writer's path) on
        ``dl4j_gen_client_disconnect_total``. Returns True when the
        sequence was still live."""
        if disconnect and not stream.done:
            self._c_disconnect.inc(1.0, session=self.session_id)
        stream.cancel()
        with self._cv:
            for idx, s in enumerate(self._waiting):
                if s.stream is stream:
                    self._waiting.pop(idx)
                    stream._finish("cancelled")
                    self._retired(s, "cancelled")
                    return True
            live = not stream.done
            self._cv.notify()
        return live

    def pending_depth(self) -> int:
        with self._cv:
            return len(self._waiting) + sum(
                1 for s in self._slots if s is not None)

    def assert_warm(self):
        if self._post_warmup_compiles:
            raise RuntimeError(
                f"{self._post_warmup_compiles} decode compile(s) after "
                "warmup — the bucket ladder missed a live shape")
        if self.watchdog.count() > 0:
            raise RuntimeError(
                "recompile watchdog observed signature drift in the "
                "decode loop")

    def stats(self) -> Dict[str, Any]:
        tq = self.token_ring.quantiles(_QUANTILES)    # {q: seconds}
        fq = self.ttft_ring.quantiles(_QUANTILES)
        with self._cv:
            active = sum(1 for s in self._slots if s is not None)
            waiting = len(self._waiting)
        out = {
            "session": self.session_id,
            "precision": self.precision,
            "sampling": self.sampling,
            "slots": {"bucket": self._bucket, "max": self.max_slots,
                      "active": active, "waiting": waiting,
                      "max_active": self._max_active,
                      "ladder": list(self.ladder)},
            "sequences": {"submitted": self._submitted,
                          "retired": dict(self._outcomes)},
            "tokens": {"generated": self._tokens_out,
                       "prefill_ticks": self._prefill_ticks},
            "prefill": {"chunk": self._prefill_chunk,
                        "ladder": list(self.chunk_ladder),
                        "chunks": self._prefill_chunks,
                        "chunk_tokens": self._prefill_chunk_tokens,
                        "tick_tokens": self._prefill_ticks},
            "latency_ms": {
                "token": {f"p{int(q * 100)}": v * 1e3
                          for q, v in tq.items()},
                "ttft": {f"p{int(q * 100)}": v * 1e3
                         for q, v in fq.items()},
                "ttft_by_mode": {
                    mode: {f"p{int(q * 100)}": v * 1e3
                           for q, v in ring.quantiles(
                               _QUANTILES).items()}
                    for mode, ring in self.ttft_rings.items()}},
            "stream_errors": self._stream_errors,
            "recompiles_after_warmup": self._post_warmup_compiles,
            "warmup_s": round(self.warmup_s, 3),
            "head_agreement": (self.gate_result.top1_agreement
                               if self.gate_result else None),
        }
        if self._spec_k:
            prop = self._spec_proposed
            out["speculative"] = {
                "k": self._spec_k,
                "proposed": prop,
                "accepted": self._spec_accepted,
                "dispatches": self._spec_dispatches,
                "acceptance": (self._spec_accepted / prop
                               if prop else None)}
        if self.session_store is not None:
            out["session_store"] = self.session_store.stats()
        return out

    def shutdown(self, timeout: float = 5.0):
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        self._thread.join(timeout=timeout)
        with self._cv:
            in_flight = [(i, s) for i, s in enumerate(self._slots)
                         if s is not None]
            waiting = list(self._waiting)
            self._slots = [None] * self.max_slots
            self._waiting = []
        for i, s in in_flight:
            # drain capture: between dispatches an in-flight slot's
            # device state is consistent, so a SIGTERM-style shutdown
            # checkpoints its session carry — the client resumes the
            # token on another node sharing the artifact store
            self._capture_session(i, s)
            s.stream._fail("generation engine shut down")
            self._retired(s, "error", count_metrics=False)
        for s in waiting:
            s.stream._fail("generation engine shut down")
            self._retired(s, "error", count_metrics=False)

    # ---- scheduler ----------------------------------------------------

    def _retired(self, slot: _Slot, outcome: str,
                 count_metrics: bool = True):
        self._outcomes[outcome] = self._outcomes.get(outcome, 0) + 1
        if count_metrics:
            self._c_seqs.inc(1.0, session=self.session_id,
                             outcome=outcome)

    def _admit_locked(self):
        """Pack waiting sequences into free slots, growing the bucket
        along the ladder first when demand exceeds it. Expired or
        cancelled waiters are dropped here — they never take a slot,
        never touch the device. Called under ``_cv``."""
        if self._waiting:
            live: List[_Slot] = []
            for s in self._waiting:
                if s.stream._cancelled.is_set():
                    s.stream._finish("cancelled")
                    self._retired(s, "cancelled")
                elif s.deadline is not None and s.deadline.expired:
                    self._c_deadline.inc(1.0, session=self.session_id,
                                         stage="queue")
                    s.stream._finish("deadline")
                    self._retired(s, "deadline")
                else:
                    live.append(s)
            self._waiting = live  # graftlint: disable=thread-discipline: caller holds _cv (same lock shutdown takes)
        active_idx = [i for i, s in enumerate(self._slots)
                      if s is not None]
        demand = len(active_idx) + len(self._waiting)
        if demand > self._bucket and self._bucket < self.max_slots:
            target = next((s for s in self.ladder
                           if s >= min(demand, self.max_slots)),
                          self.ladder[-1])
            self._resize(target)
        free = [i for i in range(self._bucket)
                if self._slots[i] is None]
        while self._waiting and free:
            i = free.pop(0)
            self._slots[i] = self._waiting.pop(0)
        self._shrink_streak = 0 if self._waiting else self._shrink_streak

    def _maybe_shrink_locked(self):
        """Drop to the previous ladder bucket after a streak of ticks
        where every active slot fits in it (hysteresis avoids thrash).
        Slots are pinned — a sequence never migrates — so we only
        shrink when the upper rows are empty."""
        idx = self.ladder.index(self._bucket)
        if idx == 0 or self._waiting:
            return
        prev = self.ladder[idx - 1]
        if any(self._slots[i] is not None
               for i in range(prev, self._bucket)):
            self._shrink_streak = 0
            return
        self._shrink_streak += 1
        if self._shrink_streak >= 16:
            self._resize(prev)
            self._shrink_streak = 0

    def _resize(self, target: int):
        if target == self._bucket:
            return
        exe = self._get_exe(("resize", self._bucket, target))
        self._h, self._c, self._rng = exe(self._h, self._c, self._rng)
        self._bucket = target
        self._g_bucket.set(float(target), session=self.session_id)  # host-sync-ok: python int gauge, no device value

    def _loop(self):
        while not self._stop.is_set():
            with self._cv:
                while (not self._stop.is_set()
                       and not self._waiting
                       and all(s is None for s in self._slots)):
                    self._cv.wait(timeout=0.25)
                if self._stop.is_set():
                    return
                self._admit_locked()
                S = self._bucket
                slots = list(self._slots[:S])
                self._g_queue.set(float(len(self._waiting)),  # host-sync-ok: python int gauge, no device value
                                  session=self.session_id)
            try:
                self._tick_once(S, slots)
            except Exception as e:  # a broken tick must not kill serving
                log.exception("generation tick failed")
                with self._cv:
                    for i, s in enumerate(self._slots):
                        if s is not None:
                            s.stream._fail(f"decode tick failed: {e}")
                            self._retired(s, "error")
                            self._slots[i] = None

    def _capture_session(self, i: int, s: _Slot,
                         overrun: bool = False):
        """Checkpoint a retiring slot's carry into the session store.
        Skipped when the sequence has no session token, the engine no
        store, nothing was ever fed (``needs_reset`` still set), the
        loaded snapshot was never restored into a device slot (the
        store's copy is still the truth), or the device state overran
        the committed stream (a speculative dispatch that stopped
        before its last accepted position) — a resume must continue
        from exactly the state the client saw."""
        if (self.session_store is None or s.session is None
                or s.needs_reset or s.resume is not None or overrun):
            return
        exe = self._get_exe(("extract", self._bucket))
        hr, cr, rr = exe(self._h, self._c, self._rng, np.int32(i))
        pending = [int(s.next_input)]
        pending += [int(t) for t in s.prompt[s.ppos:]]
        if s.draft is not None:
            history = list(s.draft.history)
        else:
            history = [int(t) for t in s.prompt] + list(s.stream.ids)
            history = history[-512:]
        snap = CarrySnapshot(
            [np.asarray(x) for x in hr],  # host-sync-ok: session capture at retirement, once per retired sequence — not the per-token path
            [np.asarray(x) for x in cr],  # host-sync-ok: session capture at retirement, once per retired sequence — not the per-token path
            np.asarray(rr, np.uint32),  # host-sync-ok: session capture at retirement, once per retired sequence — not the per-token path
            pending, s.pos, history)
        try:
            self.session_store.save(s.session, snap)
        except Exception:
            log.exception("session capture failed for %s", s.session)

    def _restore_slot(self, S: int, i: int, s: _Slot):
        """Scatter a resumed session's carry rows into slot ``i``. Runs
        before the slot's first dispatch (its ``needs_reset`` is False,
        so without the scatter it would decode from stale rows)."""
        snap = s.resume
        s.resume = None
        hr = [np.asarray(x, np.float32) for x in snap.h]  # host-sync-ok: snapshot rows are host numpy already
        cr = [np.asarray(x, np.float32) for x in snap.c]  # host-sync-ok: snapshot rows are host numpy already
        rr = np.asarray(snap.rng, np.uint32)  # host-sync-ok: snapshot rows are host numpy already
        exe = self._get_exe(("restore", S))
        self.watchdog.observe(f"gen_restore_s{S}", hr, cr, rr)
        self._h, self._c, self._rng = exe(
            self._h, self._c, self._rng, hr, cr, rr, np.int32(i))

    def _retire_eligible(self, i: int, s: _Slot,
                         retire: List[tuple]) -> bool:
        """Cancel/deadline check between dispatches; True if the slot
        was retired. Running this BEFORE every dispatch — including
        between the chunked-prefill scans of one long prompt — is what
        closes the prefill blind spot: a client that hung up (or a
        budget that ran out) during prompt ingestion must not keep
        burning dispatches until sampling starts. Between dispatches
        the device state is consistent, so these retires are capture-
        safe (overrun=False)."""
        if s.stream._cancelled.is_set():
            retire.append((i, s, "cancelled", False))
            return True
        if s.deadline is not None and s.deadline.expired:
            self._c_deadline.inc(1.0, session=self.session_id,
                                 stage="decode")
            retire.append((i, s, "deadline", False))
            return True
        return False

    def _commit_retires_locked(self, retire: List[tuple]):
        for i, s, outcome, overrun in retire:
            if outcome != "error":
                # capture BEFORE the terminal stream event: a client
                # that fires its next turn the instant it sees "done"
                # must already find the carry resumable
                self._capture_session(i, s, overrun=overrun)
                s.stream._finish(outcome)
            self._retired(s, outcome)
            self._slots[i] = None

    def _prefill_pass(self, S: int, slots: List[Optional[_Slot]],
                      retire: List[tuple]):
        """Consume every chunked slot's remaining prompt — all but its
        LAST token, which the sampling dispatch feeds to emit the first
        token — in ladder-sized jitted scans. A 512-char prompt costs
        ~ceil(511/chunk) dispatches instead of 511 ticks; the PRNG
        chain advances identically either way (one split per consumed
        token), so chunked and tick prefill are bitwise-interchangeable.
        """
        while True:
            for i, s in enumerate(slots):
                if s is not None and self._retire_eligible(i, s,
                                                           retire):
                    slots[i] = None
            rem = {i: len(s.prompt) - s.ppos
                   for i, s in enumerate(slots)
                   if s is not None and s.prefill_mode == "chunked"
                   and len(s.prompt) - s.ppos > 0}
            if not rem:
                return
            top = max(rem.values())
            C = self.chunk_ladder[-1]
            for c in self.chunk_ladder:
                if c >= top:
                    C = c
                    break
            chunk = np.zeros((S, C), np.int32)
            lens = np.zeros(S, np.int32)
            reset = np.zeros(S, bool)
            seeds = np.zeros(S, np.uint32)
            active = np.zeros(S, bool)
            consumed = 0
            for i, n in rem.items():
                s = slots[i]
                t = min(n, C)
                chunk[i, :t] = s.prompt[s.ppos - 1:s.ppos - 1 + t]
                lens[i] = t
                reset[i] = s.needs_reset
                seeds[i] = np.uint32(s.seed & 0xFFFFFFFF)
                active[i] = True
                consumed += t
            exe = self._get_exe(("prefill", S, C))
            self.watchdog.observe(
                f"gen_prefill_{self.precision}_s{S}_c{C}",
                chunk, lens, reset, seeds, active)
            self._h, self._c, self._rng = exe(
                self._dp, self._h, self._c, self._rng, chunk, lens,
                reset, seeds, active)
            for i, n in rem.items():
                s = slots[i]
                t = min(n, C)
                s.ppos += t
                s.pos += t
                s.needs_reset = False
                if s.ppos >= len(s.prompt):
                    s.next_input = s.prompt[s.ppos - 1]
            self._prefill_chunks += 1
            self._prefill_chunk_tokens += consumed
            self._c_prefill_chunks.inc(1.0, session=self.session_id)
            self._c_prefill_tokens.inc(float(consumed),  # host-sync-ok: consumed is a host int accumulator
                                       session=self.session_id,
                                       mode="chunked")

    def _tick_once(self, S: int, slots: List[Optional[_Slot]]):
        retire: List[tuple] = []      # (i, slot, outcome, overrun)

        # 0) session restore: scatter resumed carries into their slots
        #    before anything dispatches over them
        for i, s in enumerate(slots):
            if s is not None and s.resume is not None:
                self._restore_slot(S, i, s)

        # 1) chunked prefill (with mid-prefill retirement checks)
        if self._prefill_chunk:
            self._prefill_pass(S, slots, retire)

        # 2) build the decode dispatch's control arrays; cancel/expired
        #    slots retire here, BEFORE the dispatch, so their device
        #    state stays consistent for session capture
        tokens = np.zeros(S, np.int32)
        reset = np.zeros(S, bool)
        seeds = np.zeros(S, np.uint32)
        active = np.zeros(S, bool)
        temp = np.ones(S, np.float32)
        topk = np.zeros(S, np.int32)
        greedy = np.ones(S, bool)
        pos = np.zeros(S, np.uint64)
        in_prefill = [False] * S
        n_active = 0
        for i, s in enumerate(slots):
            if s is None:
                continue
            if self._retire_eligible(i, s, retire):
                slots[i] = None
                continue
            n_active += 1
            tokens[i] = s.next_input
            reset[i] = s.needs_reset
            seeds[i] = np.uint32(s.seed & 0xFFFFFFFF)
            active[i] = True
            temp[i] = s.temperature
            topk[i] = s.top_k
            greedy[i] = s.greedy
            pos[i] = s.pos
            in_prefill[i] = s.ppos < len(s.prompt)
        self._max_active = max(self._max_active, n_active)
        self._g_active.set(float(n_active), session=self.session_id)  # host-sync-ok: python int gauge, no device value
        if n_active == 0:
            with self._cv:
                self._commit_retires_locked(retire)
                self._maybe_shrink_locked()
            return

        # 3) ONE decode dispatch: the speculative verify step when
        #    drafts are on (n_draft=0 degrades to plain-tick semantics,
        #    so prefilling/chain-mode co-residents are unaffected),
        #    else the plain tick
        use_ext = np.zeros(S, bool)
        if self._spec_k:
            K1 = self._spec_k + 1
            toks2 = np.zeros((S, K1), np.int32)
            toks2[:, 0] = tokens
            n_draft = np.zeros(S, np.int32)
            for i, s in enumerate(slots):
                if s is None or in_prefill[i] or s.draft is None:
                    continue
                if not (s.greedy or self.sampling == "counter"):
                    # chain-mode sampling has no position-addressable
                    # keys, so acceptance can't be verified — plain
                    # tick semantics for this slot
                    continue
                cap = min(self._spec_k, s.max_new - s.gen_count - 1)
                if cap <= 0:
                    continue
                d = s.draft.propose(cap)
                if d:
                    toks2[i, 1:1 + len(d)] = d
                    n_draft[i] = len(d)
                    self._spec_proposed += len(d)
                    self._c_spec_proposed.inc(float(len(d)),  # host-sync-ok: draft is a host-side list
                                              session=self.session_id)
            ext_keys = np.zeros((S, K1, 2), np.uint32)
            if self.sampling == "counter":
                ext_keys = SP.counter_keys(seeds, pos, K1)
                use_ext = active.copy()
            exe = self._get_exe(("spec", S))
            self.watchdog.observe(
                f"gen_spec_{self.precision}_s{S}", toks2, n_draft,
                reset, seeds, active, temp, topk, greedy, ext_keys,
                use_ext)
            t0 = time.time()
            self._h, self._c, self._rng, out, ne = exe(
                self._dp, self._h, self._c, self._rng, toks2, n_draft,
                reset, seeds, active, temp, topk, greedy, ext_keys,
                use_ext)
            emitted = np.asarray(out)  # host-sync-ok: streaming egress — the sampled tokens ARE the response payload
            n_emit = np.asarray(ne)  # host-sync-ok: streaming egress — the commit counts route the response payload
            self._spec_dispatches += 1
        else:
            ext_key = np.zeros((S, 2), np.uint32)
            if self.sampling == "counter":
                ext_key = SP.counter_keys(seeds, pos, 1)[:, 0]
                use_ext = active.copy()
            exe = self._get_exe(("tick", S))
            self.watchdog.observe(f"gen_tick_{self.precision}_s{S}",
                                  tokens, reset, seeds, active, temp,
                                  topk, greedy, ext_key, use_ext)
            t0 = time.time()
            self._h, self._c, self._rng, out = exe(
                self._dp, self._h, self._c, self._rng, tokens, reset,
                seeds, active, temp, topk, greedy, ext_key, use_ext)
            emitted = np.asarray(out)[:, None]  # host-sync-ok: streaming egress — the sampled tokens ARE the response payload
            n_emit = active.astype(np.int32)
        dt = time.time() - t0
        self.token_ring.record(dt)
        now = time.time()

        # 4) route emitted tokens (possibly several per slot)
        for i, s in enumerate(slots):
            if s is None:
                continue
            s.needs_reset = False
            if in_prefill[i]:            # tick prefill: force next char
                s.next_input = s.prompt[s.ppos]
                s.ppos += 1
                s.pos += 1
                self._prefill_ticks += 1
                self._c_prefill_tokens.inc(1.0,
                                           session=self.session_id,
                                           mode="tick")
                continue
            m = int(n_emit[i])
            outcome = None
            overrun = False
            for j in range(m):
                tok = int(emitted[i, j])
                s.gen_count += 1
                s.pos += 1
                s.next_input = tok
                s.stream.ids.append(tok)
                if s.draft is not None:
                    s.draft.observe(tok)
                if s.t_first is None:
                    s.t_first = now
                    s.stream.ttft_ms = (now - s.t_join) * 1e3
                    self.ttft_ring.record(now - s.t_join)
                    ring = self.ttft_rings.get(s.prefill_mode)
                    if ring is not None:
                        ring.record(now - s.t_join)
                ok = s.stream._push({"token": tok,
                                     "text": self.vocab.itos[tok]
                                     if tok < self.vocab.size else "�",
                                     "i": s.gen_count - 1})
                self._tokens_out += 1
                self._c_tokens.inc(1.0, session=self.session_id)
                if j >= 1:               # an accepted draft made it out
                    self._spec_accepted += 1
                    self._c_spec_accepted.inc(1.0,
                                              session=self.session_id)
                if not ok:
                    self._stream_errors += 1
                    self._c_stream_err.inc(1.0,
                                           session=self.session_id)
                    s.stream._fail("stream buffer overflow "
                                   "(consumer too slow)")
                    outcome = "error"
                elif s.stream._cancelled.is_set():
                    outcome = "cancelled"
                elif s.stop_id is not None and tok == s.stop_id:
                    outcome = "stop"
                elif s.gen_count >= s.max_new:
                    outcome = "length"
                if outcome is not None:
                    # retiring before the dispatch's last emitted token
                    # leaves the device state ahead of the committed
                    # stream — the capture path must know
                    overrun = j < m - 1
                    break
            if outcome is not None:
                retire.append((i, s, outcome, overrun))

        if self._tokens_out - self._flush_mark >= 64:
            self._flush_mark = self._tokens_out
            for q, v in self.token_ring.quantiles(_QUANTILES).items():
                self._g_token_ms.set(v * 1e3, session=self.session_id,
                                     quantile=str(q))
            for q, v in self.ttft_ring.quantiles(_QUANTILES).items():
                self._g_ttft.set(v * 1e3, session=self.session_id,
                                 quantile=str(q))
            for mode, ring in self.ttft_rings.items():
                for q, v in ring.quantiles(_QUANTILES).items():
                    self._g_prefill_ttft.set(
                        v * 1e3, session=self.session_id, mode=mode,
                        quantile=str(q))

        with self._cv:
            self._commit_retires_locked(retire)
            self._maybe_shrink_locked()
