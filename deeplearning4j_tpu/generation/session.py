"""SessionStore: retired-but-resumable decode carries.

Multi-turn generation re-pays the whole prefix on every request unless
the (h, c) carry survives retirement. This store keeps retired
sequences' per-slot state in three tiers:

- **device** — rows pinned on device (LRU, ``device_capacity``): a
  same-node resume re-scatters them without a host round-trip;
- **host** — LRU overflow lands as numpy rows (``host_capacity``);
- **store** — every save is written through to the shared
  :class:`~deeplearning4j_tpu.parallel.aot_cache.ArtifactStore` (when
  configured), so a session started on node A resumes on node B after
  a SIGTERM drain — or node A's SIGKILL — with nothing but the session
  token. Rides PR 11's object layout (one key per session under
  ``objects/``) and PR 14's integrity discipline: the carry blob is
  sha256-checksummed, the manifest is written atomically LAST, and a
  corrupt blob quarantines aside (``.quarantine``) instead of
  resuming garbage — the ``chaos_site("store.save")`` seam mangles
  the bytes under an armed chaos plan exactly like the AOT cache's.

Snapshots carry everything continuation needs to be **bitwise** equal
to an undrained run: the f32 carry rows, the per-slot PRNG row (chain
mode), the absolute position (counter mode), the tokens still owed to
the model (``pending`` — the retired sequence's last emitted token, or
its unconsumed prompt tail), and a history tail to reseed the
speculative draft table.

``carry_dtype="int8"`` quantizes stored rows through the
``ops/quantize.py`` primitives (symmetric, one scale per row) to raise
resumable sessions per chip ~4x; it trades the bitwise-resume guarantee
for capacity, so it is opt-in and recorded in the checkpoint manifest.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.chaos.hook import chaos_site
from deeplearning4j_tpu.observe.registry import default_registry
from deeplearning4j_tpu.ops.quantize import Q_MAX, activation_scale

log = logging.getLogger(__name__)

_CARRY_BLOB = "carry.npz"
_MANIFEST = "session.json"


class CarrySnapshot:
    """One retired sequence's resumable state (host representation)."""

    __slots__ = ("h", "c", "rng", "pending", "pos", "history")

    def __init__(self, h: List[np.ndarray], c: List[np.ndarray],
                 rng: np.ndarray, pending: List[int], pos: int,
                 history: List[int]):
        self.h = h
        self.c = c
        self.rng = rng
        self.pending = pending
        self.pos = pos
        self.history = history


def _quantize_rows(rows: List[np.ndarray]):
    """f32 rows -> (int8 rows, f32 scales) via the quantize.py
    conventions: symmetric, one scale per row (amax / 127, host-side
    numpy so the bytes are deterministic cross-process)."""
    qs, scales = [], []
    for r in rows:
        r = np.asarray(r, np.float32)  # host-sync-ok: carry rows arrive as host numpy
        scale = activation_scale(float(np.abs(r).max()))  # host-sync-ok: host numpy reduction
        q = np.clip(np.rint(r / scale), -Q_MAX, Q_MAX).astype(np.int8)
        qs.append(q)
        scales.append(np.float32(scale))
    return qs, np.asarray(scales, np.float32)  # host-sync-ok: host scalars


def _dequantize_rows(qs: List[np.ndarray], scales: np.ndarray):
    return [np.asarray(q, np.float32) * np.float32(s)  # host-sync-ok: host numpy dequant
            for q, s in zip(qs, scales)]


class _Entry:
    __slots__ = ("h", "c", "h_scales", "c_scales", "rng", "pending",
                 "pos", "history", "tier")

    def __init__(self, h, c, h_scales, c_scales, rng, pending, pos,
                 history, tier):
        self.h = h
        self.c = c
        self.h_scales = h_scales
        self.c_scales = c_scales
        self.rng = rng
        self.pending = pending
        self.pos = pos
        self.history = history
        self.tier = tier


class SessionStore:
    """Tiered LRU of resumable carries, keyed by session token."""

    def __init__(self, spec, *, device_capacity: int = 32,
                 host_capacity: int = 256, store=None,
                 store_prefix: str = "gen-session",
                 carry_dtype: str = "f32", registry=None,
                 session_id: str = "generate"):
        if carry_dtype not in ("f32", "int8"):
            raise ValueError(f"unknown carry_dtype {carry_dtype!r}")
        self.spec = spec
        self.device_capacity = int(device_capacity)
        self.host_capacity = int(host_capacity)
        self.store = store
        self.store_prefix = store_prefix
        self.carry_dtype = carry_dtype
        self.session_id = session_id
        self._chaos_save = chaos_site("store.save")
        self._lock = threading.Lock()
        # token -> _Entry; order = LRU (least recent first)
        self._device: "OrderedDict[str, _Entry]" = OrderedDict()
        self._host: "OrderedDict[str, _Entry]" = OrderedDict()
        self.hits: Dict[str, int] = {"device": 0, "host": 0, "store": 0}
        self.misses = 0
        self.evictions = 0
        self.quarantined = 0
        r = registry if registry is not None else default_registry()
        self._c_hits = r.counter(
            "dl4j_gen_session_hits_total",
            "session resumes served, by carry tier (device-pinned rows"
            " | host LRU | shared artifact store)")
        self._c_miss = r.counter(
            "dl4j_gen_session_misses_total",
            "session tokens with no resumable carry in any tier "
            "(fresh sequence started)")
        self._c_evict = r.counter(
            "dl4j_gen_session_evictions_total",
            "session carries pushed down a tier by LRU pressure; "
            "tier=host (device->host) | dropped (host->store-only)")
        self._g_resident = r.gauge(
            "dl4j_gen_session_resident",
            "resumable session carries currently held, by tier")
        for tier in ("device", "host", "store"):
            self._c_hits.inc(0.0, session=session_id, tier=tier)
        self._c_miss.inc(0.0, session=session_id)
        for tier in ("host", "dropped"):
            self._c_evict.inc(0.0, session=session_id, tier=tier)
        self._g_resident.set(0.0, session=session_id, tier="device")
        self._g_resident.set(0.0, session=session_id, tier="host")

    # ---- tiering -----------------------------------------------------

    def _gauges_locked(self):
        self._g_resident.set(float(len(self._device)),  # host-sync-ok: python dict length gauge, no device value
                             session=self.session_id, tier="device")
        self._g_resident.set(float(len(self._host)),  # host-sync-ok: python dict length gauge, no device value
                             session=self.session_id, tier="host")

    def _to_host_entry(self, e: _Entry) -> _Entry:
        """Fetch a device-tier entry's rows to host numpy."""
        e.h = [np.asarray(x) for x in e.h]  # host-sync-ok: LRU demotion of a retired session's carry, off the per-token path
        e.c = [np.asarray(x) for x in e.c]  # host-sync-ok: LRU demotion of a retired session's carry, off the per-token path
        e.tier = "host"
        return e

    def save(self, token: str, snap: CarrySnapshot) -> None:
        """Insert/refresh a resumable carry. Device-pins the rows (LRU
        evicting to the host tier, which LRU-drops in turn) and writes
        through to the artifact store when one is configured — the
        write-through is what makes SIGKILL survivable."""
        self._insert(token, snap, checkpoint=True)

    def _insert(self, token: str, snap: CarrySnapshot,
                checkpoint: bool) -> None:
        import jax
        h, c = snap.h, snap.c
        h_scales = c_scales = None
        if self.carry_dtype == "int8":
            h, h_scales = _quantize_rows(h)
            c, c_scales = _quantize_rows(c)
        if checkpoint and self.store is not None:
            self._checkpoint(token, h, c, h_scales, c_scales, snap)
        e = _Entry([jax.device_put(x) for x in h],
                   [jax.device_put(x) for x in c],
                   h_scales, c_scales,
                   np.asarray(snap.rng, np.uint32),  # host-sync-ok: snapshot rng is host numpy
                   list(snap.pending), int(snap.pos),
                   list(snap.history), "device")
        with self._lock:
            self._device.pop(token, None)
            self._host.pop(token, None)
            self._device[token] = e
            while len(self._device) > self.device_capacity:
                old_tok, old = self._device.popitem(last=False)
                self._host[old_tok] = self._to_host_entry(old)
                self.evictions += 1
                self._c_evict.inc(1.0, session=self.session_id,
                                  tier="host")
            while len(self._host) > self.host_capacity:
                self._host.popitem(last=False)
                self.evictions += 1
                self._c_evict.inc(1.0, session=self.session_id,
                                  tier="dropped")
            self._gauges_locked()

    def load(self, token: str) -> Optional[CarrySnapshot]:
        """Resumable carry for ``token``, or None (miss). Checks tiers
        in device -> host -> store order; a store hit repopulates the
        device tier so the next resume on this node is local."""
        with self._lock:
            e = self._device.pop(token, None)
            if e is not None:
                self._device[token] = e          # refresh LRU position
                self.hits["device"] += 1
                self._c_hits.inc(1.0, session=self.session_id,
                                 tier="device")
                return self._snap_of(e)
            e = self._host.pop(token, None)
            if e is not None:
                self._host[token] = e
                self.hits["host"] += 1
                self._c_hits.inc(1.0, session=self.session_id,
                                 tier="host")
                return self._snap_of(e)
        snap = self._load_checkpoint(token)
        if snap is not None:
            with self._lock:
                self.hits["store"] += 1
                self._c_hits.inc(1.0, session=self.session_id,
                                 tier="store")
            return snap
        with self._lock:
            self.misses += 1
            self._c_miss.inc(1.0, session=self.session_id)
        return None

    def resident(self, token: str) -> Optional[str]:
        """Tier holding ``token`` locally (``"device"``/``"host"``) or
        None — the router's session-affinity signal."""
        with self._lock:
            if token in self._device:
                return "device"
            if token in self._host:
                return "host"
        return None

    def _snap_of(self, e: _Entry) -> CarrySnapshot:
        h, c = e.h, e.c
        if e.tier == "device":
            h = [np.asarray(x) for x in h]  # host-sync-ok: session resume fetch, once per resumed sequence — not the per-token path
            c = [np.asarray(x) for x in c]  # host-sync-ok: session resume fetch, once per resumed sequence — not the per-token path
        if self.carry_dtype == "int8":
            h = _dequantize_rows(h, e.h_scales)
            c = _dequantize_rows(c, e.c_scales)
        else:
            h = [np.asarray(x, np.float32) for x in h]  # host-sync-ok: host-tier rows, already numpy
            c = [np.asarray(x, np.float32) for x in c]  # host-sync-ok: host-tier rows, already numpy
        return CarrySnapshot(h, c, np.asarray(e.rng, np.uint32),  # host-sync-ok: rng row is host numpy
                             list(e.pending), e.pos, list(e.history))

    # ---- artifact-store checkpoint -----------------------------------

    def _dir(self, token: str) -> str:
        return self.store.cache_dir(f"{self.store_prefix}-{token}")

    def _checkpoint(self, token, h, c, h_scales, c_scales,
                    snap: CarrySnapshot) -> None:
        try:
            d = self._dir(token)
            buf = io.BytesIO()
            arrays: Dict[str, np.ndarray] = {
                "rng": np.asarray(snap.rng, np.uint32),  # host-sync-ok: checkpoint serialization, host numpy
                "pending": np.asarray(snap.pending, np.int32),  # host-sync-ok: checkpoint serialization, host list
                "history": np.asarray(snap.history, np.int32),  # host-sync-ok: checkpoint serialization, host list
                "pos": np.asarray([snap.pos], np.int64),  # host-sync-ok: checkpoint serialization, host int
            }
            for i, (hr, cr) in enumerate(zip(h, c)):
                arrays[f"h_{i}"] = np.asarray(hr)  # host-sync-ok: checkpoint serialization, host numpy
                arrays[f"c_{i}"] = np.asarray(cr)  # host-sync-ok: checkpoint serialization, host numpy
            if h_scales is not None:
                arrays["h_scales"] = h_scales
                arrays["c_scales"] = c_scales
            np.savez(buf, **arrays)
            blob = buf.getvalue()
            checksum = hashlib.sha256(blob).hexdigest()
            if self._chaos_save is not None:
                blob, _ = self._chaos_save.mangle(blob, arg="blob")
            with open(os.path.join(d, _CARRY_BLOB), "wb") as f:  # graftlint: disable=atomic-write: blob bytes are sha256-checksummed and only become visible through the manifest's atomic os.replace below; a torn blob quarantines at load
                f.write(blob)
            data = json.dumps({
                "checksum": checksum,
                "carry_dtype": self.carry_dtype,
                "hidden_sizes": list(self.spec.hidden_sizes),
                "pos": int(snap.pos),
            }).encode("utf-8")
            if self._chaos_save is not None:
                data, _ = self._chaos_save.mangle(data, arg="manifest")
            tmp = os.path.join(d, _MANIFEST + ".tmp")
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, os.path.join(d, _MANIFEST))
        except OSError:
            log.exception("session checkpoint failed for %s", token)

    def _load_checkpoint(self, token: str) -> Optional[CarrySnapshot]:
        if self.store is None:
            return None
        try:
            d = self._dir(token)
            with open(os.path.join(d, _MANIFEST)) as f:
                meta = json.load(f)
            blob_path = os.path.join(d, _CARRY_BLOB)
            with open(blob_path, "rb") as f:
                raw = f.read()
        except (OSError, json.JSONDecodeError):
            return None
        want = meta.get("checksum")
        if want is not None and \
                hashlib.sha256(raw).hexdigest() != want:
            # torn or bit-rotted carry: quarantine it and report a miss
            # — a resume must NEVER continue from corrupt state
            self.quarantined += 1
            try:
                os.replace(blob_path, blob_path + ".quarantine")
            except OSError:
                pass
            log.warning("session %s: carry checksum mismatch, "
                        "quarantined", token)
            return None
        if list(meta.get("hidden_sizes", [])) != \
                list(self.spec.hidden_sizes):
            return None                   # foreign model's carry: miss
        try:
            z = np.load(io.BytesIO(raw), allow_pickle=False)
            n = len(self.spec.hidden_sizes)
            h = [z[f"h_{i}"] for i in range(n)]
            c = [z[f"c_{i}"] for i in range(n)]
            if meta.get("carry_dtype") == "int8":
                h = _dequantize_rows(h, z["h_scales"])
                c = _dequantize_rows(c, z["c_scales"])
            else:
                h = [np.asarray(x, np.float32) for x in h]  # host-sync-ok: npz load, host numpy
                c = [np.asarray(x, np.float32) for x in c]  # host-sync-ok: npz load, host numpy
            snap = CarrySnapshot(
                h, c, np.asarray(z["rng"], np.uint32),  # host-sync-ok: npz load, host numpy
                [int(t) for t in z["pending"]],
                int(z["pos"][0]),
                [int(t) for t in z["history"]])
        except Exception:
            log.exception("session %s: unreadable carry blob", token)
            return None
        # repopulate the local tiers (no re-checkpoint: the store copy
        # is already the bytes we just verified) so the next resume on
        # this node skips the store round-trip
        self._insert(token, snap, checkpoint=False)
        return snap

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "carry_dtype": self.carry_dtype,
                "resident": {"device": len(self._device),
                             "host": len(self._host)},
                "capacity": {"device": self.device_capacity,
                             "host": self.host_capacity},
                "hits": dict(self.hits),
                "misses": self.misses,
                "evictions": self.evictions,
                "quarantined": self.quarantined,
                "store": (str(getattr(self.store, "root", None))
                          if self.store is not None else None),
            }
