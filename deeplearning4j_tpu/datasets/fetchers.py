"""Dataset fetchers + canonical iterators.

Analogs of deeplearning4j-data/deeplearning4j-datasets fetchers
(MnistDataFetcher, EmnistDataFetcher, IrisDataFetcher,
TinyImageNetFetcher — SURVEY §2.3) and the iterator impls
(MnistDataSetIterator, IrisDataSetIterator, ...).

Network policy: this environment has zero egress, so fetchers look for
locally cached raw files under ``DL4J_TPU_DATA_DIR`` (default
``~/.deeplearning4j_tpu/data``) and otherwise generate a deterministic
procedural stand-in with the same shapes/dtypes/class structure. The
stand-in makes smoke tests and benchmarks runnable anywhere; real-data
parity only needs the cache directory populated (same contract as the
reference's ``CacheableExtractableDataSetFetcher``).
"""

from __future__ import annotations

import gzip
import os
import struct
import zlib
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import (
    ArrayDataSetIterator,
    DataSet,
    DataSetIterator,
)

DATA_DIR = os.environ.get("DL4J_TPU_DATA_DIR",
                          os.path.expanduser("~/.deeplearning4j_tpu/data"))


def verify_checksum(path: str, expected: int) -> None:
    """Adler32 check of a cached dataset file — the reference's
    CacheableExtractableDataSetFetcher contract (Adler32 over the
    artifact, hard failure on mismatch). Verified once per file; a
    ``<path>.adler32.ok`` stamp (containing the value) skips re-hashing
    unless the file changed size/mtime after stamping."""
    stamp = path + ".adler32.ok"
    sig = f"{expected}:{os.path.getsize(path)}:{os.path.getmtime(path)}"
    if os.path.exists(stamp):
        with open(stamp) as fh:
            if fh.read().strip() == sig:
                return
    a = 1
    with open(path, "rb") as fh:
        while True:
            chunk = fh.read(1 << 20)
            if not chunk:
                break
            a = zlib.adler32(chunk, a)
    if a != expected:
        raise IOError(
            f"Dataset file failed checksum: {path} has adler32 {a}, "
            f"expected {expected}. Delete the file and re-populate the "
            "cache (reference: CacheableExtractableDataSetFetcher).")
    with open(stamp, "w") as fh:
        fh.write(sig)


def _sidecar_checksum(path: str) -> Optional[int]:
    """Expected checksum from a ``<path>.adler32`` sidecar, if present."""
    side = path + ".adler32"
    if os.path.exists(side):
        with open(side) as fh:
            return int(fh.read().strip())
    return None


def _maybe_verify(path: str, expected: Optional[int] = None) -> None:
    expected = expected if expected is not None else _sidecar_checksum(path)
    if expected is not None:
        verify_checksum(path, expected)


def fetch_with_mirror(url: str, dest: str,
                      expected_checksum: Optional[int] = None) -> str:
    """Download-and-verify (reference:
    CacheableExtractableDataSetFetcher.downloadAndExtract). Zero-egress
    environments point ``url`` at a ``file://`` mirror; the checksum
    contract is identical either way. Returns ``dest``."""
    if not os.path.exists(dest):
        import urllib.request
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        tmp = dest + ".part"
        urllib.request.urlretrieve(url, tmp)
        os.replace(tmp, dest)
    try:
        _maybe_verify(dest, expected_checksum)
    except IOError:
        os.unlink(dest)     # reference behavior: failed files are purged
        raise
    return dest


def _one_hot(idx: np.ndarray, n: int) -> np.ndarray:
    out = np.zeros((idx.shape[0], n), np.float32)
    out[np.arange(idx.shape[0]), idx] = 1.0
    return out


def _synthetic_image_classes(num: int, h: int, w: int, c: int, classes: int,
                             seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic class-structured images: each class is a distinct
    frequency/orientation pattern + noise, so models actually learn."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, classes, size=num)
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    images = np.empty((num, h, w, c), np.float32)
    for k in range(classes):
        mask = labels == k
        n_k = int(mask.sum())
        if n_k == 0:
            continue
        fx = 1.0 + (k % 5)
        fy = 1.0 + (k // 5) % 5
        base = np.sin(2 * np.pi * fx * xx / w + k) * \
            np.cos(2 * np.pi * fy * yy / h)
        pattern = np.repeat(base[:, :, None], c, axis=2)
        noise = rng.normal(0, 0.3, size=(n_k, h, w, c)).astype(np.float32)
        images[mask] = pattern[None] + noise
    images = (images - images.min()) / (images.max() - images.min() + 1e-8)
    return images.astype(np.float32), labels


class _ArrayBackedIterator(DataSetIterator):
    """Shared delegation for fetcher-backed iterators: subclasses build a
    DataSet and call ``_wrap``; iteration/reset delegate to one
    ArrayDataSetIterator."""

    def _wrap(self, ds: DataSet, batch_size: int, seed: int,
              shuffle: bool = True):
        self._it = ArrayDataSetIterator(ds, batch_size, shuffle=shuffle,
                                        seed=seed, drop_last=True)

    def __iter__(self):
        return iter(self._it)

    def reset(self):
        self._it.reset()

    @property
    def batch_size(self):
        return self._it.batch_size


class MnistDataFetcher:
    """Reads the canonical IDX-format files if cached locally, else builds
    a synthetic 10-class 28x28 set (reference: MnistDataFetcher)."""

    NUM_TRAIN = 60000
    NUM_TEST = 10000

    def __init__(self, train: bool = True, subset: Optional[int] = None,
                 seed: int = 123):
        self.train = train
        self.subset = subset
        self.seed = seed

    def fetch(self) -> Tuple[np.ndarray, np.ndarray]:
        base = os.path.join(DATA_DIR, "mnist")
        prefix = "train" if self.train else "t10k"
        img_path = os.path.join(base, f"{prefix}-images-idx3-ubyte.gz")
        lbl_path = os.path.join(base, f"{prefix}-labels-idx1-ubyte.gz")
        if os.path.exists(img_path) and os.path.exists(lbl_path):
            images = self._read_idx_images(img_path)
            labels = self._read_idx_labels(lbl_path)
        else:
            n = self.NUM_TRAIN if self.train else self.NUM_TEST
            n = min(n, self.subset or n)
            images4d, labels = _synthetic_image_classes(
                n, 28, 28, 1, 10, self.seed + (0 if self.train else 1))
            images = images4d.reshape(n, 784)
        if self.subset:
            images = images[:self.subset]
            labels = labels[:self.subset]
        return images.astype(np.float32), labels

    @staticmethod
    def _read_idx_images(path: str) -> np.ndarray:
        with gzip.open(path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), np.uint8)
        return data.reshape(n, rows * cols).astype(np.float32) / 255.0

    @staticmethod
    def _read_idx_labels(path: str) -> np.ndarray:
        with gzip.open(path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), np.uint8).astype(np.int64)


def write_idx_gz(images: np.ndarray, labels: np.ndarray, directory: str,
                 prefix: str) -> None:
    """Write (N, H, W) uint8 images + (N,) labels as canonical gzipped
    IDX files (``{prefix}-images-idx3-ubyte.gz`` etc.) — the exact byte
    format of the MNIST distribution. Lets a user (or test) populate the
    ``DL4J_TPU_DATA_DIR`` cache so fetchers take the real-file path; the
    reference's MnistFetcher downloads these same files
    (deeplearning4j-data/.../MnistDataFetcher.java:1)."""
    images = np.asarray(images, np.uint8)  # host-sync-ok: host-side data decode/build pre-transfer
    labels = np.asarray(labels, np.uint8)  # host-sync-ok: host-side data decode/build pre-transfer
    n, rows, cols = images.shape
    os.makedirs(directory, exist_ok=True)
    with gzip.open(os.path.join(
            directory, f"{prefix}-images-idx3-ubyte.gz"), "wb") as f:
        f.write(struct.pack(">IIII", 0x803, n, rows, cols))
        f.write(images.tobytes())
    with gzip.open(os.path.join(
            directory, f"{prefix}-labels-idx1-ubyte.gz"), "wb") as f:
        f.write(struct.pack(">II", 0x801, n))
        f.write(labels.tobytes())


class DigitsDataSetIterator(_ArrayBackedIterator):
    """REAL handwritten-digit data that ships inside scikit-learn (the
    UCI optical-recognition test corpus: 1797 genuine 8x8 grayscale
    digit scans). The in-image real-data correctness benchmark for
    zero-egress environments where canonical MNIST cannot be fetched:
    images are upscaled to 28x28 (3x nearest + 2px border) so LeNet-class
    models run unchanged, with a deterministic 80/20 train/test split.
    """

    IMG = 28

    def __init__(self, batch_size: int, train: bool = True, seed: int = 123,
                 shuffle: bool = True):
        images, labels = self.fetch(train)
        ds = DataSet(images, _one_hot(labels, 10))
        self._wrap(ds, batch_size, seed, shuffle=shuffle)

    @classmethod
    def fetch(cls, train: bool) -> Tuple[np.ndarray, np.ndarray]:
        from sklearn.datasets import load_digits
        digits = load_digits()
        images = digits.images.astype(np.float32) / 16.0   # (1797, 8, 8)
        labels = digits.target.astype(np.int64)
        # 8x8 -> 24x24 nearest-neighbour, then 2px zero border -> 28x28
        up = np.repeat(np.repeat(images, 3, axis=1), 3, axis=2)
        up = np.pad(up, ((0, 0), (2, 2), (2, 2)))
        # deterministic interleaved split: every 5th example is test
        test = np.arange(up.shape[0]) % 5 == 0
        sel = ~test if train else test
        return up[sel].reshape(-1, cls.IMG * cls.IMG), labels[sel]


class MnistDataSetIterator(_ArrayBackedIterator):
    """(reference: MnistDataSetIterator) — yields flattened 784-float
    features + one-hot 10 labels."""

    def __init__(self, batch_size: int, train: bool = True,
                 subset: Optional[int] = None, seed: int = 123,
                 shuffle: bool = True):
        images, labels = MnistDataFetcher(train, subset, seed).fetch()
        ds = DataSet(images, _one_hot(labels, 10))
        self._it = ArrayDataSetIterator(ds, batch_size, shuffle=shuffle,
                                        seed=seed, drop_last=True)



class IrisDataSetIterator(_ArrayBackedIterator):
    """(reference: IrisDataSetIterator) — the classic 150x4 set, generated
    deterministically from the published means/stds when no cache exists."""

    def __init__(self, batch_size: int = 150, seed: int = 6):
        rng = np.random.default_rng(seed)
        means = np.array([[5.0, 3.4, 1.5, 0.2],
                          [5.9, 2.8, 4.3, 1.3],
                          [6.6, 3.0, 5.6, 2.0]], np.float32)
        stds = np.array([[0.35, 0.38, 0.17, 0.10],
                         [0.52, 0.31, 0.47, 0.20],
                         [0.64, 0.32, 0.55, 0.27]], np.float32)
        feats, labels = [], []
        for k in range(3):
            feats.append(rng.normal(means[k], stds[k], size=(50, 4)))
            labels.append(np.full(50, k))
        x = np.concatenate(feats).astype(np.float32)
        y = np.concatenate(labels)
        perm = rng.permutation(150)
        ds = DataSet(x[perm], _one_hot(y[perm], 3))
        self._it = ArrayDataSetIterator(ds, batch_size)



class TinyImageNetFetcher:
    """64x64x3, 200 classes (reference: TinyImageNetFetcher). Parses the
    CANONICAL distribution layout — ``tiny-imagenet-200/`` with
    ``wnids.txt``, ``train/<wnid>/images/*.JPEG`` and
    ``val/images`` + ``val_annotations.txt`` — decoding JPEGs via PIL
    (the reference decodes through datavec-image's native loaders).
    Falls back to a preprocessed ``train.npz`` cache, else synthetic."""

    H, W, C, CLASSES = 64, 64, 3, 200

    def __init__(self, subset: int = 10000, seed: int = 7,
                 train: bool = True):
        self.subset = subset
        self.seed = seed
        self.train = train

    def _decode(self, path: str) -> np.ndarray:
        from PIL import Image
        with Image.open(path) as im:
            a = np.asarray(im.convert("RGB"), np.uint8)  # host-sync-ok: host-side data decode/build pre-transfer
        if a.shape[:2] != (self.H, self.W):   # canonical files are 64x64
            from PIL import Image as I
            with I.open(path) as im:
                a = np.asarray(im.convert("RGB").resize((self.W, self.H)),  # host-sync-ok: host-side data decode/build pre-transfer
                               np.uint8)
        return a

    def _fetch_canonical(self, root: str) -> Tuple[np.ndarray, np.ndarray]:
        with open(os.path.join(root, "wnids.txt")) as fh:
            wnids = [w.strip() for w in fh if w.strip()]
        cls = {w: i for i, w in enumerate(wnids)}
        images, labels = [], []
        if self.train:
            # round-robin over classes so a subset stays class-balanced
            per_cls = [[] for _ in wnids]
            for w in wnids:
                d = os.path.join(root, "train", w, "images")
                if os.path.isdir(d):
                    per_cls[cls[w]] = sorted(os.listdir(d))
            i = 0
            while len(images) < self.subset:
                added = False
                for w in wnids:
                    files = per_cls[cls[w]]
                    if i < len(files):
                        images.append(self._decode(os.path.join(
                            root, "train", w, "images", files[i])))
                        labels.append(cls[w])
                        added = True
                        if len(images) >= self.subset:
                            break
                if not added:
                    break
                i += 1
        else:
            ann = os.path.join(root, "val", "val_annotations.txt")
            with open(ann) as fh:
                for line in fh:
                    parts = line.split("\t")
                    if len(parts) < 2:
                        continue
                    fname, wnid = parts[0], parts[1]
                    images.append(self._decode(os.path.join(
                        root, "val", "images", fname)))
                    labels.append(cls[wnid])
                    if len(images) >= self.subset:
                        break
        x = np.stack(images).astype(np.float32) / 255.0
        return x, np.asarray(labels, np.int64)  # host-sync-ok: host-side data decode/build pre-transfer

    def fetch(self) -> Tuple[np.ndarray, np.ndarray]:
        base = os.path.join(DATA_DIR, "tinyimagenet")
        if self.train:
            # preprocessed cache stays the fast path when present
            legacy = os.path.join(base, "train.npz")
            if os.path.exists(legacy):
                _maybe_verify(legacy)
                z = np.load(legacy)
                return (z["images"][:self.subset],
                        z["labels"][:self.subset])
        root = os.path.join(base, "tiny-imagenet-200")
        if os.path.isdir(root):
            split = "train" if self.train else "val"
            # write-through decode cache: ~10k PIL decodes per call
            # otherwise
            cache = os.path.join(base,
                                 f"decoded_{split}_{self.subset}.npz")
            if os.path.exists(cache):
                z = np.load(cache)
                return z["images"], z["labels"]
            images, labels = self._fetch_canonical(root)
            try:
                np.savez_compressed(cache, images=images, labels=labels)
            except OSError:
                pass                      # read-only cache dir: skip
            return images, labels
        return _synthetic_image_classes(self.subset, self.H, self.W, self.C,
                                        self.CLASSES, self.seed)


class TinyImageNetDataSetIterator(_ArrayBackedIterator):
    def __init__(self, batch_size: int, subset: int = 10000, seed: int = 7,
                 num_classes: Optional[int] = None):
        images, labels = TinyImageNetFetcher(subset, seed).fetch()
        n_cls = num_classes or TinyImageNetFetcher.CLASSES
        labels = labels % n_cls
        ds = DataSet(images, _one_hot(labels, n_cls))
        self._wrap(ds, batch_size, seed)



class EmnistDataSetIterator(_ArrayBackedIterator):
    """(reference: EmnistDataSetIterator + EmnistDataFetcher) — MNIST-format
    IDX files per EMNIST split; synthetic fallback with the split's class
    count. Splits mirror EmnistDataSetIterator.Set."""

    SETS = {"COMPLETE": 62, "MERGE": 47, "BALANCED": 47, "LETTERS": 26,
            "DIGITS": 10, "MNIST": 10}

    def __init__(self, batch_size: int, dataset: str = "BALANCED",
                 train: bool = True, subset: Optional[int] = None,
                 seed: int = 123):
        dataset = dataset.upper()
        if dataset not in self.SETS:
            raise ValueError(f"unknown EMNIST split {dataset!r}; "
                             f"one of {sorted(self.SETS)}")
        n_cls = self.SETS[dataset]
        base = os.path.join(DATA_DIR, "emnist")
        prefix = f"emnist-{dataset.lower()}-" + ("train" if train else "test")
        img_path = os.path.join(base, f"{prefix}-images-idx3-ubyte.gz")
        lbl_path = os.path.join(base, f"{prefix}-labels-idx1-ubyte.gz")
        if os.path.exists(img_path) and os.path.exists(lbl_path):
            images = MnistDataFetcher._read_idx_images(img_path)
            labels = MnistDataFetcher._read_idx_labels(lbl_path)
            if dataset == "LETTERS":
                labels = labels - 1  # EMNIST letters are 1-indexed (a=1)
            labels = labels % n_cls
            if subset:
                images, labels = images[:subset], labels[:subset]
        else:
            n = min(subset or 10000, 10000)
            images4d, labels = _synthetic_image_classes(
                n, 28, 28, 1, n_cls, seed + (0 if train else 1))
            images = images4d.reshape(n, 784)
        ds = DataSet(images.astype(np.float32), _one_hot(labels, n_cls))
        self.num_classes = n_cls
        self._wrap(ds, batch_size, seed)



class SvhnDataFetcher:
    """32x32x3 street-view house numbers, 10 classes (reference:
    SvhnDataFetcher, which also publishes Adler32 checksums for its
    artifacts — the same contract ``verify_checksum`` implements here).

    Reads the CANONICAL cropped-digits distribution
    ``svhn/{train,test}_32x32.mat`` (MATLAB v7: ``X`` (32,32,3,N) uint8,
    ``y`` (N,1) with 10 meaning digit 0) via scipy's libmat reader; a
    preprocessed ``.npz`` is accepted for back-compat; synthetic
    fallback otherwise. A ``<file>.adler32`` sidecar in the cache dir
    triggers checksum verification."""

    H = W = 32
    C = 3
    CLASSES = 10

    def __init__(self, train: bool = True, subset: Optional[int] = None,
                 seed: int = 11,
                 expected_checksum: Optional[int] = None):
        self.train = train
        self.subset = subset
        self.seed = seed
        self.expected_checksum = expected_checksum

    def fetch(self) -> Tuple[np.ndarray, np.ndarray]:
        split = "train" if self.train else "test"
        mat = os.path.join(DATA_DIR, "svhn", f"{split}_32x32.mat")
        if os.path.exists(mat):
            _maybe_verify(mat, self.expected_checksum)
            from scipy.io import loadmat
            z = loadmat(mat)
            # (32, 32, 3, N) → NHWC; label "10" is the digit 0
            images = np.transpose(z["X"], (3, 0, 1, 2)) \
                .astype(np.float32) / 255.0
            labels = z["y"].reshape(-1).astype(np.int64) % self.CLASSES
            if self.subset:
                images, labels = images[:self.subset], labels[:self.subset]
            return images, labels
        path = os.path.join(DATA_DIR, "svhn", f"{split}_32x32.npz")
        if os.path.exists(path):
            _maybe_verify(path, self.expected_checksum)
            with np.load(path) as z:
                images = z["X"].astype(np.float32) / 255.0
                labels = z["y"].astype(np.int64) % self.CLASSES
            if self.subset:
                images, labels = images[:self.subset], labels[:self.subset]
            return images, labels
        n = min(self.subset or 5000, 5000)
        return _synthetic_image_classes(
            n, self.H, self.W, self.C, self.CLASSES,
            self.seed + (0 if self.train else 1))


class SvhnDataSetIterator(_ArrayBackedIterator):
    def __init__(self, batch_size: int, train: bool = True,
                 subset: Optional[int] = None, seed: int = 11):
        images, labels = SvhnDataFetcher(train, subset, seed).fetch()
        ds = DataSet(images, _one_hot(labels, SvhnDataFetcher.CLASSES))
        self._wrap(ds, batch_size, seed)



class CifarDataSetIterator(_ArrayBackedIterator):
    """32x32x3, 10 classes (reference: CifarDataSetIterator). Reads the
    canonical ``cifar-10-batches-bin`` layout if cached, else synthetic."""

    H = W = 32
    C = 3
    CLASSES = 10

    def __init__(self, batch_size: int, train: bool = True,
                 subset: Optional[int] = None, seed: int = 17):
        base = os.path.join(DATA_DIR, "cifar-10-batches-bin")
        files = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
                 else ["test_batch.bin"])
        paths = [os.path.join(base, f) for f in files]
        if all(os.path.exists(p) for p in paths):
            images, labels = self._read_bin(paths)
            if subset:
                images, labels = images[:subset], labels[:subset]
        else:
            n = min(subset or 5000, 5000)
            images, labels = _synthetic_image_classes(
                n, self.H, self.W, self.C, self.CLASSES,
                seed + (0 if train else 1))
        ds = DataSet(images, _one_hot(labels, self.CLASSES))
        self._wrap(ds, batch_size, seed)

    @classmethod
    def _read_bin(cls, paths) -> Tuple[np.ndarray, np.ndarray]:
        rec = 1 + 3072
        imgs, lbls = [], []
        for p in paths:
            raw = np.fromfile(p, np.uint8).reshape(-1, rec)
            lbls.append(raw[:, 0].astype(np.int64))
            chw = raw[:, 1:].reshape(-1, 3, 32, 32)
            imgs.append(chw.transpose(0, 2, 3, 1).astype(np.float32) / 255.0)
        return np.concatenate(imgs), np.concatenate(lbls)


def write_cifar_bin(images: np.ndarray, labels: np.ndarray,
                    path: str) -> None:
    """Write (N, 32, 32, 3) uint8 NHWC images + (N,) labels in the
    canonical ``cifar-10-batches-bin`` record format (label byte + 3072
    CHW bytes) — lets tests/users populate the cache so the real-file
    path is exercised byte-for-byte (same contract as write_idx_gz)."""
    images = np.asarray(images, np.uint8)  # host-sync-ok: host-side data decode/build pre-transfer
    labels = np.asarray(labels, np.uint8)  # host-sync-ok: host-side data decode/build pre-transfer
    n = images.shape[0]
    chw = images.transpose(0, 3, 1, 2).reshape(n, 3072)
    rec = np.concatenate([labels[:, None], chw], axis=1)
    d = os.path.dirname(path)
    if d:                      # bare filename → cwd, no mkdir needed
        os.makedirs(d, exist_ok=True)
    rec.tofile(path)



class LFWDataSetIterator(_ArrayBackedIterator):
    """Labeled-faces-in-the-wild (reference: LFWDataSetIterator). The
    reference decodes JPEGs via DataVec's image reader; here a cached
    ``lfw/lfw.npz`` (``X`` float images NHWC, ``y`` int labels) is used,
    else a synthetic multi-class face-shaped set."""

    def __init__(self, batch_size: int, num_examples: int = 1000,
                 image_shape: Tuple[int, int, int] = (64, 64, 3),
                 num_labels: int = 40, train: bool = True, seed: int = 42):
        h, w, c = image_shape
        path = os.path.join(DATA_DIR, "lfw", "lfw.npz")
        if os.path.exists(path):
            with np.load(path) as z:
                images = z["X"].astype(np.float32)
                labels = z["y"].astype(np.int64) % num_labels
            images, labels = images[:num_examples], labels[:num_examples]
        else:
            images, labels = _synthetic_image_classes(
                min(num_examples, 2000), h, w, c, num_labels,
                seed + (0 if train else 1))
        self.num_labels = num_labels
        ds = DataSet(images, _one_hot(labels, num_labels))
        self._wrap(ds, batch_size, seed)



class UciSequenceDataSetIterator(_ArrayBackedIterator):
    """UCI synthetic-control time series: 600 univariate length-60 series,
    6 classes (reference: UciSequenceDataFetcher/-Iterator). Reads cached
    ``uci/synthetic_control.data`` (600x60 whitespace floats, class = row
    block of 100), else generates the same six regimes procedurally."""

    CLASSES = 6
    LENGTH = 60

    def __init__(self, batch_size: int, train: bool = True, seed: int = 23):
        path = os.path.join(DATA_DIR, "uci", "synthetic_control.data")
        if os.path.exists(path):
            series = np.loadtxt(path).astype(np.float32)
            labels = np.repeat(np.arange(6), 100)
        else:
            series, labels = self._synthesize(seed)
        # reference split: alternating 450 train / 150 test after shuffle
        rng = np.random.default_rng(seed)
        order = rng.permutation(series.shape[0])
        cut = int(0.75 * len(order))
        keep = order[:cut] if train else order[cut:]
        series, labels = series[keep], labels[keep]
        # normalize per-series, shape (N, T, 1)
        mu = series.mean(axis=1, keepdims=True)
        sd = series.std(axis=1, keepdims=True) + 1e-6
        feats = ((series - mu) / sd)[:, :, None].astype(np.float32)
        # sequence labels: one-hot at every step (RnnOutputLayer format)
        lab = np.repeat(_one_hot(labels, self.CLASSES)[:, None, :],
                        self.LENGTH, axis=1)
        ds = DataSet(feats, lab)
        self._wrap(ds, batch_size, seed)

    @classmethod
    def _synthesize(cls, seed: int) -> Tuple[np.ndarray, np.ndarray]:
        rng = np.random.default_rng(seed)
        t = np.arange(cls.LENGTH, dtype=np.float32)
        rows, labels = [], []
        for k in range(cls.CLASSES):
            for _ in range(100):
                base = 30 + rng.normal(0, 2, cls.LENGTH)
                if k == 1:    # cyclic
                    base += 15 * np.sin(2 * np.pi * t / rng.uniform(10, 15))
                elif k == 2:  # increasing trend
                    base += rng.uniform(0.2, 0.5) * t
                elif k == 3:  # decreasing trend
                    base -= rng.uniform(0.2, 0.5) * t
                elif k == 4:  # upward shift
                    base[cls.LENGTH // 2:] += rng.uniform(7.5, 20)
                elif k == 5:  # downward shift
                    base[cls.LENGTH // 2:] -= rng.uniform(7.5, 20)
                rows.append(base)
                labels.append(k)
        return (np.asarray(rows, np.float32),  # host-sync-ok: host-side data decode/build pre-transfer
                np.asarray(labels, np.int64))  # host-sync-ok: host-side data decode/build pre-transfer

