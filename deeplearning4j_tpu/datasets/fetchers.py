"""Dataset fetchers + canonical iterators.

Analogs of deeplearning4j-data/deeplearning4j-datasets fetchers
(MnistDataFetcher, EmnistDataFetcher, IrisDataFetcher,
TinyImageNetFetcher — SURVEY §2.3) and the iterator impls
(MnistDataSetIterator, IrisDataSetIterator, ...).

Network policy: this environment has zero egress, so fetchers look for
locally cached raw files under ``DL4J_TPU_DATA_DIR`` (default
``~/.deeplearning4j_tpu/data``) and otherwise generate a deterministic
procedural stand-in with the same shapes/dtypes/class structure. The
stand-in makes smoke tests and benchmarks runnable anywhere; real-data
parity only needs the cache directory populated (same contract as the
reference's ``CacheableExtractableDataSetFetcher``).
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import (
    ArrayDataSetIterator,
    DataSet,
    DataSetIterator,
)

DATA_DIR = os.environ.get("DL4J_TPU_DATA_DIR",
                          os.path.expanduser("~/.deeplearning4j_tpu/data"))


def _one_hot(idx: np.ndarray, n: int) -> np.ndarray:
    out = np.zeros((idx.shape[0], n), np.float32)
    out[np.arange(idx.shape[0]), idx] = 1.0
    return out


def _synthetic_image_classes(num: int, h: int, w: int, c: int, classes: int,
                             seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic class-structured images: each class is a distinct
    frequency/orientation pattern + noise, so models actually learn."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, classes, size=num)
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    images = np.empty((num, h, w, c), np.float32)
    for k in range(classes):
        mask = labels == k
        n_k = int(mask.sum())
        if n_k == 0:
            continue
        fx = 1.0 + (k % 5)
        fy = 1.0 + (k // 5) % 5
        base = np.sin(2 * np.pi * fx * xx / w + k) * \
            np.cos(2 * np.pi * fy * yy / h)
        pattern = np.repeat(base[:, :, None], c, axis=2)
        noise = rng.normal(0, 0.3, size=(n_k, h, w, c)).astype(np.float32)
        images[mask] = pattern[None] + noise
    images = (images - images.min()) / (images.max() - images.min() + 1e-8)
    return images.astype(np.float32), labels


class MnistDataFetcher:
    """Reads the canonical IDX-format files if cached locally, else builds
    a synthetic 10-class 28x28 set (reference: MnistDataFetcher)."""

    NUM_TRAIN = 60000
    NUM_TEST = 10000

    def __init__(self, train: bool = True, subset: Optional[int] = None,
                 seed: int = 123):
        self.train = train
        self.subset = subset
        self.seed = seed

    def fetch(self) -> Tuple[np.ndarray, np.ndarray]:
        base = os.path.join(DATA_DIR, "mnist")
        prefix = "train" if self.train else "t10k"
        img_path = os.path.join(base, f"{prefix}-images-idx3-ubyte.gz")
        lbl_path = os.path.join(base, f"{prefix}-labels-idx1-ubyte.gz")
        if os.path.exists(img_path) and os.path.exists(lbl_path):
            images = self._read_idx_images(img_path)
            labels = self._read_idx_labels(lbl_path)
        else:
            n = self.NUM_TRAIN if self.train else self.NUM_TEST
            n = min(n, self.subset or n)
            images4d, labels = _synthetic_image_classes(
                n, 28, 28, 1, 10, self.seed + (0 if self.train else 1))
            images = images4d.reshape(n, 784)
        if self.subset:
            images = images[:self.subset]
            labels = labels[:self.subset]
        return images.astype(np.float32), labels

    @staticmethod
    def _read_idx_images(path: str) -> np.ndarray:
        with gzip.open(path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), np.uint8)
        return data.reshape(n, rows * cols).astype(np.float32) / 255.0

    @staticmethod
    def _read_idx_labels(path: str) -> np.ndarray:
        with gzip.open(path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), np.uint8).astype(np.int64)


class MnistDataSetIterator(DataSetIterator):
    """(reference: MnistDataSetIterator) — yields flattened 784-float
    features + one-hot 10 labels."""

    def __init__(self, batch_size: int, train: bool = True,
                 subset: Optional[int] = None, seed: int = 123,
                 shuffle: bool = True):
        images, labels = MnistDataFetcher(train, subset, seed).fetch()
        ds = DataSet(images, _one_hot(labels, 10))
        self._it = ArrayDataSetIterator(ds, batch_size, shuffle=shuffle,
                                        seed=seed, drop_last=True)

    def __iter__(self):
        return iter(self._it)

    def reset(self):
        self._it.reset()

    @property
    def batch_size(self):
        return self._it.batch_size


class IrisDataSetIterator(DataSetIterator):
    """(reference: IrisDataSetIterator) — the classic 150x4 set, generated
    deterministically from the published means/stds when no cache exists."""

    def __init__(self, batch_size: int = 150, seed: int = 6):
        rng = np.random.default_rng(seed)
        means = np.array([[5.0, 3.4, 1.5, 0.2],
                          [5.9, 2.8, 4.3, 1.3],
                          [6.6, 3.0, 5.6, 2.0]], np.float32)
        stds = np.array([[0.35, 0.38, 0.17, 0.10],
                         [0.52, 0.31, 0.47, 0.20],
                         [0.64, 0.32, 0.55, 0.27]], np.float32)
        feats, labels = [], []
        for k in range(3):
            feats.append(rng.normal(means[k], stds[k], size=(50, 4)))
            labels.append(np.full(50, k))
        x = np.concatenate(feats).astype(np.float32)
        y = np.concatenate(labels)
        perm = rng.permutation(150)
        ds = DataSet(x[perm], _one_hot(y[perm], 3))
        self._it = ArrayDataSetIterator(ds, batch_size)

    def __iter__(self):
        return iter(self._it)

    def reset(self):
        self._it.reset()

    @property
    def batch_size(self):
        return self._it.batch_size


class TinyImageNetFetcher:
    """64x64x3, 200 classes (reference: TinyImageNetFetcher). Synthetic
    fallback mirrors shapes/classes for benchmarks."""

    H, W, C, CLASSES = 64, 64, 3, 200

    def __init__(self, subset: int = 10000, seed: int = 7):
        self.subset = subset
        self.seed = seed

    def fetch(self) -> Tuple[np.ndarray, np.ndarray]:
        cache = os.path.join(DATA_DIR, "tinyimagenet", "train.npz")
        if os.path.exists(cache):
            z = np.load(cache)
            return z["images"][:self.subset], z["labels"][:self.subset]
        return _synthetic_image_classes(self.subset, self.H, self.W, self.C,
                                        self.CLASSES, self.seed)


class TinyImageNetDataSetIterator(DataSetIterator):
    def __init__(self, batch_size: int, subset: int = 10000, seed: int = 7,
                 num_classes: Optional[int] = None):
        images, labels = TinyImageNetFetcher(subset, seed).fetch()
        n_cls = num_classes or TinyImageNetFetcher.CLASSES
        labels = labels % n_cls
        ds = DataSet(images, _one_hot(labels, n_cls))
        self._it = ArrayDataSetIterator(ds, batch_size, shuffle=True,
                                        seed=seed, drop_last=True)

    def __iter__(self):
        return iter(self._it)

    def reset(self):
        self._it.reset()

    @property
    def batch_size(self):
        return self._it.batch_size
