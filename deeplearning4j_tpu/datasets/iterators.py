"""Utility dataset iterators.

Analogs of deeplearning4j-data/deeplearning4j-utility-iterators
(SURVEY §2.3): AsyncDataSetIterator (background prefetch),
MultipleEpochsIterator, EarlyTerminationDataSetIterator,
DataSetIteratorSplitter, AsyncShieldDataSetIterator.

The async prefetcher is the ETL/compute overlap mechanism: a host thread
prepares the next minibatches while the TPU executes the current step
(reference: AsyncDataSetIterator wraps fit's iterator at
MultiLayerNetwork.java:1273). Combined with the jitted step's async
dispatch, this keeps the device fed without an explicit infeed queue.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional

from deeplearning4j_tpu.datasets.dataset import DataSet, DataSetIterator


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch with a bounded queue (reference:
    AsyncDataSetIterator, default queue size 8)."""

    _SENTINEL = object()

    def __init__(self, base: DataSetIterator, queue_size: int = 8):
        self.base = base
        self.queue_size = queue_size
        self._worker: Optional[threading.Thread] = None
        self._stop: Optional[threading.Event] = None
        self._q: Optional[queue.Queue] = None

    def __iter__(self) -> Iterator[DataSet]:
        # one pass at a time: an unfinished previous pass (early break)
        # must not keep filling the queue we are about to read
        self._shutdown_worker()
        q: queue.Queue = queue.Queue(maxsize=self.queue_size)
        stop = threading.Event()
        error = []

        def worker():
            try:
                for batch in self.base:
                    while not stop.is_set():
                        try:
                            q.put(batch, timeout=0.05)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:  # propagate to consumer
                error.append(e)
            finally:
                while not stop.is_set():
                    try:
                        q.put(self._SENTINEL, timeout=0.05)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=worker, daemon=True)
        self._worker, self._stop, self._q = t, stop, q
        t.start()
        finished = False
        try:
            while True:
                item = q.get()
                if item is self._SENTINEL:
                    finished = True
                    break
                yield item
        finally:
            if finished:
                t.join()
            else:
                # consumer abandoned the pass (break / exception / GC):
                # stop and reap the worker instead of leaving it blocked
                # on a full queue forever
                self._reap(t, stop, q)
            if self._worker is t:
                self._worker = self._stop = self._q = None
        if error:
            raise error[0]

    @staticmethod
    def _reap(t: threading.Thread, stop: threading.Event, q: queue.Queue):
        stop.set()
        while t.is_alive():
            try:          # drain so a put-blocked worker sees the stop
                q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=0.05)

    def _shutdown_worker(self):
        t, stop, q = self._worker, self._stop, self._q
        self._worker = self._stop = self._q = None
        if t is None or not t.is_alive():
            return
        self._reap(t, stop, q)

    def reset(self):
        # stop → drain → JOIN, and only then reset the base: resetting
        # first would let the still-running worker interleave stale
        # batches from the old pass (or race a non-reentrant base) into
        # the next one
        self._shutdown_worker()
        self.base.reset()

    @property
    def batch_size(self):
        return self.base.batch_size


class AsyncShieldDataSetIterator(DataSetIterator):
    """Marks an iterator as not-async-safe (reference:
    AsyncShieldDataSetIterator) — fit() will not wrap it."""

    def __init__(self, base: DataSetIterator):
        self.base = base

    def __iter__(self):
        return iter(self.base)

    def reset(self):
        self.base.reset()

    @property
    def async_supported(self):
        return False

    @property
    def batch_size(self):
        return self.base.batch_size


class MultipleEpochsIterator(DataSetIterator):
    """Replays the base iterator N times as one pass (reference:
    MultipleEpochsIterator)."""

    def __init__(self, base: DataSetIterator, epochs: int):
        self.base = base
        self.epochs = epochs

    def __iter__(self):
        for e in range(self.epochs):
            for batch in self.base:
                yield batch
            self.base.reset()

    def reset(self):
        self.base.reset()

    @property
    def batch_size(self):
        return self.base.batch_size


class EarlyTerminationDataSetIterator(DataSetIterator):
    """Caps the number of minibatches per pass (reference:
    EarlyTerminationDataSetIterator)."""

    def __init__(self, base: DataSetIterator, max_batches: int):
        self.base = base
        self.max_batches = max_batches

    def __iter__(self):
        for i, batch in enumerate(self.base):
            if i >= self.max_batches:
                break
            yield batch

    def reset(self):
        self.base.reset()

    @property
    def batch_size(self):
        return self.base.batch_size


class DataSetIteratorSplitter:
    """Splits one iterator into train/test partitions by batch count
    (reference: DataSetIteratorSplitter)."""

    def __init__(self, base: DataSetIterator, total_batches: int,
                 ratio: float):
        self.base = base
        self.n_train = int(total_batches * ratio)
        self.total = total_batches

    @property
    def train_iterator(self) -> DataSetIterator:
        return _SplitView(self.base, 0, self.n_train)

    @property
    def test_iterator(self) -> DataSetIterator:
        return _SplitView(self.base, self.n_train, self.total)


class _SplitView(DataSetIterator):
    def __init__(self, base, lo, hi):
        self.base, self.lo, self.hi = base, lo, hi

    def __iter__(self):
        for i, batch in enumerate(self.base):
            if i >= self.hi:
                break
            if i >= self.lo:
                yield batch
        self.base.reset()

    def reset(self):
        self.base.reset()

    @property
    def batch_size(self):
        return self.base.batch_size
