"""Utility dataset iterators.

Analogs of deeplearning4j-data/deeplearning4j-utility-iterators
(SURVEY §2.3): AsyncDataSetIterator (background prefetch),
MultipleEpochsIterator, EarlyTerminationDataSetIterator,
DataSetIteratorSplitter, AsyncShieldDataSetIterator.

The async prefetcher is the ETL/compute overlap mechanism: a host thread
prepares the next minibatches while the TPU executes the current step
(reference: AsyncDataSetIterator wraps fit's iterator at
MultiLayerNetwork.java:1273). Combined with the jitted step's async
dispatch, this keeps the device fed without an explicit infeed queue.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional

from deeplearning4j_tpu.datasets.dataset import DataSet, DataSetIterator


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch with a bounded queue (reference:
    AsyncDataSetIterator, default queue size 8)."""

    _SENTINEL = object()

    def __init__(self, base: DataSetIterator, queue_size: int = 8):
        self.base = base
        self.queue_size = queue_size

    def __iter__(self) -> Iterator[DataSet]:
        q: queue.Queue = queue.Queue(maxsize=self.queue_size)
        error = []

        def worker():
            try:
                for batch in self.base:
                    q.put(batch)
            except BaseException as e:  # propagate to consumer
                error.append(e)
            finally:
                q.put(self._SENTINEL)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is self._SENTINEL:
                break
            yield item
        t.join()
        if error:
            raise error[0]

    def reset(self):
        self.base.reset()

    @property
    def batch_size(self):
        return self.base.batch_size


class AsyncShieldDataSetIterator(DataSetIterator):
    """Marks an iterator as not-async-safe (reference:
    AsyncShieldDataSetIterator) — fit() will not wrap it."""

    def __init__(self, base: DataSetIterator):
        self.base = base

    def __iter__(self):
        return iter(self.base)

    def reset(self):
        self.base.reset()

    @property
    def async_supported(self):
        return False

    @property
    def batch_size(self):
        return self.base.batch_size


class MultipleEpochsIterator(DataSetIterator):
    """Replays the base iterator N times as one pass (reference:
    MultipleEpochsIterator)."""

    def __init__(self, base: DataSetIterator, epochs: int):
        self.base = base
        self.epochs = epochs

    def __iter__(self):
        for e in range(self.epochs):
            for batch in self.base:
                yield batch
            self.base.reset()

    def reset(self):
        self.base.reset()

    @property
    def batch_size(self):
        return self.base.batch_size


class EarlyTerminationDataSetIterator(DataSetIterator):
    """Caps the number of minibatches per pass (reference:
    EarlyTerminationDataSetIterator)."""

    def __init__(self, base: DataSetIterator, max_batches: int):
        self.base = base
        self.max_batches = max_batches

    def __iter__(self):
        for i, batch in enumerate(self.base):
            if i >= self.max_batches:
                break
            yield batch

    def reset(self):
        self.base.reset()

    @property
    def batch_size(self):
        return self.base.batch_size


class DataSetIteratorSplitter:
    """Splits one iterator into train/test partitions by batch count
    (reference: DataSetIteratorSplitter)."""

    def __init__(self, base: DataSetIterator, total_batches: int,
                 ratio: float):
        self.base = base
        self.n_train = int(total_batches * ratio)
        self.total = total_batches

    @property
    def train_iterator(self) -> DataSetIterator:
        return _SplitView(self.base, 0, self.n_train)

    @property
    def test_iterator(self) -> DataSetIterator:
        return _SplitView(self.base, self.n_train, self.total)


class _SplitView(DataSetIterator):
    def __init__(self, base, lo, hi):
        self.base, self.lo, self.hi = base, lo, hi

    def __iter__(self):
        for i, batch in enumerate(self.base):
            if i >= self.hi:
                break
            if i >= self.lo:
                yield batch
        self.base.reset()

    def reset(self):
        self.base.reset()

    @property
    def batch_size(self):
        return self.base.batch_size
