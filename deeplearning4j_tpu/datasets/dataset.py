"""DataSet and iterator protocol.

Analog of ND4J's ``DataSet``/``MultiDataSet`` and the reference's
``DataSetIterator`` contract (consumed by MultiLayerNetwork.fit at
deeplearning4j-nn/.../nn/multilayer/MultiLayerNetwork.java:1268).

A DataSet is a minibatch: features, labels, optional masks. Arrays are host
numpy until they hit the jitted train step — the async prefetch iterator
(datasets/iterators.py) overlaps host ETL with device compute, the analog of
the reference's AsyncDataSetIterator thread.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np


@dataclasses.dataclass
class DataSet:
    features: Union[np.ndarray, "jax.Array"]
    labels: Optional[Union[np.ndarray, "jax.Array"]] = None
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def split_test_and_train(self, n_train: int) -> Tuple["DataSet", "DataSet"]:
        def sl(a, lo, hi):
            return None if a is None else a[lo:hi]
        n = self.num_examples()
        return (DataSet(*(sl(a, 0, n_train) for a in self._arrays())),
                DataSet(*(sl(a, n_train, n) for a in self._arrays())))

    def _arrays(self):
        return (self.features, self.labels, self.features_mask, self.labels_mask)

    def shuffle(self, seed: int = 0) -> "DataSet":
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self.num_examples())
        def idx(a):
            # host-sync-ok: host-side shuffle of numpy arrays pre-transfer
            return None if a is None else np.asarray(a)[perm]  # host-sync-ok: host shuffle
        return DataSet(*(idx(a) for a in self._arrays()))

    @staticmethod
    def merge(batches: Sequence["DataSet"]) -> "DataSet":
        def cat(xs):
            xs = [x for x in xs if x is not None]
            return np.concatenate(  # host-sync-ok: host-side batch merge
                [np.asarray(x) for x in xs],  # host-sync-ok: host batch merge
                axis=0) if xs else None
        return DataSet(cat([b.features for b in batches]),
                       cat([b.labels for b in batches]),
                       cat([b.features_mask for b in batches]),
                       cat([b.labels_mask for b in batches]))


@dataclasses.dataclass
class MultiDataSet:
    """Multiple feature/label arrays for ComputationGraph (analog of ND4J
    MultiDataSet)."""
    features: List[np.ndarray]
    labels: List[np.ndarray]
    features_masks: Optional[List[Optional[np.ndarray]]] = None
    labels_masks: Optional[List[Optional[np.ndarray]]] = None

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])


class DataSetIterator:
    """Iterator protocol: iterable over DataSet minibatches with reset().
    Matches the reference's interface surface (batch(), totalOutcomes(),
    resetSupported(), asyncSupported()) where meaningful in Python."""

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError

    def reset(self):
        pass

    @property
    def batch_size(self) -> Optional[int]:
        return None

    @property
    def async_supported(self) -> bool:
        return True


class ListDataSetIterator(DataSetIterator):
    """In-memory iterator over a list of pre-built minibatches (analog of
    the reference's ListDataSetIterator)."""

    def __init__(self, batches: Sequence[DataSet]):
        self._batches = list(batches)

    def __iter__(self):
        return iter(self._batches)

    def __len__(self):
        return len(self._batches)

    @property
    def batch_size(self):
        return self._batches[0].num_examples() if self._batches else None


class ArrayDataSetIterator(DataSetIterator):
    """Batches a single large DataSet (analog of creating an iterator from
    arrays; supports shuffling each epoch)."""

    def __init__(self, data: DataSet, batch_size: int, shuffle: bool = False,
                 seed: int = 0, drop_last: bool = False):
        self._data = data
        self._bs = batch_size
        self._shuffle = shuffle
        self._seed = seed
        self._epoch = 0
        self._drop_last = drop_last

    def __iter__(self):
        d = self._data
        if self._shuffle:
            d = d.shuffle(self._seed + self._epoch)
            self._epoch += 1
        n = d.num_examples()
        end = n - (n % self._bs) if self._drop_last else n
        for lo in range(0, end, self._bs):
            hi = min(lo + self._bs, n)
            def cut(a):
                # host-sync-ok: host-side batch slicing before transfer
                return None if a is None else np.asarray(a)[lo:hi]  # host-sync-ok: host slice
            yield DataSet(cut(d.features), cut(d.labels),
                          cut(d.features_mask), cut(d.labels_mask))

    @property
    def batch_size(self):
        return self._bs
