"""Record readers + the DataVec bridge.

TPU-native equivalent of the external DataVec ETL surface the reference
depends on (SURVEY §2.14: 175 ``org.datavec.api`` imports) and of the
in-repo bridge iterators (§2.3:
``RecordReaderDataSetIterator.java``, ``SequenceRecordReaderDataSetIterator.java``
with seq2seq alignment modes). Records are plain numpy rows; readers are
small host-side objects whose hot parse loops run in the native C++
library when built (native/dl4j_native.cpp), numpy otherwise.

Design notes vs the reference:
- DataVec's Writable type zoo collapses to float32 ndarrays — device
  infeed wants dense tensors, not boxed values;
- the bridge emits static-shaped batches (padded + masked for sequences)
  because XLA recompiles on shape change; alignment modes map to mask
  layouts, same semantics as the reference's ALIGN_START/ALIGN_END.
"""

from __future__ import annotations

import enum
import os
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, DataSetIterator


# -------------------------------------------------------------------------
# Readers
# -------------------------------------------------------------------------

class RecordReader:
    """Iterable over records (1-D float arrays)."""

    def __iter__(self) -> Iterator[np.ndarray]:
        raise NotImplementedError

    def reset(self):
        pass


class CSVRecordReader(RecordReader):
    """Numeric CSV file/text reader (DataVec CSVRecordReader analog).

    ``skip_lines`` skips headers; parsing uses the native C++ loop when
    available.
    """

    def __init__(self, path: Optional[str] = None,
                 text: Optional[str] = None, delimiter: str = ",",
                 skip_lines: int = 0):
        if (path is None) == (text is None):
            raise ValueError("provide exactly one of path= or text=")
        self.path, self.text = path, text
        self.delimiter = delimiter
        self.skip_lines = skip_lines
        self._data: Optional[np.ndarray] = None

    def _load(self) -> np.ndarray:
        if self._data is None:
            text = self.text
            if text is None:
                with open(self.path, "r") as f:
                    text = f.read()
            if self.skip_lines:
                text = "\n".join(text.splitlines()[self.skip_lines:])
            from deeplearning4j_tpu.utils import native
            mat = native.parse_csv(text, self.delimiter)
            if mat is None:   # no toolchain: numpy fallback
                rows = [r for r in text.splitlines() if r.strip()]
                mat = np.asarray(  # host-sync-ok: host-side data decode/build pre-transfer
                    [[float(v) for v in r.split(self.delimiter)]  # host-sync-ok: host-side data decode/build pre-transfer
                     for r in rows], np.float32)
            self._data = mat
        return self._data

    def __iter__(self) -> Iterator[np.ndarray]:
        return iter(self._load())


class CollectionRecordReader(RecordReader):
    """In-memory records (DataVec CollectionRecordReader)."""

    def __init__(self, records: Sequence[Sequence[float]]):
        self._records = [np.asarray(r, np.float32) for r in records]  # host-sync-ok: host-side data decode/build pre-transfer

    def __iter__(self):
        return iter(self._records)


class SequenceRecordReader:
    """Iterable over sequences: each item is a (T, F) float matrix
    (DataVec SequenceRecordReader)."""

    def __iter__(self) -> Iterator[np.ndarray]:
        raise NotImplementedError

    def reset(self):
        pass


class CollectionSequenceRecordReader(SequenceRecordReader):
    def __init__(self, sequences: Sequence[Sequence[Sequence[float]]]):
        self._seqs = [np.asarray(s, np.float32) for s in sequences]  # host-sync-ok: host-side data decode/build pre-transfer

    def __iter__(self):
        return iter(self._seqs)


class CSVSequenceRecordReader(SequenceRecordReader):
    """One CSV file per sequence (DataVec CSVSequenceRecordReader)."""

    def __init__(self, paths: Sequence[str], delimiter: str = ",",
                 skip_lines: int = 0):
        self.readers = [CSVRecordReader(path=p, delimiter=delimiter,
                                        skip_lines=skip_lines)
                        for p in paths]

    def __iter__(self):
        for r in self.readers:
            yield r._load()


# -------------------------------------------------------------------------
# Bridge iterators
# -------------------------------------------------------------------------

def _one_hot(idx: np.ndarray, n: int) -> np.ndarray:
    out = np.zeros((idx.shape[0], n), np.float32)
    out[np.arange(idx.shape[0]), idx.astype(np.int64)] = 1.0
    return out


class RecordReaderDataSetIterator(DataSetIterator):
    """records -> DataSet batches (RecordReaderDataSetIterator.java).

    ``label_index`` selects the label column; with ``num_classes`` the
    label becomes one-hot (classification), otherwise it stays a
    regression target. ``label_index_to`` selects a label column range
    (multi-output regression), inclusive, like the reference.
    """

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = None,
                 num_classes: Optional[int] = None,
                 label_index_to: Optional[int] = None,
                 regression: bool = False):
        self.reader = reader
        self._batch = batch_size
        self.label_index = label_index
        self.label_index_to = label_index_to
        self.num_classes = num_classes
        self.regression = regression or label_index_to is not None

    def _split(self, rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        li = self.label_index
        if li is None:
            return rows, rows        # unsupervised: features as labels
        hi = (self.label_index_to if self.label_index_to is not None
              else li)
        feats = np.concatenate([rows[:, :li], rows[:, hi + 1:]], axis=1)
        labels = rows[:, li:hi + 1]
        if not self.regression:
            if self.num_classes is None:
                raise ValueError(
                    "classification needs num_classes (or pass"
                    " regression=True)")
            labels = _one_hot(labels[:, 0], self.num_classes)
        return feats.astype(np.float32), labels.astype(np.float32)

    def __iter__(self) -> Iterator[DataSet]:
        buf: List[np.ndarray] = []
        for rec in self.reader:
            buf.append(np.asarray(rec, np.float32))  # host-sync-ok: host-side data decode/build pre-transfer
            if len(buf) == self._batch:
                f, l = self._split(np.stack(buf))
                yield DataSet(f, l)
                buf = []
        if buf:
            f, l = self._split(np.stack(buf))
            yield DataSet(f, l)
        self.reader.reset()

    def reset(self):
        self.reader.reset()

    @property
    def batch_size(self):
        return self._batch


class AlignmentMode(enum.Enum):
    """Sequence label alignment (SequenceRecordReaderDataSetIterator
    AlignmentMode): where shorter sequences sit inside the padded window."""
    ALIGN_START = "align_start"
    ALIGN_END = "align_end"
    EQUAL_LENGTH = "equal_length"


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """(features_seq_reader, labels_seq_reader) -> padded+masked DataSet
    batches (SequenceRecordReaderDataSetIterator.java, incl. seq2seq
    alignment modes — SURVEY §2.3)."""

    def __init__(self, feature_reader: SequenceRecordReader,
                 label_reader: Optional[SequenceRecordReader],
                 batch_size: int,
                 num_classes: Optional[int] = None,
                 regression: bool = False,
                 alignment: AlignmentMode = AlignmentMode.ALIGN_START):
        self.feature_reader = feature_reader
        self.label_reader = label_reader
        self._batch = batch_size
        self.num_classes = num_classes
        self.regression = regression
        self.alignment = alignment

    def _pack(self, feats: List[np.ndarray], labels: List[np.ndarray]):
        t_max = max(max(f.shape[0] for f in feats),
                    max(l.shape[0] for l in labels))
        n = len(feats)
        fdim = feats[0].shape[1]
        if self.regression or self.num_classes is None:
            ldim = labels[0].shape[1]
        else:
            ldim = self.num_classes
        f_out = np.zeros((n, t_max, fdim), np.float32)
        l_out = np.zeros((n, t_max, ldim), np.float32)
        f_mask = np.zeros((n, t_max), np.float32)
        l_mask = np.zeros((n, t_max), np.float32)
        for i, (f, l) in enumerate(zip(feats, labels)):
            tf_, tl = f.shape[0], l.shape[0]
            if self.alignment is AlignmentMode.EQUAL_LENGTH \
                    and tf_ != tl:
                raise ValueError(
                    f"EQUAL_LENGTH alignment but lengths {tf_} != {tl}")
            if not self.regression and self.num_classes is not None:
                l = _one_hot(l[:, 0], self.num_classes)
            if self.alignment is AlignmentMode.ALIGN_END:
                f_out[i, t_max - tf_:] = f
                f_mask[i, t_max - tf_:] = 1.0
                l_out[i, t_max - tl:] = l
                l_mask[i, t_max - tl:] = 1.0
            else:
                f_out[i, :tf_] = f
                f_mask[i, :tf_] = 1.0
                l_out[i, :tl] = l
                l_mask[i, :tl] = 1.0
        return DataSet(f_out, l_out, f_mask, l_mask)

    def __iter__(self) -> Iterator[DataSet]:
        feats, labels = [], []
        label_iter = (iter(self.label_reader)
                      if self.label_reader is not None else None)
        for f in self.feature_reader:
            f = np.asarray(f, np.float32)  # host-sync-ok: host-side data decode/build pre-transfer
            if label_iter is not None:
                l = np.asarray(next(label_iter), np.float32)  # host-sync-ok: host-side data decode/build pre-transfer
            else:
                # single-reader mode: last column is the per-step label
                l = f[:, -1:]
                f = f[:, :-1]
            feats.append(f)
            labels.append(l)
            if len(feats) == self._batch:
                yield self._pack(feats, labels)
                feats, labels = [], []
        if feats:
            yield self._pack(feats, labels)
        self.feature_reader.reset()
        if self.label_reader is not None:
            self.label_reader.reset()

    def reset(self):
        self.feature_reader.reset()
        if self.label_reader is not None:
            self.label_reader.reset()

    @property
    def batch_size(self):
        return self._batch
