"""Object-store corpus shards: spool an unbounded sentence stream into
an ``ArtifactStore`` bucket and read it back as a (re-iterable,
follow-able) sentence source.

The reference's object-store iterator shape (BaseS3DataSetIterator):
training reads records from a bucket it doesn't own the lifecycle of.
Here the bucket layout is ``parallel/aot_cache.py``'s ``ArtifactStore``
(local dir today, the key/object split maps 1:1 onto GCS/S3), sharing
its concurrency discipline — shard files are written whole, then the
manifest is rewritten atomically and LAST, so a reader mid-append just
misses the newest shard and picks it up on the next manifest poll::

    <root>/objects/<key>/shard_000000.txt     one sentence per line
    <root>/objects/<key>/shard_000001.txt
    <root>/objects/<key>/manifest.json        {"kind": "corpus", ...}

This is what decouples streaming ingestion from training cadence: a
``CorpusShardWriter`` drains a broker topic at wire speed while
``Word2Vec.fit_stream`` (or plain ``fit``) re-reads sealed shards as
many times as it likes — the unbounded stream becomes a replayable
corpus.
"""

from __future__ import annotations

import json
import os
import time
from typing import Iterable, Iterator, Optional

from deeplearning4j_tpu.parallel.aot_cache import ArtifactStore

CORPUS_KIND = "corpus"


class CorpusShardWriter:
    """Append sentences into ``<store>/objects/<key>/`` as line-text
    shards of ``shard_sentences`` lines each. Every sealed shard
    republishes the manifest (atomic replace), so follow-mode readers
    see it immediately; ``close()`` seals the partial tail shard and
    marks the manifest ``complete`` — the reader's end-of-corpus
    signal."""

    def __init__(self, store: ArtifactStore, key: str,
                 shard_sentences: int = 10000):
        self.store = store
        self.key = key
        self.dir = store.cache_dir(key)
        self.shard_sentences = int(shard_sentences)
        self.shards: list = []
        self.sentences = 0
        self._buf: list = []
        self._closed = False

    def append(self, sentence: str) -> None:
        assert not self._closed, "writer is closed"
        s = sentence.strip()
        if not s:
            return
        self._buf.append(s)
        if len(self._buf) >= self.shard_sentences:
            self._seal_shard()

    def extend(self, sentences: Iterable[str]) -> int:
        n = 0
        for s in sentences:
            self.append(s)
            n += 1
        return n

    def _seal_shard(self) -> None:
        if not self._buf:
            return
        name = f"shard_{len(self.shards):06d}.txt"
        path = os.path.join(self.dir, name)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write("\n".join(self._buf) + "\n")
        os.replace(tmp, path)        # shard lands whole or not at all
        self.shards.append(name)
        self.sentences += len(self._buf)
        self._buf = []
        self._publish(complete=False)

    def _publish(self, complete: bool) -> None:
        manifest = {
            "kind": CORPUS_KIND,
            "shards": list(self.shards),
            "sentences": self.sentences,
            "complete": bool(complete),
        }
        path = os.path.join(self.dir, "manifest.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(manifest, f)
        os.replace(tmp, path)        # manifest atomically, LAST

    def close(self) -> None:
        if self._closed:
            return
        self._seal_shard()
        self._publish(complete=True)
        self._closed = True


class CorpusDataSetIterator:
    """Sentence iterator over an ArtifactStore corpus bucket (the
    ``BaseS3DataSetIterator`` shape). Two modes:

    - snapshot (``follow=False``): iterate the shards the manifest
      lists right now; ``reset()``/re-iteration replays them — this is
      the multi-pass corpus ``Word2Vec.fit`` wants.
    - ``follow=True``: poll the manifest for new shards as a writer
      appends them, yielding sentences until the manifest goes
      ``complete`` (all shards drained), ``idle_timeout_s`` passes
      with no growth, or ``stop_event`` is set — the unbounded-stream
      face consumed by ``fit_stream``.

    A dead store is NOT a quiet writer: in follow mode, a manifest that
    VANISHES after having been seen, or a listed shard that can no
    longer be read, terminates immediately with ``termination_reason =
    "store_dead"`` (error text in ``store_error``) instead of idling
    until ``idle_timeout_s``. ``termination_reason`` after a follow
    iteration is one of ``"complete"`` | ``"stopped"`` |
    ``"idle_timeout"`` | ``"store_dead"`` (snapshot mode: ``"eos"``).
    """

    def __init__(self, store: ArtifactStore, key: str, *,
                 follow: bool = False, poll_interval_s: float = 0.1,
                 idle_timeout_s: Optional[float] = None,
                 stop_event=None):
        self.store = store
        self.key = key
        self.follow = bool(follow)
        self.poll_interval_s = float(  # host-sync-ok: config scalar
            poll_interval_s)
        self.idle_timeout_s = idle_timeout_s
        self.stop_event = stop_event
        self.consumed = 0
        self.termination_reason: Optional[str] = None
        self.store_error: Optional[str] = None

    def _manifest(self) -> Optional[dict]:
        m = self.store.manifest(self.key)
        if m is not None and m.get("kind") != CORPUS_KIND:
            raise ValueError(
                f"artifact key {self.key!r} holds a "
                f"{m.get('kind', 'unknown')!r} manifest, not a corpus")
        return m

    def _read_shard(self, name: str) -> Iterator[str]:
        path = os.path.join(self.store.cache_dir(self.key), name)
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    self.consumed += 1
                    yield line

    def __iter__(self) -> Iterator[str]:
        self.termination_reason = None
        self.store_error = None
        if not self.follow:
            m = self._manifest() or {"shards": []}
            for name in m["shards"]:
                yield from self._read_shard(name)
            self.termination_reason = "eos"
            return
        done = 0
        idle = 0.0
        seen = False
        while True:
            if self.stop_event is not None and self.stop_event.is_set():
                self.termination_reason = "stopped"
                return
            m = self._manifest()
            if m is None:
                if seen:
                    # the bucket existed and is now gone — the store
                    # died under us; idling until idle_timeout would
                    # hide that from the consumer
                    self.termination_reason = "store_dead"
                    self.store_error = (
                        f"manifest for {self.key!r} vanished after "
                        f"{done} shard(s)")
                    return
                m = {"shards": [], "complete": False}
            else:
                seen = True
            shards = m["shards"]
            if done < len(shards):
                idle = 0.0
                for name in shards[done:]:
                    try:
                        yield from self._read_shard(name)
                    except OSError as e:
                        # manifest-listed shard unreadable: a sealed
                        # shard never disappears in a healthy store
                        self.termination_reason = "store_dead"
                        self.store_error = str(e)
                        return
                    done += 1
                continue
            if m.get("complete"):
                self.termination_reason = "complete"
                return
            time.sleep(self.poll_interval_s)
            idle += self.poll_interval_s
            if (self.idle_timeout_s is not None
                    and idle >= self.idle_timeout_s):
                self.termination_reason = "idle_timeout"
                return

    def reset(self):
        """Snapshot mode re-iterates from the first shard anyway; kept
        for SentenceIterator protocol compatibility."""


def spool_stream(sentences: Iterable[str], store: ArtifactStore,
                 key: str, *, shard_sentences: int = 10000,
                 writer: Optional[CorpusShardWriter] = None) -> int:
    """Drain a sentence stream (e.g. a StreamingSentenceIterator) into
    a corpus bucket; returns the sentence count. The ingest side of the
    broker -> object store -> trainer pipeline."""
    w = writer or CorpusShardWriter(store, key,
                                    shard_sentences=shard_sentences)
    n = w.extend(sentences)
    w.close()
    return n
