"""DeviceFeeder: device-side input prefetch + K-step batch staging.

The reference keeps the accelerator fed by wrapping every
``fit(DataSetIterator)`` in an AsyncDataSetIterator thread that stages
minibatches into device workspaces (MultiLayerNetwork.java:1273, SURVEY
§2.3). The JAX analog has TWO gaps to close, both measured in
PERF_ANALYSIS:

1. **Transfer on the critical path.** ``jnp.asarray(batch)`` inside the
   step loop serializes host→device wire time with compute. The feeder
   instead issues ``jax.device_put`` for batches *i+1 / i+2* while the
   (asynchronously dispatched) step *i* still computes, holding up to
   ``depth`` staged batches in a bounded double-buffer (default 2
   slots, optional byte budget).
2. **Per-dispatch overhead.** Each dispatch carries fixed cost (~26–30
   ms through tunneled PJRT transports, r3); ``k_steps > 1`` groups K
   prefetched batches into ONE stacked device array and the fit loop
   runs ``make_scan_train_step`` over it — the exact mechanism bench.py
   hand-rolls, promoted to the user-facing ``fit()``.

To keep the K-step path (and, opted in, the per-batch path) at ONE
compiled signature, the feeder normalizes ragged batches: every batch
gets an explicit labels mask (ones where it had none) and the final
partial batch is padded to the bucket size with duplicated zero-weight
rows — the masked loss mean ignores them, so the trajectory matches the
unpadded dispatch bitwise while the RecompileWatchdog sees zero new
signatures (it used to count every ragged tail as a storm).

Observability: ``dl4j_feed_depth`` (staged batches at last hand-off)
and ``dl4j_etl_stall_ms`` (cumulative ms the step loop actually waited
for data) ride the process registry; the tracer gets ``etl`` spans for
host-side batch production, ``host_to_device`` spans for the staging
issue (wire), and ``feed_stall`` spans whenever the queue ran dry — so
overlap (or its absence) is visible in the Perfetto timeline.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Iterable, List, NamedTuple, Optional

import jax
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.observe.registry import default_registry
from deeplearning4j_tpu.observe.tracer import NULL_TRACER

DEFAULT_DEPTH = 2


# ---- ragged-batch normalization (shared with parallel/wrapper.py) ------

def ones_labels_mask(batch: DataSet) -> np.ndarray:
    """The all-ones labels mask matching this batch's label rank — the
    identity element of the masked loss mean (ops/losses._masked_mean
    divides by sum(mask), so ones reproduce the plain mean bitwise)."""
    lab = np.asarray(batch.labels)  # host-sync-ok: host-side batch staging before transfer
    n = batch.num_examples()
    if lab.ndim <= 2:
        # (N,) sparse or (N, C) dense labels → per-example weights
        return np.ones((n,), np.float32)
    if lab.ndim == 3 and batch.features_mask is not None:
        # variable-length sequences: the loss would have used the
        # features mask — keep those semantics explicit
        return np.asarray(batch.features_mask, np.float32)  # host-sync-ok: host-side batch staging before transfer
    # (N, T, C) → (N, T); (N, H, W, C) → (N, H, W)
    return np.ones(lab.shape[:-1], np.float32)


def ensure_labels_mask(batch: DataSet) -> DataSet:
    """Attach an explicit (all-ones) labels mask when the batch carries
    none, so full and padded batches share one compile signature."""
    if batch.labels_mask is not None or batch.labels is None:
        return batch
    return DataSet(batch.features, batch.labels, batch.features_mask,
                   ones_labels_mask(batch))


def pad_rows(batch: DataSet, pad: int) -> DataSet:
    """Append ``pad`` zero-weight rows: features/labels/features-mask
    duplicate the last row (finite activations — a zeroed row could
    still NaN through log/normalization paths), the labels mask extends
    with zeros so the masked loss mean and its gradients ignore them.
    The one caveat is BatchNormalization batch statistics, which see the
    duplicated rows (mask-free batch moments) — same bounded
    perturbation the parallel wrapper's padding has always accepted."""
    if pad <= 0:
        return batch

    def rep(a):
        if a is None:
            return None
        a = np.asarray(a)  # host-sync-ok: host-side batch staging before transfer
        return np.concatenate([a, np.repeat(a[-1:], pad, axis=0)], axis=0)

    lmask = batch.labels_mask
    if lmask is None:
        lmask = ones_labels_mask(batch)
    lmask = np.asarray(lmask)  # host-sync-ok: host-side batch staging before transfer
    zeros = np.zeros((pad,) + lmask.shape[1:], lmask.dtype)
    return DataSet(rep(batch.features), rep(batch.labels),
                   rep(batch.features_mask),
                   np.concatenate([lmask, zeros], axis=0))


def pad_to_bucket(batch: DataSet, bucket: int) -> DataSet:
    """Normalize one batch to exactly ``bucket`` examples with an
    explicit labels mask (see ``pad_rows``). Bitwise-neutral for masked
    losses; raises when the batch is LARGER than the bucket (a growing
    batch is a data-pipeline bug, not a ragged tail)."""
    n = batch.num_examples()
    if n > bucket:
        raise ValueError(
            f"batch of {n} examples exceeds the feed bucket size "
            f"{bucket}; ragged-batch padding only shrinks tails")
    return pad_rows(ensure_labels_mask(batch), bucket - n)


# ---- staged items -------------------------------------------------------

class FeedItem(NamedTuple):
    """One staged hand-off from the feeder to the fit loop. Arrays are
    device-resident (already ``device_put``). ``k == 0`` marks a
    passthrough batch the feeder does not understand (e.g. a
    MultiDataSet) — ``raw`` then holds the untouched host object and the
    fit loop takes its unfed path for it."""
    features: Any
    labels: Any
    features_mask: Any
    labels_mask: Any
    k: int                  # inner optimizer steps this item carries
    n_examples: int         # REAL examples (pre-padding), for listeners
    queue_wait_ms: float    # time the consumer stalled for this item
    nbytes: int
    raw: Any = None

    def as_dataset(self) -> DataSet:
        return DataSet(self.features, self.labels, self.features_mask,
                       self.labels_mask)


class _HostItem(NamedTuple):
    """Host-side prepared arrays, pre-staging."""
    arrays: tuple           # (features, labels, fmask, lmask) numpy/None
    k: int
    n_examples: int
    raw: Any = None


class StagingPool:
    """Reusable host staging buffers, the pinned-memory analog: one
    rotating ring of ``slots`` numpy buffers per (shape, dtype), so
    steady-state feeding stops allocating fresh host arrays per batch.
    Only safe when ``put`` COPIES (real accelerators do; the CPU backend
    zero-copy adopts numpy buffers — reusing one would corrupt staged
    batches, so the feeder auto-disables the pool there)."""

    def __init__(self, slots: int):
        self.slots = max(2, int(slots))
        self._rings = {}

    def stage(self, a: np.ndarray) -> np.ndarray:
        key = (a.shape, a.dtype.str)
        ring = self._rings.get(key)
        if ring is None:
            ring = [np.empty(a.shape, a.dtype) for _ in range(self.slots)]
            self._rings[key] = ring
        buf = ring[0]
        ring.append(ring.pop(0))
        np.copyto(buf, a)
        return buf


class DeviceFeeder:
    """Bounded device-side prefetch queue over an iterable of DataSets.

    Parameters
    ----------
    source : iterable of DataSet (foreign objects pass through unstaged)
    depth : staged batches held ahead of the consumer (default 2 — the
        classic double buffer)
    byte_budget : optional soft cap on staged bytes; refill stops above
        it (at least one item is always staged)
    k_steps : >1 groups K batches into one stacked (K, B, ...) device
        array for the scanned multi-step dispatch; the remainder of an
        epoch not filling a group is yielded as per-batch items at the
        same bucket shape (no K-recompile, no dummy optimizer steps)
    pad_ragged : normalize every batch to the bucket size (first batch's
        example count) with an explicit labels mask. Defaults to True
        when ``k_steps > 1`` (stacking requires it), else False.
    prepare : optional host-side hook ``DataSet -> DataSet`` applied
        before normalization/stacking (the parallel wrapper pads to its
        worker multiple here)
    group_prepare : optional hook ``[DataSet] -> (f, l, fm, lm)``
        overriding the default stack of a K-group (the wrapper's
        AVERAGING round staging)
    group_remainder : "split" (default) yields a short tail group as
        per-batch items; "pad" repeats the last batch to a full group —
        the AVERAGING-round contract, where the round is the unit
    put : staging function ``np.ndarray -> jax.Array`` (default
        ``jax.device_put``; the wrapper passes its sharded staging)
    reuse_staging : reuse host staging buffers between batches (None =
        auto: on for non-CPU backends, where ``device_put`` copies)
    """

    def __init__(self, source: Iterable, *, depth: Optional[int] = None,
                 byte_budget: Optional[int] = None, k_steps: int = 1,
                 pad_ragged: Optional[bool] = None,
                 prepare: Optional[Callable[[DataSet], DataSet]] = None,
                 group_prepare: Optional[Callable[[List[DataSet]], tuple]]
                 = None,
                 group_remainder: str = "split",
                 put: Optional[Callable] = None,
                 tracer=None, registry=None, session_id: str = "train",
                 reuse_staging: Optional[bool] = None):
        if depth is None:
            # direct constructions (fit() resolves its own): measured
            # tuned depth when a process TunedConfig is installed, else
            # the committed double buffer
            from deeplearning4j_tpu.optimize.autotune import tuned_value
            tuned = tuned_value("feeder.depth")
            depth = DEFAULT_DEPTH if tuned is None else int(tuned)
        if depth < 1:
            raise ValueError("feeder depth must be >= 1")
        if k_steps < 1:
            raise ValueError("k_steps must be >= 1")
        if group_remainder not in ("split", "pad"):
            raise ValueError("group_remainder must be 'split' or 'pad'")
        self.source = source
        self.depth = int(depth)
        self.byte_budget = byte_budget
        self.k_steps = int(k_steps)
        self.pad_ragged = (self.k_steps > 1 if pad_ragged is None
                           else bool(pad_ragged))
        self.prepare = prepare
        self.group_prepare = group_prepare
        self.group_remainder = group_remainder
        self.put = put if put is not None else jax.device_put
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.session_id = session_id
        reg = registry if registry is not None else default_registry()
        self._g_depth = reg.gauge(
            "dl4j_feed_depth", "device batches staged ahead of the step "
            "loop by the input feeder")
        self._g_stall = reg.gauge(
            "dl4j_etl_stall_ms", "cumulative ms the step loop waited on "
            "the input feeder (0 = ETL fully hidden behind compute)")
        if reuse_staging is None:
            reuse_staging = jax.devices()[0].platform != "cpu"
        self._pool = (StagingPool(self.depth + 2) if reuse_staging
                      else None)
        # bucket = the normalized example count; seeded from the
        # source's declared batch size so a tiny first pass (ragged
        # FIRST batch) can't lock in an undersized bucket
        bs = getattr(source, "batch_size", None)
        self.bucket_size: Optional[int] = (int(bs) if isinstance(bs, int)
                                           and bs > 0 else None)
        self.stall_ms = 0.0
        self.max_depth_seen = 0
        self._staged_bytes = 0

    # ---- host-side production -------------------------------------------
    def _normalize(self, batch: DataSet) -> DataSet:
        if self.bucket_size is None:
            self.bucket_size = batch.num_examples()
        return pad_to_bucket(batch, self.bucket_size)

    def _arrays_of(self, batch: DataSet) -> tuple:
        return (batch.features, batch.labels, batch.features_mask,
                batch.labels_mask)

    def _make_group(self, group: List[DataSet]) -> _HostItem:
        """Stack a K-group of RAW batches into (K, B, ...) host arrays.
        Real example counts are taken before the prepare hooks run —
        listeners must see genuine counts, not padded ones."""
        n_real = sum(b.num_examples() for b in group)
        prepared = [self.prepare(b) if self.prepare is not None else b
                    for b in group]
        if self.group_prepare is not None:
            arrays = self.group_prepare(prepared)
        else:
            norm = [self._arrays_of(self._normalize(b)) for b in prepared]
            arrays = tuple(
                None if any(a[i] is None for a in norm)
                else np.stack([np.asarray(a[i]) for a in norm])  # host-sync-ok: host-side batch staging before transfer
                for i in range(4))
        return _HostItem(arrays, len(group), n_real)

    def _make_single(self, batch: DataSet, normalize: bool) -> _HostItem:
        n_real = batch.num_examples()
        if self.prepare is not None:
            batch = self.prepare(batch)
        if normalize:
            batch = self._normalize(batch)
        return _HostItem(self._arrays_of(batch), 1, n_real)

    def _host_items(self):
        """Generator of host-prepared items: per-batch DataSets (k=1),
        stacked K-groups (k=K), or passthrough foreign objects (k=0)."""
        group: List[DataSet] = []
        for b in self.source:
            if not isinstance(b, DataSet):
                for item in self._flush_group(group):
                    yield item
                group = []
                yield _HostItem((None,) * 4, 0, 0, raw=b)
                continue
            if self.k_steps > 1 or self.group_prepare is not None:
                # a group_prepare hook defines the staged LAYOUT (e.g.
                # the wrapper's stacked (K, B, ...) AVERAGING rounds),
                # so it must run even for K=1 groups
                group.append(b)
                if len(group) == self.k_steps:
                    yield self._make_group(group)
                    group = []
            else:
                yield self._make_single(b, normalize=self.pad_ragged)
        for item in self._flush_group(group):
            yield item

    def _flush_group(self, group: List[DataSet]):
        if not group:
            return
        if self.group_remainder == "pad" and len(group) < self.k_steps:
            # the round is the unit: repeat the tail batch to a full
            # group (the AVERAGING contract — ParallelWrapper has always
            # padded short rounds this way, counting the repeats)
            padded = group + [group[-1]] * (self.k_steps - len(group))
            yield self._make_group(padded)
            return
        if len(group) == self.k_steps:
            yield self._make_group(group)
            return
        # short tail, "split": per-batch items at the SAME bucket shape
        # the K-group members were padded to — the per-batch step keeps
        # its one signature and no dummy optimizer steps run
        for b in group:
            yield self._make_single(b, normalize=True)

    # ---- staging ---------------------------------------------------------
    def _stage(self, item: _HostItem) -> FeedItem:
        if item.k == 0:
            return FeedItem(None, None, None, None, 0, item.n_examples,
                            0.0, 0, raw=item.raw)
        start = time.perf_counter()
        staged = []
        nbytes = 0
        for a in item.arrays:
            if a is None:
                staged.append(None)
                continue
            a = np.asarray(a)  # host-sync-ok: host-side batch staging before transfer
            nbytes += a.nbytes
            if self._pool is not None:
                a = self._pool.stage(a)
            staged.append(self.put(a))
        self.tracer.add_span("host_to_device", start, time.perf_counter(),
                             cat="data", wire=True, k=item.k,
                             bytes=nbytes)
        self._staged_bytes += nbytes
        return FeedItem(staged[0], staged[1], staged[2], staged[3],
                        item.k, item.n_examples, 0.0, nbytes)

    # ---- the prefetch loop ----------------------------------------------
    def __iter__(self):
        src = self._host_items()
        pending: deque = deque()
        exhausted = False
        self.stall_ms = 0.0
        self._staged_bytes = 0
        while True:
            wait_ms = 0.0
            while not exhausted and len(pending) < self.depth and (
                    not pending or self.byte_budget is None
                    or self._staged_bytes < self.byte_budget):
                t0 = time.perf_counter()
                try:
                    item = next(src)
                except StopIteration:
                    exhausted = True
                    break
                t1 = time.perf_counter()
                self.tracer.add_span("etl", t0, t1, cat="data")
                staged = self._stage(item)
                if not pending:
                    # queue ran dry: the consumer genuinely waited for
                    # host production + staging issue of THIS item
                    stall = (time.perf_counter() - t0) * 1000.0
                    wait_ms += stall
                    self.stall_ms += stall
                    self.tracer.add_span("feed_stall", t0,
                                         time.perf_counter(), cat="data")
                pending.append(staged)
            if not pending:
                break
            self.max_depth_seen = max(self.max_depth_seen, len(pending))
            self._g_depth.set(len(pending), session=self.session_id)
            self._g_stall.set(self.stall_ms, session=self.session_id)
            out = pending.popleft()
            self._staged_bytes -= out.nbytes
            yield out._replace(queue_wait_ms=wait_ms)
