"""Camel-style routes: source topic → transforms → sink.

Analog of the reference's Camel route builders in dl4j-streaming
(SURVEY §2.11): declarative pipelines that move NDArray records between
topics with per-hop transforms — e.g. raw records in, model scores out.
A route runs on a background thread; transforms are host-side Python
(decode/reshape) or jitted model calls (the inference hop).
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

import numpy as np

from deeplearning4j_tpu.streaming.broker import (
    NDArrayConsumer,
    NDArrayPublisher,
    Transport,
)
from deeplearning4j_tpu.streaming.serde import NDArrayMessage

StreamStep = Callable[[np.ndarray], np.ndarray]


class Route:
    """``Route(t).from_topic("in").process(f).to_topic("out").start()``"""

    def __init__(self, transport: Transport):
        self.transport = transport
        self._source: Optional[str] = None
        self._sink: Optional[str] = None
        self._steps: List[StreamStep] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.processed = 0
        self.errors = 0
        self.last_error: Optional[BaseException] = None

    def from_topic(self, topic: str) -> "Route":
        self._source = topic
        return self

    def process(self, fn: StreamStep) -> "Route":
        self._steps.append(fn)
        return self

    def to_topic(self, topic: str) -> "Route":
        self._sink = topic
        return self

    def start(self) -> "Route":
        if self._source is None:
            raise ValueError("route needs from_topic(...)")
        consumer = NDArrayConsumer(self.transport, self._source)
        publisher = (None if self._sink is None
                     else NDArrayPublisher(self.transport, self._sink))

        def run():
            while not self._stop.is_set():
                try:
                    # poll inside the try: a transport error (broker
                    # restart beyond the transport's own retries) must
                    # not kill the route thread for good
                    msg = consumer.poll(timeout=0.1)
                    if msg is None:
                        continue
                    arr = msg.array
                    for step in self._steps:
                        arr = step(arr)
                    if publisher is not None:
                        publisher.publish(np.asarray(arr), key=msg.key)
                    self.processed += 1
                except Exception as e:  # bad message: record, keep going
                    self.errors += 1
                    self.last_error = e

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
