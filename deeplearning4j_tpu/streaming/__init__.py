"""Streaming ingestion — NDArray pub-sub over pluggable transports.

Analog of the reference's ``dl4j-streaming`` module (SURVEY §2.11):
``NDArrayKafkaClient`` + Camel routes publish/consume serialized NDArrays
so training/inference can ride a message bus. Kafka itself is an external
service; here the client API is transport-agnostic — an in-process broker
for tests/single-host pipelines and a TCP transport for cross-process —
with the same publish/subscribe surface, so a Kafka transport is a
drop-in (implement ``Transport``).
"""

from deeplearning4j_tpu.streaming.serde import (
    NDArrayMessage,
    deserialize_ndarray,
    serialize_ndarray,
)
from deeplearning4j_tpu.streaming.broker import (
    InProcessTransport,
    NDArrayConsumer,
    NDArrayPublisher,
    NDArrayStreamingClient,
    TcpTransport,
    Transport,
)
from deeplearning4j_tpu.streaming.routes import Route, StreamStep

__all__ = [
    "NDArrayMessage", "serialize_ndarray", "deserialize_ndarray",
    "Transport", "InProcessTransport", "TcpTransport",
    "NDArrayPublisher", "NDArrayConsumer", "NDArrayStreamingClient",
    "Route", "StreamStep",
]
