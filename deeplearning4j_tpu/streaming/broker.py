"""Transports + publisher/consumer client.

Analog of ``NDArrayKafkaClient`` (dl4j-streaming, SURVEY §2.11) with the
broker abstracted: ``InProcessTransport`` (queue per topic — the test/
single-host path, like the reference's Camel direct: routes) and
``TcpTransport`` (length-prefixed frames over a socket — cross-process).
A Kafka/PubSub transport is the same interface against a real broker.

``TcpTransport`` survives peer drops: a broken/timed-out socket is torn
down and the frame retried over a fresh connection with bounded
exponential backoff (a mid-exchange failure desyncs the framed
protocol, so reconnect is the only safe resync). Every reconnect
attempt is surfaced on the ``dl4j_stream_reconnects_total`` Prometheus
counter — before this, one dropped connection killed the consumer
thread for good (the online learner's input just stopped).
"""

from __future__ import annotations

import queue
import socket
import socketserver
import struct
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.chaos.hook import chaos_site
from deeplearning4j_tpu.streaming.serde import NDArrayMessage


class Transport:
    """publish/poll on named topics."""

    def publish(self, topic: str, payload: bytes) -> None:
        raise NotImplementedError

    def poll(self, topic: str, timeout: float = 1.0) -> Optional[bytes]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InProcessTransport(Transport):
    """Thread-safe per-topic queues; every subscriber pool shares one
    stream (competing consumers, like one Kafka consumer group).

    ``publish`` is BOUNDED: it waits up to ``put_timeout_s`` for a slot,
    then sheds the message and increments
    ``dl4j_stream_dropped_total{topic}`` — a slow (or dead) consumer
    must never wedge the publisher. Before this, a full topic queue
    blocked ``publish`` forever, which through the TCP broker's handler
    thread also wedged every other client on that connection."""

    def __init__(self, max_queue: int = 1024,
                 put_timeout_s: float = 1.0, registry=None):
        self._queues: Dict[str, queue.Queue] = defaultdict(
            lambda: queue.Queue(maxsize=max_queue))
        self._lock = threading.Lock()
        self.put_timeout_s = float(put_timeout_s)
        self.dropped = 0
        from deeplearning4j_tpu.observe.registry import default_registry
        reg = registry if registry is not None else default_registry()
        self._c_dropped = reg.counter(
            "dl4j_stream_dropped_total",
            "messages shed by a full bounded topic queue (slow "
            "consumer), by topic")

    def _q(self, topic: str) -> queue.Queue:
        with self._lock:
            return self._queues[topic]

    def publish(self, topic: str, payload: bytes) -> None:
        try:
            self._q(topic).put(payload, timeout=self.put_timeout_s)
        except queue.Full:
            self.dropped += 1
            self._c_dropped.inc(1.0, topic=topic)

    def poll(self, topic: str, timeout: float = 1.0) -> Optional[bytes]:
        try:
            return self._q(topic).get(timeout=timeout)
        except queue.Empty:
            return None


class _FrameHandler(socketserver.BaseRequestHandler):
    def handle(self):
        broker: InProcessTransport = self.server.broker  # type: ignore
        try:
            while True:
                hdr = self._recv_exact(9)
                if hdr is None:
                    return
                op, tlen, plen = struct.unpack("<BII", hdr)
                tbytes = self._recv_exact(tlen)
                if tbytes is None:
                    return
                topic = tbytes.decode("utf-8")
                if op == 0:  # publish
                    payload = self._recv_exact(plen)
                    if payload is None:
                        return
                    broker.publish(topic, payload)
                elif op == 1:  # poll
                    payload = broker.poll(topic, timeout=float(plen) / 1000)
                    body = payload or b""
                    self.request.sendall(
                        struct.pack("<I", len(body)) + body)
        except (ConnectionError, OSError):
            return

    def _recv_exact(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = self.request.recv(n - len(buf))
            if not chunk:
                return None  # disconnect (mid-frame partials discarded)
            buf += chunk
        return buf


class _BrokerServer(socketserver.ThreadingTCPServer):
    # SO_REUSEADDR: a restarted broker must be able to rebind its port
    # while old connections sit in TIME_WAIT (the reconnect story
    # depends on it)
    allow_reuse_address = True
    daemon_threads = True


class TcpTransport(Transport):
    """Client side of the socket broker; ``serve()`` starts the broker
    (an InProcessTransport behind a threaded TCP server).

    ``reconnect=True`` (default) makes ``publish``/``poll`` retry over a
    fresh connection when the peer drops mid-exchange: up to
    ``max_retries`` attempts with exponential backoff
    ``backoff_base_s * 2**attempt`` capped at ``backoff_max_s``. A
    publish retried after a send-side failure may be delivered twice
    (at-least-once, like any reconnecting producer); polls are
    idempotent. Retries exhausted -> the original error propagates."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 reconnect: bool = True, max_retries: int = 5,
                 backoff_base_s: float = 0.05,
                 backoff_max_s: float = 2.0, registry=None):
        self.host = host
        self.port = port
        self.reconnect = bool(reconnect)
        self.max_retries = int(max_retries)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.reconnects = 0
        self._sock: Optional[socket.socket] = None
        self._server = None
        self._lock = threading.Lock()
        # chaos faults here surface as ConnectionError so the reconnect
        # machinery under test treats them exactly like a dropped peer
        self._chaos_pub = chaos_site("broker.publish")
        self._chaos_poll = chaos_site("broker.poll")
        from deeplearning4j_tpu.observe.registry import default_registry
        reg = registry if registry is not None else default_registry()
        self._c_reconnects = reg.counter(
            "dl4j_stream_reconnects_total",
            "streaming transport reconnect attempts after a dropped/"
            "failed broker connection, by endpoint and operation")

    def serve(self) -> "TcpTransport":
        srv = _BrokerServer((self.host, self.port), _FrameHandler)
        srv.broker = InProcessTransport()  # type: ignore
        self.port = srv.server_address[1]
        self._server = srv
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return self

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection((self.host, self.port),
                                                  timeout=10)
        return self._sock

    def _drop_conn(self):
        """Tear down the (possibly desynced) connection so the next
        attempt starts from a clean frame boundary."""
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _with_retry(self, op: str, fn):
        """Run ``fn`` holding the connection lock; on a transport error
        drop the connection and retry with bounded exponential backoff.
        The lock is held across the whole retry loop so interleaved
        callers can never split a frame."""
        with self._lock:
            attempt = 0
            while True:
                try:
                    return fn()
                except (ConnectionError, OSError) as e:
                    self._drop_conn()
                    if not self.reconnect or attempt >= self.max_retries:
                        raise ConnectionError(
                            f"broker {self.host}:{self.port} {op} failed "
                            f"after {attempt} reconnect attempt(s): {e}"
                        ) from e
                    delay = min(self.backoff_max_s,
                                self.backoff_base_s * (2 ** attempt))
                    attempt += 1
                    self.reconnects += 1
                    self._c_reconnects.inc(
                        1.0, endpoint=f"{self.host}:{self.port}", op=op)
                    time.sleep(delay)

    def publish(self, topic: str, payload: bytes) -> None:
        tb = topic.encode("utf-8")
        frame = struct.pack("<BII", 0, len(tb), len(payload)) + tb + payload

        def send():
            if self._chaos_pub is not None:
                self._chaos_pub.fail(arg=topic, raise_as=ConnectionError)
            self._conn().sendall(frame)
        self._with_retry("publish", send)

    def poll(self, topic: str, timeout: float = 1.0) -> Optional[bytes]:
        tb = topic.encode("utf-8")

        def exchange():
            if self._chaos_poll is not None:
                self._chaos_poll.fail(arg=topic,
                                      raise_as=ConnectionError)
            s = self._conn()
            # socket deadline must outlast the server-side poll wait, or a
            # mid-exchange timeout desyncs the framed protocol
            s.settimeout(timeout + 10)
            s.sendall(struct.pack("<BII", 1, len(tb),
                                  int(timeout * 1000)) + tb)
            hdr = self._recv_exact(s, 4)
            (plen,) = struct.unpack("<I", hdr)
            if plen == 0:
                return None
            return self._recv_exact(s, plen)
        return self._with_retry("poll", exchange)

    @staticmethod
    def _recv_exact(s: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = s.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("broker closed connection")
            buf += chunk
        return buf

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None
        if self._server is not None:
            self._server.shutdown()
            # release the listening socket too, so a restarted broker
            # can rebind the same port immediately
            self._server.server_close()
            self._server = None


class NDArrayPublisher:
    """Pushes arrays to a topic (reference: NDArrayPublisher)."""

    def __init__(self, transport: Transport, topic: str):
        self.transport = transport
        self.topic = topic

    def publish(self, array: np.ndarray, key: str = "") -> None:
        self.transport.publish(
            self.topic, NDArrayMessage(np.asarray(array), key).to_bytes())


class NDArrayConsumer:
    """Pulls arrays from a topic (reference: NDArrayConsumer)."""

    def __init__(self, transport: Transport, topic: str):
        self.transport = transport
        self.topic = topic

    def poll(self, timeout: float = 1.0) -> Optional[NDArrayMessage]:
        payload = self.transport.poll(self.topic, timeout)
        return None if payload is None else NDArrayMessage.from_bytes(payload)

    def poll_batch(self, n: int, timeout: float = 1.0
                   ) -> List[NDArrayMessage]:
        out = []
        for _ in range(n):
            msg = self.poll(timeout)
            if msg is None:
                break
            out.append(msg)
        return out


class NDArrayStreamingClient:
    """Facade bundling both directions on one transport (reference:
    NDArrayKafkaClient)."""

    def __init__(self, transport: Optional[Transport] = None):
        self.transport = transport or InProcessTransport()

    def publisher(self, topic: str) -> NDArrayPublisher:
        return NDArrayPublisher(self.transport, topic)

    def consumer(self, topic: str) -> NDArrayConsumer:
        return NDArrayConsumer(self.transport, topic)
