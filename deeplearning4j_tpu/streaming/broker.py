"""Transports + publisher/consumer client.

Analog of ``NDArrayKafkaClient`` (dl4j-streaming, SURVEY §2.11) with the
broker abstracted: ``InProcessTransport`` (queue per topic — the test/
single-host path, like the reference's Camel direct: routes) and
``TcpTransport`` (length-prefixed frames over a socket — cross-process).
A Kafka/PubSub transport is the same interface against a real broker.
"""

from __future__ import annotations

import queue
import socket
import socketserver
import struct
import threading
from collections import defaultdict
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.streaming.serde import NDArrayMessage


class Transport:
    """publish/poll on named topics."""

    def publish(self, topic: str, payload: bytes) -> None:
        raise NotImplementedError

    def poll(self, topic: str, timeout: float = 1.0) -> Optional[bytes]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InProcessTransport(Transport):
    """Thread-safe per-topic queues; every subscriber pool shares one
    stream (competing consumers, like one Kafka consumer group)."""

    def __init__(self, max_queue: int = 1024):
        self._queues: Dict[str, queue.Queue] = defaultdict(
            lambda: queue.Queue(maxsize=max_queue))
        self._lock = threading.Lock()

    def _q(self, topic: str) -> queue.Queue:
        with self._lock:
            return self._queues[topic]

    def publish(self, topic: str, payload: bytes) -> None:
        self._q(topic).put(payload)

    def poll(self, topic: str, timeout: float = 1.0) -> Optional[bytes]:
        try:
            return self._q(topic).get(timeout=timeout)
        except queue.Empty:
            return None


class _FrameHandler(socketserver.BaseRequestHandler):
    def handle(self):
        broker: InProcessTransport = self.server.broker  # type: ignore
        try:
            while True:
                hdr = self._recv_exact(9)
                if hdr is None:
                    return
                op, tlen, plen = struct.unpack("<BII", hdr)
                tbytes = self._recv_exact(tlen)
                if tbytes is None:
                    return
                topic = tbytes.decode("utf-8")
                if op == 0:  # publish
                    payload = self._recv_exact(plen)
                    if payload is None:
                        return
                    broker.publish(topic, payload)
                elif op == 1:  # poll
                    payload = broker.poll(topic, timeout=float(plen) / 1000)
                    body = payload or b""
                    self.request.sendall(
                        struct.pack("<I", len(body)) + body)
        except (ConnectionError, OSError):
            return

    def _recv_exact(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = self.request.recv(n - len(buf))
            if not chunk:
                return None  # disconnect (mid-frame partials discarded)
            buf += chunk
        return buf


class TcpTransport(Transport):
    """Client side of the socket broker; ``serve()`` starts the broker
    (an InProcessTransport behind a threaded TCP server)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._sock: Optional[socket.socket] = None
        self._server = None
        self._lock = threading.Lock()

    def serve(self) -> "TcpTransport":
        srv = socketserver.ThreadingTCPServer(
            (self.host, self.port), _FrameHandler)
        srv.daemon_threads = True
        srv.broker = InProcessTransport()  # type: ignore
        self.port = srv.server_address[1]
        self._server = srv
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return self

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection((self.host, self.port),
                                                  timeout=10)
        return self._sock

    def publish(self, topic: str, payload: bytes) -> None:
        tb = topic.encode("utf-8")
        with self._lock:
            self._conn().sendall(
                struct.pack("<BII", 0, len(tb), len(payload)) + tb + payload)

    def poll(self, topic: str, timeout: float = 1.0) -> Optional[bytes]:
        tb = topic.encode("utf-8")
        with self._lock:
            s = self._conn()
            # socket deadline must outlast the server-side poll wait, or a
            # mid-exchange timeout desyncs the framed protocol
            s.settimeout(timeout + 10)
            s.sendall(struct.pack("<BII", 1, len(tb),
                                  int(timeout * 1000)) + tb)
            hdr = self._recv_exact(s, 4)
            (plen,) = struct.unpack("<I", hdr)
            if plen == 0:
                return None
            return self._recv_exact(s, plen)

    @staticmethod
    def _recv_exact(s: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = s.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("broker closed connection")
            buf += chunk
        return buf

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None
        if self._server is not None:
            self._server.shutdown()
            self._server = None


class NDArrayPublisher:
    """Pushes arrays to a topic (reference: NDArrayPublisher)."""

    def __init__(self, transport: Transport, topic: str):
        self.transport = transport
        self.topic = topic

    def publish(self, array: np.ndarray, key: str = "") -> None:
        self.transport.publish(
            self.topic, NDArrayMessage(np.asarray(array), key).to_bytes())


class NDArrayConsumer:
    """Pulls arrays from a topic (reference: NDArrayConsumer)."""

    def __init__(self, transport: Transport, topic: str):
        self.transport = transport
        self.topic = topic

    def poll(self, timeout: float = 1.0) -> Optional[NDArrayMessage]:
        payload = self.transport.poll(self.topic, timeout)
        return None if payload is None else NDArrayMessage.from_bytes(payload)

    def poll_batch(self, n: int, timeout: float = 1.0
                   ) -> List[NDArrayMessage]:
        out = []
        for _ in range(n):
            msg = self.poll(timeout)
            if msg is None:
                break
            out.append(msg)
        return out


class NDArrayStreamingClient:
    """Facade bundling both directions on one transport (reference:
    NDArrayKafkaClient)."""

    def __init__(self, transport: Optional[Transport] = None):
        self.transport = transport or InProcessTransport()

    def publisher(self, topic: str) -> NDArrayPublisher:
        return NDArrayPublisher(self.transport, topic)

    def consumer(self, topic: str) -> NDArrayConsumer:
        return NDArrayConsumer(self.transport, topic)
