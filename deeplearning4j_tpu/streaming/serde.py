"""NDArray wire format.

Analog of the reference's NDArray-to-Kafka serialization
(``dl4j-streaming/.../streaming/serde/`` + the Aeron ``NDArrayMessage``
format in nd4j): a compact self-describing binary frame —
magic, dtype, rank, shape, raw little-endian data — plus optional
metadata (timestamp, origin id). No pickle: frames are safe to parse
from untrusted peers (bounded rank/size checks)."""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass, field
from typing import Optional, Tuple

import numpy as np

_MAGIC = b"DL4JTPU1"
_MAX_RANK = 16
_MAX_BYTES = 1 << 33  # 8 GiB sanity cap

_DTYPES = ["float32", "float64", "float16", "bfloat16", "int8", "int16",
           "int32", "int64", "uint8", "bool"]


def serialize_ndarray(arr: np.ndarray, timestamp_ns: Optional[int] = None
                      ) -> bytes:
    """array → frame bytes."""
    arr = np.ascontiguousarray(arr)
    name = str(arr.dtype)
    if name not in _DTYPES:
        raise ValueError(f"unsupported dtype {name}")
    ts = time.time_ns() if timestamp_ns is None else timestamp_ns
    header = struct.pack(
        "<8sBBq", _MAGIC, _DTYPES.index(name), arr.ndim, ts)
    shape = struct.pack(f"<{arr.ndim}q", *arr.shape)
    return header + shape + arr.tobytes()


def deserialize_ndarray(data: bytes) -> Tuple[np.ndarray, int]:
    """frame bytes → (array, timestamp_ns). Validates bounds before
    allocating; truncated/corrupt frames always raise ValueError."""
    hsize = struct.calcsize("<8sBBq")
    try:
        magic, dt_idx, rank, ts = struct.unpack_from("<8sBBq", data)
    except struct.error as e:
        raise ValueError(f"truncated frame header: {e}") from e
    if magic != _MAGIC:
        raise ValueError("bad magic; not an NDArray frame")
    if dt_idx >= len(_DTYPES) or rank > _MAX_RANK:
        raise ValueError("corrupt frame header")
    try:
        shape = struct.unpack_from(f"<{rank}q", data, hsize)
    except struct.error as e:
        raise ValueError(f"truncated shape block: {e}") from e
    if any(d < 0 for d in shape):
        raise ValueError("negative dimension")
    dtype = np.dtype(_DTYPES[dt_idx]) if _DTYPES[dt_idx] != "bfloat16" \
        else np.dtype("uint16")  # bf16 carried as raw 16-bit payload
    nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    if nbytes > _MAX_BYTES:
        raise ValueError("frame exceeds size cap")
    off = hsize + rank * 8
    if len(data) - off < nbytes:
        raise ValueError(f"truncated payload: need {nbytes} bytes, "
                         f"have {len(data) - off}")
    arr = np.frombuffer(data, dtype, count=nbytes // dtype.itemsize,
                        offset=off).reshape(shape)
    return arr, ts


@dataclass
class NDArrayMessage:
    """A keyed array record on a topic (reference: NDArrayMessage)."""

    array: np.ndarray
    key: str = ""
    timestamp_ns: int = field(default_factory=time.time_ns)

    def to_bytes(self) -> bytes:
        kb = self.key.encode("utf-8")
        return (struct.pack("<I", len(kb)) + kb +
                serialize_ndarray(self.array, self.timestamp_ns))

    @classmethod
    def from_bytes(cls, data: bytes) -> "NDArrayMessage":
        try:
            (klen,) = struct.unpack_from("<I", data)
        except struct.error as e:
            raise ValueError(f"truncated message: {e}") from e
        if len(data) < 4 + klen:
            raise ValueError("truncated message key")
        key = data[4:4 + klen].decode("utf-8")
        arr, ts = deserialize_ndarray(data[4 + klen:])
        return cls(arr, key, ts)
