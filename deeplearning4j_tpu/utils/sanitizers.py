"""Runtime sanitizers — the TPU analog of the reference's workspace
scope panics (SURVEY §5.2).

The reference's ND4J workspaces crash loudly (``SCOPE_PANIC``) when a
buffer is used outside its workspace scope or leaks across iterations.
The JAX/XLA failure modes that correspond:

- **silent host↔device transfers** — a stray ``np.asarray`` / implicit
  convert inside a training loop stalls the device exactly like a
  workspace spill. ``no_implicit_transfers()`` turns those into errors
  via jax's transfer guard.
- **donated-buffer reuse** — a donated ``TrainState`` (every train step
  here donates) must never be touched again; reuse raises by default
  but only at dispatch time. ``check_not_donated()`` asserts eagerly at
  the API boundary for a clear error.

Use in tests and tight loops:

    with no_implicit_transfers():
        ts, loss = step(ts, batch)          # device-resident or it raises

    check_not_donated(model.train_state)    # SCOPE_PANIC-style assert
"""

from __future__ import annotations

import contextlib
from typing import Any, Iterator

import jax


@contextlib.contextmanager
def no_implicit_transfers(level: str = "disallow") -> Iterator[None]:
    """Error on implicit host↔device transfers inside the scope.

    ``level``: "disallow" (raise), "log" (warn), or "allow".
    Explicit transfers (``jax.device_put`` / ``jax.device_get``) stay
    legal — only *implicit* conversions are flagged, which is exactly
    the workspace-scope-leak class of bug."""
    with jax.transfer_guard(level):
        yield


def is_deleted(x: Any) -> bool:
    """True if ``x`` is a jax array whose buffer was donated/deleted."""
    try:
        return hasattr(x, "is_deleted") and x.is_deleted()
    except Exception:
        return False


def check_not_donated(tree: Any, what: str = "buffer") -> None:
    """Raise immediately (not at next dispatch) if any leaf of ``tree``
    was donated — the reference's scope panic, eagerly."""
    bad = [
        "/".join(str(getattr(p, "key", p)) for p in path)
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
        if is_deleted(leaf)
    ]
    if bad:
        raise RuntimeError(
            f"SCOPE_PANIC: {what} uses {len(bad)} donated/deleted "
            f"buffer(s), first: {bad[0]!r}. A train step donated this "
            "pytree; use the returned TrainState instead of the stale "
            "reference.")
