"""JSON serialization registry for configuration dataclasses.

The reference serializes typed builder configs to JSON/YAML with polymorphic
subtype discovery via classpath scanning (reference:
deeplearning4j-nn/.../nn/conf/NeuralNetConfiguration.java:434,472-574).
Here the equivalent is an explicit registry: every config dataclass registers
under a stable type name, and nested configs round-trip through ``to_dict`` /
``from_dict`` with an ``@type`` discriminator key. Custom user layers call
``register_serializable`` exactly like DL4J's ``registerSubtypes``.
"""

from __future__ import annotations

import dataclasses
import enum
import json
from typing import Any, Dict, Type

_REGISTRY: Dict[str, Type] = {}
_TYPE_KEY = "@type"


def register_serializable(cls=None, *, name: str | None = None):
    """Class decorator: register a dataclass for polymorphic JSON serde."""

    def wrap(c):
        key = name or c.__name__
        if key in _REGISTRY and _REGISTRY[key] is not c:
            raise ValueError(f"serde type name already registered: {key}")
        _REGISTRY[key] = c
        c._serde_name = key
        return c

    if cls is None:
        return wrap
    return wrap(cls)


def registered_types() -> Dict[str, Type]:
    return dict(_REGISTRY)


def to_dict(obj: Any) -> Any:
    """Recursively convert registered dataclasses to JSON-safe dicts."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, enum.Enum):
        return obj.name
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items()}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        name = getattr(obj, "_serde_name", None)
        if name is None:
            raise TypeError(
                f"{type(obj).__name__} is not registered for serde; "
                "decorate it with @register_serializable"
            )
        out = {_TYPE_KEY: name}
        for f in dataclasses.fields(obj):
            if not f.metadata.get("serde_skip", False):
                out[f.name] = to_dict(getattr(obj, f.name))
        return out
    raise TypeError(f"cannot serialize {type(obj).__name__}")


def from_dict(data: Any) -> Any:
    """Inverse of :func:`to_dict`; resolves ``@type`` via the registry."""
    if isinstance(data, list):
        return [from_dict(v) for v in data]
    if isinstance(data, dict):
        if _TYPE_KEY in data:
            name = data[_TYPE_KEY]
            cls = _REGISTRY.get(name)
            if cls is None:
                raise KeyError(f"unknown serde type: {name}")
            fields = {f.name: f for f in dataclasses.fields(cls)}
            kwargs = {}
            for k, v in data.items():
                if k == _TYPE_KEY or k not in fields:
                    continue
                f = fields[k]
                val = from_dict(v)
                # Re-hydrate enums declared by annotation.
                val = _coerce(f.type, val)
                kwargs[k] = val
            return cls(**kwargs)
        return {k: from_dict(v) for k, v in data.items()}
    return data


def _base_name(annotation) -> str:
    """'Optional[L.LossFunction]' → 'LossFunction'; 'Tuple[int, int]' →
    'Tuple'. Handles string annotations (from __future__ annotations)."""
    if not isinstance(annotation, str):
        annotation = getattr(annotation, "__name__", str(annotation))
    s = annotation.strip().strip('"\'')
    for wrapper in ("Optional[", "typing.Optional["):
        if s.startswith(wrapper) and s.endswith("]"):
            s = s[len(wrapper):-1].strip()
    s = s.split("[")[0].strip()
    return s.split(".")[-1]


def _coerce(annotation, val):
    """Best-effort coercion of primitives back to enums / tuples."""
    base = _base_name(annotation)
    if isinstance(val, str):
        cls = _ENUM_REGISTRY.get(base)
        if cls is not None and val in cls.__members__:
            return cls[val]
    if isinstance(val, list):
        if base in ("tuple", "Tuple"):
            return tuple(val)
    return val


_ENUM_REGISTRY: Dict[str, Type[enum.Enum]] = {}


def register_enum(cls: Type[enum.Enum]):
    """Register an enum so string values re-hydrate on deserialization."""
    _ENUM_REGISTRY[cls.__name__] = cls
    return cls


def to_json(obj: Any, *, indent: int | None = 2) -> str:
    return json.dumps(to_dict(obj), indent=indent)


def from_json(s: str) -> Any:
    return from_dict(json.loads(s))
