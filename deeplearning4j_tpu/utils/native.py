"""ctypes loader for the native host runtime (native/dl4j_native.cpp).

The reference reaches native code through JavaCPP/JNI (libnd4j ops,
ThresholdCompression, DataVec readers — SURVEY §2.14); here the host-side
hot loops live in one small C++ library bound via ctypes. Everything has
a numpy fallback, so the framework works without a toolchain — the native
path is a speedup, not a dependency (the reference's helper-fallback
philosophy, ConvolutionLayer.java:173).

Build is on demand and cached: first use runs ``make`` in native/ if the
shared object is missing and a compiler is present.
"""

from __future__ import annotations

import ctypes
import logging
import os
import shutil
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_log = logging.getLogger(__name__)
_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libdl4j_native.so")

_lib = None
_lib_lock = threading.Lock()
_load_failed = False


def _try_build() -> bool:
    if not shutil.which("make") and not shutil.which("g++"):
        return False
    try:
        subprocess.run(["make", "-s"], cwd=_NATIVE_DIR, check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_SO_PATH)
    except Exception as e:   # noqa: BLE001 — build is best-effort
        _log.warning("native build failed, using numpy fallback: %s", e)
        return False


def _disabled() -> bool:
    """DL4J_NATIVE=0 is the kill switch: every wrapper reports the
    library unavailable, so callers take their mandatory numpy
    fallback. Checked on every call (not cached) so tests and
    operators can flip it mid-process."""
    return os.environ.get("DL4J_NATIVE", "").strip() == "0"


def _stale() -> bool:
    """True when the shared object predates its source — a stale
    binary would silently miss newly added entry points."""
    src = os.path.join(_NATIVE_DIR, "dl4j_native.cpp")
    try:
        return os.path.getmtime(_SO_PATH) < os.path.getmtime(src)
    except OSError:
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded library, building it on first use; None if unavailable
    or killed via DL4J_NATIVE=0."""
    global _lib, _load_failed
    if _disabled():
        return None
    if _lib is not None or _load_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        if (not os.path.exists(_SO_PATH) or _stale()) and not _try_build():
            if not os.path.exists(_SO_PATH):
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as e:
            _log.warning("could not load %s: %s", _SO_PATH, e)
            _load_failed = True
            return None
        i64, i32p, i8p, f32p, u8p, cp = (
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int8), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_char_p)
        lib.dl4j_encode.argtypes = [i8p, i64, i32p]
        lib.dl4j_encode.restype = i64
        lib.dl4j_encode_flexible.argtypes = [i8p, i64, i32p]
        lib.dl4j_encode_flexible.restype = i64
        lib.dl4j_encode_bitmap.argtypes = [i8p, i64, i32p]
        lib.dl4j_encode_bitmap.restype = i64
        lib.dl4j_decode.argtypes = [i32p, i64, i8p, i64]
        lib.dl4j_decode.restype = i64
        lib.dl4j_decode_axpy.argtypes = [i32p, i64, ctypes.c_float, f32p,
                                         i64]
        lib.dl4j_decode_axpy.restype = i64
        lib.dl4j_csv_dims.argtypes = [cp, i64, ctypes.c_char,
                                      ctypes.POINTER(i64)]
        lib.dl4j_csv_dims.restype = i64
        lib.dl4j_csv_parse.argtypes = [cp, i64, ctypes.c_char, f32p, i64,
                                       i64]
        lib.dl4j_csv_parse.restype = i64
        lib.dl4j_idx_decode.argtypes = [u8p, i64, f32p, i64,
                                        ctypes.POINTER(i64),
                                        ctypes.POINTER(i64)]
        lib.dl4j_idx_decode.restype = i64
        # pairgen entry points are newer than the codec: a stale
        # prebuilt .so without them still serves the codec paths,
        # pairgen_available() just reports False
        if hasattr(lib, "dl4j_pairgen_walk"):
            u64, u8pp, i32 = (ctypes.c_uint64,
                              ctypes.POINTER(ctypes.c_uint8),
                              ctypes.c_int32)
            u64p = ctypes.POINTER(u64)
            lib.dl4j_sm64_fill.argtypes = [u64, i64, i64, u64p]
            lib.dl4j_sm64_fill.restype = None
            f64p = ctypes.POINTER(ctypes.c_double)
            lib.dl4j_pairgen_subsample.argtypes = [i32p, i64, f64p, u64,
                                                   u8pp]
            lib.dl4j_pairgen_subsample.restype = i64
            lib.dl4j_pairgen_negatives.argtypes = [
                i32p, i64, i32p, i64, i32, i32, u64, u64, i64, i32p]
            lib.dl4j_pairgen_negatives.restype = None
            lib.dl4j_pairgen_walk.argtypes = [
                i32p, i32p, i32p, i64, i64, i32, u64, i32p, i64, i32,
                i32, u64, u64, i64, i32p, i32p, i32p]
            lib.dl4j_pairgen_walk.restype = i64
            lib.dl4j_pairgen_walk_cbow.argtypes = [
                i32p, i32p, i32p, i64, i64, i64, i32, u64, i32p, i64,
                i32, i32, u64, u64, i64, i32p, f32p, i32p, i32p]
            lib.dl4j_pairgen_walk_cbow.restype = i64
        _lib = lib
    return _lib


def available() -> bool:
    return get_lib() is not None


def _i8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int8))


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


# -------------------------------------------------------------------------
# Threshold codec
# -------------------------------------------------------------------------

def encode(signs: np.ndarray) -> Optional[np.ndarray]:
    """Native auto-codec encode; None when the library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    signs = np.ascontiguousarray(signs.reshape(-1), np.int8)
    out = np.empty(3 + signs.size, np.int32)
    n = lib.dl4j_encode(_i8p(signs), signs.size, _i32p(out))
    return out[:n].copy()


def decode(message: np.ndarray) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    msg = np.ascontiguousarray(message, np.int32)
    length = int(msg[1])
    out = np.zeros(length, np.int8)
    n = lib.dl4j_decode(_i32p(msg), msg.size, _i8p(out), length)
    if n < 0:
        raise ValueError("malformed threshold-codec message")
    return out


def decode_axpy(message: np.ndarray, threshold: float,
                acc: np.ndarray) -> bool:
    """acc += decode(message) * threshold, fused. False if no native lib."""
    lib = get_lib()
    if lib is None:
        return False
    msg = np.ascontiguousarray(message, np.int32)
    assert acc.dtype == np.float32 and acc.flags.c_contiguous
    n = lib.dl4j_decode_axpy(_i32p(msg), msg.size,
                             ctypes.c_float(threshold), _f32p(acc),
                             acc.size)
    if n < 0:
        raise ValueError("malformed threshold-codec message")
    return True


# -------------------------------------------------------------------------
# Record readers
# -------------------------------------------------------------------------

def parse_csv(text: bytes | str, delimiter: str = ",") \
        -> Optional[np.ndarray]:
    """Numeric CSV -> float32 matrix via the native parser; None if the
    library is unavailable (caller falls back to numpy)."""
    lib = get_lib()
    if lib is None:
        return None
    data = text.encode() if isinstance(text, str) else bytes(text)
    ncols = ctypes.c_int64(0)
    rows = lib.dl4j_csv_dims(data, len(data), delimiter.encode(),
                             ctypes.byref(ncols))
    if rows <= 0 or ncols.value <= 0:
        return np.zeros((0, 0), np.float32)
    out = np.empty((rows, ncols.value), np.float32)
    got = lib.dl4j_csv_parse(data, len(data), delimiter.encode(),
                             _f32p(out), rows, ncols.value)
    if got < 0:
        raise ValueError("ragged or non-numeric CSV")
    return out[:got]


def decode_idx(raw: bytes) -> Optional[Tuple[np.ndarray, Tuple[int, ...]]]:
    """IDX (MNIST) u8 container -> (float32 array scaled to [0,1], dims)."""
    lib = get_lib()
    if lib is None:
        return None
    buf = np.frombuffer(raw, np.uint8)
    if buf.size < 4:
        raise ValueError("truncated IDX file")
    # payload bound: total elements <= len(raw)
    out = np.empty(buf.size, np.float32)
    dims = np.zeros(4, np.int64)
    ndims = ctypes.c_int64(0)
    n = lib.dl4j_idx_decode(
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), buf.size,
        _f32p(out), out.size, dims.ctypes.data_as(
            ctypes.POINTER(ctypes.c_int64)), ctypes.byref(ndims))
    if n < 0:
        raise ValueError("malformed IDX file")
    shape = tuple(int(d) for d in dims[:ndims.value])
    return out[:n].reshape(shape), shape


# -------------------------------------------------------------------------
# Fused pair generation (the Word2Vec/ParagraphVectors host producer).
# Thin ctypes shims — the walk semantics and the bitwise-identical numpy
# fallback live in deeplearning4j_tpu/nlp/pairgen.py.
# -------------------------------------------------------------------------

def pairgen_available() -> bool:
    """True when the loaded library carries the pairgen entry points
    (a stale .so without them still serves the codec)."""
    lib = get_lib()
    return lib is not None and hasattr(lib, "dl4j_pairgen_walk")


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def sm64_fill(seed: int, start: int, n: int) -> Optional[np.ndarray]:
    """Raw counter-based splitmix64 draws (parity probe)."""
    if not pairgen_available():
        return None
    out = np.empty(n, np.uint64)
    get_lib().dl4j_sm64_fill(
        ctypes.c_uint64(seed), start, n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
    return out


def pairgen_subsample(ids: np.ndarray, keep_p: np.ndarray,
                      seed: int) -> Optional[np.ndarray]:
    """Boolean keep mask for the flat corpus; None without the lib."""
    if not pairgen_available():
        return None
    ids = np.ascontiguousarray(ids, np.int32)
    keep_p = np.ascontiguousarray(keep_p, np.float64)
    out = np.empty(len(ids), np.uint8)
    get_lib().dl4j_pairgen_subsample(
        _i32p(ids), len(ids),
        keep_p.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.c_uint64(seed), _u8p(out))
    return out.view(bool)


def pairgen_negatives(table: np.ndarray, positive: np.ndarray,
                      n_neg: int, n_words: int, nseed: int, n2seed: int,
                      pair_base: int) -> Optional[np.ndarray]:
    """(n, n_neg) fused negative-table draws; None without the lib."""
    if not pairgen_available() or n_neg <= 0:
        return None
    positive = np.ascontiguousarray(positive, np.int32)
    out = np.empty((len(positive), n_neg), np.int32)
    get_lib().dl4j_pairgen_negatives(
        _i32p(table), len(table), _i32p(positive), len(positive),
        n_neg, n_words, ctypes.c_uint64(nseed), ctypes.c_uint64(n2seed),
        pair_base, _i32p(out))
    return out


def pairgen_walk(ids: np.ndarray, pos: np.ndarray, length: np.ndarray,
                 lo: int, hi: int, window: int, wseed: int,
                 table: Optional[np.ndarray], n_neg: int, n_words: int,
                 nseed: int, n2seed: int, pair_base: int,
                 out_center: np.ndarray, out_context: np.ndarray,
                 out_negs: Optional[np.ndarray]) -> Optional[int]:
    """Fused SGNS/HS/DBOW window walk into caller-owned slab buffers;
    returns the pair count, or None without the lib."""
    if not pairgen_available():
        return None
    tbl = table if table is not None else np.empty(1, np.int32)
    return get_lib().dl4j_pairgen_walk(
        _i32p(ids), _i32p(pos), _i32p(length), lo, hi, window,
        ctypes.c_uint64(wseed), _i32p(tbl), len(tbl), n_neg, n_words,
        ctypes.c_uint64(nseed), ctypes.c_uint64(n2seed), pair_base,
        _i32p(out_center), _i32p(out_context),
        _i32p(out_negs if out_negs is not None else out_center))


def pairgen_walk_cbow(ids: np.ndarray, pos: np.ndarray,
                      length: np.ndarray, lo: int, hi: int, window: int,
                      wseed: int, table: Optional[np.ndarray],
                      n_neg: int, n_words: int, nseed: int, n2seed: int,
                      row_base: int, out_ctx: np.ndarray,
                      out_cmask: np.ndarray, out_center: np.ndarray,
                      out_negs: Optional[np.ndarray]) -> Optional[int]:
    """Fused CBOW row walk into caller-owned slab buffers; returns the
    row count, or None without the lib."""
    if not pairgen_available():
        return None
    tbl = table if table is not None else np.empty(1, np.int32)
    return get_lib().dl4j_pairgen_walk_cbow(
        _i32p(ids), _i32p(pos), _i32p(length), len(ids), lo, hi, window,
        ctypes.c_uint64(wseed), _i32p(tbl), len(tbl), n_neg, n_words,
        ctypes.c_uint64(nseed), ctypes.c_uint64(n2seed), row_base,
        _i32p(out_ctx), _f32p(out_cmask), _i32p(out_center),
        _i32p(out_negs if out_negs is not None else out_center))
