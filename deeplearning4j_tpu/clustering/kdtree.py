"""KD-tree for low-dimensional exact nearest neighbors.

Analog of the reference's clustering/kdtree/KDTree.java (SURVEY §2.10).
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class _KDNode:
    __slots__ = ("index", "axis", "left", "right")

    def __init__(self, index: int, axis: int):
        self.index = index
        self.axis = axis
        self.left: Optional["_KDNode"] = None
        self.right: Optional["_KDNode"] = None


class KDTree:
    def __init__(self, points: np.ndarray):
        self.points = np.asarray(points, np.float64)  # host-sync-ok: legacy host tree holds host f64 rows by design
        self.dims = self.points.shape[1]
        self.root = self._build(list(range(len(self.points))), 0)

    def _build(self, idxs: List[int], depth: int) -> Optional[_KDNode]:
        if not idxs:
            return None
        axis = depth % self.dims
        idxs.sort(key=lambda i: self.points[i, axis])
        mid = len(idxs) // 2
        node = _KDNode(idxs[mid], axis)
        node.left = self._build(idxs[:mid], depth + 1)
        node.right = self._build(idxs[mid + 1:], depth + 1)
        return node

    def insert_point_index(self, idx: int):
        raise NotImplementedError(
            "rebuild the tree to add points (static index)")

    def knn(self, query: np.ndarray, k: int
            ) -> Tuple[List[int], List[float]]:
        q = np.asarray(query, np.float64)  # host-sync-ok: query decode at the host-tree input boundary
        heap: List[Tuple[float, int]] = []

        def visit(node: Optional[_KDNode]):
            if node is None:
                return
            d = float(np.linalg.norm(self.points[node.index] - q))  # host-sync-ok: host walk: distance on host rows
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.index))
            delta = q[node.axis] - self.points[node.index, node.axis]
            near, far = ((node.left, node.right) if delta < 0
                         else (node.right, node.left))
            visit(near)
            if len(heap) < k or abs(delta) < -heap[0][0]:
                visit(far)

        visit(self.root)
        out = sorted(((-nd, i) for nd, i in heap))
        return [i for _d, i in out], [d for d, _i in out]

    def nearest(self, query: np.ndarray) -> Tuple[int, float]:
        idxs, ds = self.knn(query, 1)
        return idxs[0], ds[0]
