"""Locality-sensitive hashing + random projection.

Analogs of the reference's clustering/lsh/ (RandomProjectionLSH.java) and
clustering/randomprojection/ (SURVEY §2.10): approximate cosine
neighbors via signed-random-projection bucket hashing.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Tuple

import numpy as np


class RandomProjectionLSH:
    """Sign-LSH over ``n_tables`` independent hash tables of ``n_bits``
    hyperplanes each; candidates are re-ranked exactly."""

    def __init__(self, n_bits: int = 16, n_tables: int = 4, seed: int = 0):
        self.n_bits = n_bits
        self.n_tables = n_tables
        self.seed = seed
        self._planes: List[np.ndarray] = []
        self._tables: List[Dict[int, List[int]]] = []
        self._data: np.ndarray = None

    def _hash(self, planes: np.ndarray, x: np.ndarray) -> np.ndarray:
        bits = (x @ planes.T) > 0
        return bits @ (1 << np.arange(self.n_bits))

    def index(self, data: np.ndarray):
        self._data = np.asarray(data, np.float64)  # host-sync-ok: host hash-table structure holds host rows by design
        d = self._data.shape[1]
        rng = np.random.default_rng(self.seed)
        self._planes = [rng.normal(size=(self.n_bits, d))
                        for _ in range(self.n_tables)]
        self._tables = []
        for planes in self._planes:
            table: Dict[int, List[int]] = defaultdict(list)
            keys = self._hash(planes, self._data)
            for i, key in enumerate(keys):
                table[int(key)].append(i)
            self._tables.append(table)
        return self

    def search(self, query: np.ndarray, k: int
               ) -> Tuple[List[int], List[float]]:
        q = np.asarray(query, np.float64)  # host-sync-ok: query decode at the host-structure input boundary
        cands = set()
        for planes, table in zip(self._planes, self._tables):
            key = int(self._hash(planes, q[None, :])[0])
            cands.update(table.get(key, ()))
        if not cands:
            cands = set(range(len(self._data)))
        idxs = np.fromiter(cands, int)
        sub = self._data[idxs]
        qn = q / max(np.linalg.norm(q), 1e-12)
        sn = sub / np.maximum(np.linalg.norm(sub, axis=1, keepdims=True),
                              1e-12)
        sims = sn @ qn
        order = np.argsort(-sims)[:k]
        return idxs[order].tolist(), (1.0 - sims[order]).tolist()


class RandomProjection:
    """Johnson-Lindenstrauss Gaussian projection to ``n_components``
    (reference: randomprojection/RandomProjection.java)."""

    def __init__(self, n_components: int, seed: int = 0):
        self.n_components = n_components
        self.seed = seed
        self._proj: np.ndarray = None

    def fit(self, data: np.ndarray) -> "RandomProjection":
        d = np.asarray(data).shape[1]  # host-sync-ok: build-time shape probe on host ingest
        rng = np.random.default_rng(self.seed)
        self._proj = rng.normal(
            size=(d, self.n_components)) / np.sqrt(self.n_components)
        return self

    def transform(self, data: np.ndarray) -> np.ndarray:
        return np.asarray(data) @ self._proj  # host-sync-ok: build-time host projection of ingest rows

    def fit_transform(self, data: np.ndarray) -> np.ndarray:
        return self.fit(data).transform(data)
