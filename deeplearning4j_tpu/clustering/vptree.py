"""Vantage-point tree for exact metric nearest-neighbor search.

Analog of the reference's clustering/vptree/VPTree.java:48 (SURVEY
§2.10; backs wordsNearest-style serving and t-SNE's input neighborhoods).
Host-side index; batched distance evaluations are vectorized numpy.
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class _Node:
    __slots__ = ("index", "threshold", "inside", "outside")

    def __init__(self, index: int):
        self.index = index
        self.threshold = 0.0
        self.inside: Optional["_Node"] = None
        self.outside: Optional["_Node"] = None


class VPTree:
    def __init__(self, points: np.ndarray, distance: str = "euclidean",
                 seed: int = 0):
        self.points = np.asarray(points, np.float64)  # host-sync-ok: legacy host tree holds host f64 rows by design
        if distance not in ("euclidean", "cosine"):
            raise ValueError(f"unsupported distance {distance!r}")
        self.distance = distance
        if self.distance == "cosine":
            norms = np.linalg.norm(self.points, axis=1, keepdims=True)
            self._unit = self.points / np.maximum(norms, 1e-12)
        self._rng = np.random.default_rng(seed)
        idxs = list(range(len(self.points)))
        self.root = self._build(idxs)

    def _dist(self, i: int, idxs: np.ndarray) -> np.ndarray:
        if self.distance == "cosine":
            return 1.0 - self._unit[idxs] @ self._unit[i]
        diff = self.points[idxs] - self.points[i]
        return np.sqrt(np.sum(diff * diff, axis=1))

    def _build(self, idxs: List[int]) -> Optional[_Node]:
        if not idxs:
            return None
        vp_pos = int(self._rng.integers(len(idxs)))
        vp = idxs.pop(vp_pos)
        node = _Node(vp)
        if idxs:
            arr = np.asarray(idxs)  # host-sync-ok: build-time index array on host rows
            d = self._dist(vp, arr)
            median = float(np.median(d))  # host-sync-ok: build-time median split scalar
            node.threshold = median
            inside = [i for i, di in zip(idxs, d) if di < median]
            outside = [i for i, di in zip(idxs, d) if di >= median]
            node.inside = self._build(inside)
            node.outside = self._build(outside)
        return node

    def _dist_to_query(self, q: np.ndarray, idx: int) -> float:
        if self.distance == "cosine":
            qn = q / max(np.linalg.norm(q), 1e-12)
            return float(1.0 - self._unit[idx] @ qn)  # host-sync-ok: host walk: distance on host rows
        return float(np.linalg.norm(self.points[idx] - q))  # host-sync-ok: host walk: distance on host rows

    def search(self, query: np.ndarray, k: int
               ) -> Tuple[List[int], List[float]]:
        """k nearest (indices, distances), best-first with pruning."""
        q = np.asarray(query, np.float64)  # host-sync-ok: query decode at the host-tree input boundary
        heap: List[Tuple[float, int]] = []   # max-heap via negated dist
        tau = [np.inf]

        def visit(node: Optional[_Node]):
            if node is None:
                return
            d = self._dist_to_query(q, node.index)
            if d < tau[0] or len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
                if len(heap) > k:
                    heapq.heappop(heap)
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            if node.inside is None and node.outside is None:
                return
            if d < node.threshold:
                visit(node.inside)
                if d + tau[0] >= node.threshold:
                    visit(node.outside)
            else:
                visit(node.outside)
                if d - tau[0] <= node.threshold:
                    visit(node.inside)

        visit(self.root)
        out = sorted(((-nd, i) for nd, i in heap))
        return [i for _d, i in out], [d for d, _i in out]
