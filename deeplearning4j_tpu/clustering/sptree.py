"""SPTree (generalized quadtree/octree) with Barnes-Hut accumulation.

Analog of the reference's clustering/sptree/SpTree.java (SURVEY §2.10),
the spatial index behind BarnesHutTsne. Center-of-mass cells let the
repulsive-force sum be approximated in O(N log N) on host.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class _Cell:
    __slots__ = ("center", "width", "n", "com", "point_index", "children",
                 "is_leaf")

    def __init__(self, center: np.ndarray, width: np.ndarray):
        self.center = center
        self.width = width
        self.n = 0                       # points in subtree
        self.com = np.zeros_like(center)  # center of mass
        self.point_index: Optional[int] = None
        self.children: Optional[List["_Cell"]] = None
        self.is_leaf = True


class SpTree:
    def __init__(self, points: np.ndarray):
        self.points = np.asarray(points, np.float64)  # host-sync-ok: legacy host tree holds host f64 rows by design
        lo = self.points.min(0)
        hi = self.points.max(0)
        center = (lo + hi) / 2
        width = np.maximum(hi - lo, 1e-10) * 0.5 + 1e-6
        self.d = self.points.shape[1]
        self.root = _Cell(center, width)
        for i in range(len(self.points)):
            self._insert(self.root, i)

    def _insert(self, cell: _Cell, idx: int, depth: int = 0):
        p = self.points[idx]
        cell.com = (cell.com * cell.n + p) / (cell.n + 1)
        cell.n += 1
        if cell.is_leaf and cell.point_index is None:
            cell.point_index = idx
            return
        if cell.is_leaf:
            # duplicate-point guard (reference caps subdivision depth)
            if depth > 48 or np.allclose(
                    self.points[cell.point_index], p, atol=1e-12):
                return
            self._subdivide(cell)
            old = cell.point_index
            cell.point_index = None
            self._insert(self._child_for(cell, self.points[old]), old,
                         depth + 1)
        self._insert(self._child_for(cell, p), idx, depth + 1)

    def _subdivide(self, cell: _Cell):
        cell.is_leaf = False
        cell.children = []
        for mask in range(1 << self.d):
            offs = np.array([(1 if mask >> j & 1 else -1)
                             for j in range(self.d)], np.float64)
            c = _Cell(cell.center + offs * cell.width / 2, cell.width / 2)
            cell.children.append(c)

    def _child_for(self, cell: _Cell, p: np.ndarray) -> _Cell:
        mask = 0
        for j in range(self.d):
            if p[j] > cell.center[j]:
                mask |= 1 << j
        return cell.children[mask]

    def compute_non_edge_forces(self, idx: int, theta: float
                                ) -> tuple:
        """Barnes-Hut negative-force accumulation for point ``idx``
        (reference: SpTree.computeNonEdgeForces): returns (neg_f, sum_q)
        using the t-SNE q_ij = 1/(1+||y_i-y_j||²) kernel."""
        p = self.points[idx]
        neg = np.zeros(self.d)
        sum_q = 0.0

        def visit(cell: _Cell):
            nonlocal sum_q, neg
            if cell.n == 0 or (cell.is_leaf and cell.point_index == idx
                               and cell.n == 1):
                return
            diff = p - cell.com
            d2 = float(diff @ diff)  # host-sync-ok: host walk scalar (Barnes-Hut criterion)
            max_w = float(cell.width.max() * 2)  # host-sync-ok: host walk scalar (Barnes-Hut criterion)
            if cell.is_leaf or (d2 > 0 and max_w / np.sqrt(d2) < theta):
                cnt = cell.n - (1 if (cell.is_leaf and
                                      cell.point_index == idx) else 0)
                if cnt <= 0:
                    return
                q = 1.0 / (1.0 + d2)
                sum_q += cnt * q
                neg += cnt * q * q * diff
                return
            for ch in cell.children or ():
                visit(ch)

        visit(self.root)
        return neg, sum_q
