"""KMeans on device.

Analog of the reference's clustering/kmeans/KMeansClustering.java (SURVEY
§2.10). TPU-first: each Lloyd iteration is one jitted step — the N×K
distance matrix is a single matmul (MXU), assignment is an argmin, and
the centroid update is a one-hot-matmul segment-sum. The reference's
thread-pool over points becomes data parallelism inside XLA.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _assign(x, centers):
    """argmin_k ||x_i - c_k||² via the expanded-quadratic matmul form."""
    x2 = jnp.sum(x * x, axis=1, keepdims=True)          # [N, 1]
    c2 = jnp.sum(centers * centers, axis=1)[None, :]    # [1, K]
    d2 = x2 - 2.0 * (x @ centers.T) + c2                # [N, K] one matmul
    labels = jnp.argmin(d2, axis=1)
    return labels, jnp.take_along_axis(
        d2, labels[:, None], axis=1)[:, 0]


@jax.jit
def _update(x, labels, centers):
    k = centers.shape[0]
    onehot = jax.nn.one_hot(labels, k, dtype=x.dtype)   # [N, K]
    sums = onehot.T @ x                                  # [K, D] MXU
    counts = onehot.sum(0)[:, None]
    # empty clusters keep their previous center
    return jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0), centers)


class KMeansClustering:
    """reference API: KMeansClustering.setup(nClusters, maxIterations,
    distanceFunction); applyTo(points) → ClusterSet."""

    def __init__(self, n_clusters: int, max_iterations: int = 100,
                 tol: float = 1e-6, seed: int = 0):
        self.n_clusters = n_clusters
        self.max_iterations = max_iterations
        self.tol = tol
        self.seed = seed
        self.cluster_centers_: Optional[np.ndarray] = None
        self.labels_: Optional[np.ndarray] = None
        self.inertia_: Optional[float] = None

    @classmethod
    def setup(cls, n_clusters: int, max_iterations: int = 100,
              distance_function: str = "euclidean",
              seed: int = 0) -> "KMeansClustering":
        if distance_function not in ("euclidean", "sqeuclidean"):
            raise ValueError("only euclidean distances are supported")
        return cls(n_clusters, max_iterations, seed=seed)

    def _init_centers(self, x: np.ndarray) -> np.ndarray:
        """kmeans++ seeding, on host. The running min-distance is updated
        incrementally against only the newest center (O(N·D) numpy per
        step) — routing this through the jitted ``_assign`` would compile
        K-1 distinct center shapes before Lloyd iterations even start."""
        rng = np.random.default_rng(self.seed)
        n = x.shape[0]
        center = x[rng.integers(n)]
        centers = [center]
        d2 = np.sum((x - center) ** 2, axis=1)
        for _ in range(1, self.n_clusters):
            p = np.maximum(d2, 0)
            s = p.sum()
            probs = p / s if s > 0 else np.full(n, 1.0 / n)
            center = x[rng.choice(n, p=probs)]
            centers.append(center)
            d2 = np.minimum(d2, np.sum((x - center) ** 2, axis=1))
        return np.stack(centers)

    def apply_to(self, points: np.ndarray) -> "KMeansClustering":
        x = np.asarray(points, np.float32)  # host-sync-ok: one-time fit() ingest of host points
        if x.shape[0] < self.n_clusters:
            raise ValueError(
                f"{x.shape[0]} points < {self.n_clusters} clusters")
        xd = jnp.asarray(x)
        centers = jnp.asarray(self._init_centers(x))
        prev_inertia = np.inf
        for _i in range(self.max_iterations):
            labels, d2 = _assign(xd, centers)
            centers = _update(xd, labels, centers)
            inertia = float(d2.sum())  # host-sync-ok: per-iteration convergence scalar drives host control flow
            if abs(prev_inertia - inertia) <= self.tol * max(
                    abs(prev_inertia), 1.0):
                break
            prev_inertia = inertia
        labels, d2 = _assign(xd, centers)
        self.cluster_centers_ = np.asarray(centers)  # host-sync-ok: fitted attributes fetched once at fit() end (sklearn-style contract)
        self.labels_ = np.asarray(labels)  # host-sync-ok: fitted attributes fetched once at fit() end (sklearn-style contract)
        self.inertia_ = float(d2.sum())  # host-sync-ok: fitted attributes fetched once at fit() end (sklearn-style contract)
        return self

    fit = apply_to

    def predict(self, points: np.ndarray) -> np.ndarray:
        labels, _ = _assign(jnp.asarray(np.asarray(points, np.float32)),  # host-sync-ok: predict() ingest of host points
                            jnp.asarray(self.cluster_centers_))
        return np.asarray(labels)  # host-sync-ok: predict() returns host labels by API contract
