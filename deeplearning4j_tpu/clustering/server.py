"""NearestNeighborsServer: REST k-NN serving (legacy shim).

Analog of the reference's deeplearning4j-nearestneighbor-server
(NearestNeighborsServer.java:42, a Play REST app — SURVEY §2.10). POST
/knn with {"vector": [...], "k": N} (query by vector) or {"index": i,
"k": N} (query by stored point) returns {"results": [{"index",
"distance"}...]}, mirroring the reference's NearestNeighborRequest/
NearestNeighborsResults DTOs.

Since the retrieval subsystem landed this class is a thin compatibility
shim: the private BaseHTTPRequestHandler loop and the host-side VPTree
walk are gone, replaced by a UIServer route over a jitted
RetrievalEngine (retrieval/engine.py — fused distance+top-k on device,
AOT-warmed at ``start()``). The JSON contract is unchanged:

- distances are reported in the legacy metric — true euclidean
  (sqrt of the kernel's squared L2) or cosine distance ``1 - cos``
  (rows and query are unit-normalized, so squared L2 = 2(1 - cos)
  and we report half of it);
- ``k > n`` returns n results, query-by-index returns the point
  itself first, and a body without ``vector``/``index`` answers 400.

``server.tree`` survives as a duck-typed handle (``.points``,
``.distance``, ``.search``) for callers that reached into the old
attribute; its ``search`` runs through the same engine.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.ui.modules import Route, UIModule


class _EngineTree:
    """Duck-type of the old ``VPTree`` attribute: ``.points``,
    ``.distance``, ``.search(q, k)`` — answered by the jitted engine,
    distances in the legacy metric."""

    def __init__(self, server: "NearestNeighborsServer"):
        self._server = server
        self.points = server.points
        self.distance = server.distance

    def search(self, query: np.ndarray, k: int
               ) -> Tuple[List[int], List[float]]:
        return self._server.search(query, k)


class _KnnModule(UIModule):
    def __init__(self, server: "NearestNeighborsServer"):
        self._server = server

    def get_routes(self) -> List[Route]:
        return [Route("POST", "/knn", self._knn)]

    def _knn(self, ctx, query, body):
        # the legacy contract answers 400 with {"error": ...} for any
        # malformed request (the old handler caught in-loop), so catch
        # here rather than letting UIServer's 500 fallback see it
        try:
            req = body if isinstance(body, dict) else {}
            k = int(req.get("k", 5))
            if "vector" in req:
                q = np.asarray(req["vector"], np.float64)  # host-sync-ok: decoding the JSON request body, already host data
            elif "index" in req:
                q = self._server.points[int(req["index"])]
            else:
                raise ValueError("request needs 'vector' or 'index'")
            idxs, dists = self._server.search(q, k)
            return {"results": [
                {"index": int(i), "distance": float(d)}  # host-sync-ok: HTTP response must be host JSON
                for i, d in zip(idxs, dists)]}
        except (ValueError, KeyError, IndexError, TypeError) as e:
            return ({"error": str(e)}, None, 400)


class NearestNeighborsServer:
    def __init__(self, points: np.ndarray, port: int = 0,
                 distance: str = "euclidean"):
        from deeplearning4j_tpu.retrieval.engine import RetrievalEngine
        from deeplearning4j_tpu.retrieval.index import ShardedCorpusIndex
        if distance not in ("euclidean", "cosine"):
            raise ValueError(f"unsupported distance {distance!r}")
        self.points = np.asarray(points, np.float64)  # host-sync-ok: legacy contract: f64 points kept for the duck-typed host-tree surface
        self.distance = distance
        self.port = port
        n = len(self.points)
        rows = np.asarray(self.points, np.float32)  # host-sync-ok: one-time build ingest into the device index
        if distance == "cosine":
            norms = np.linalg.norm(rows, axis=1, keepdims=True)
            rows = rows / np.maximum(norms, np.float32(1e-12))
        # one shard (this is the single-host legacy surface); the
        # k-ladder covers 1..n in powers of 4 so any legacy k is
        # served by the next warmed cell and sliced
        ladder = []
        kk = 1
        while kk < min(n, 1024):
            ladder.append(kk)
            kk *= 4
        ladder.append(min(n, 1024))     # ladder top = full corpus
        ladder = sorted(set(ladder))
        index = ShardedCorpusIndex.build(rows, shard_rows=max(n, 2))
        self._engine = RetrievalEngine(index, k_ladder=tuple(ladder),
                                       max_batch=1,
                                       session_id="legacy-knn")
        self._engine.warmup()
        self.tree = _EngineTree(self)
        self._ui = None

    @staticmethod
    def _next_pow2(n: int) -> int:
        p = 1
        while p < n:
            p *= 2
        return p

    def search(self, query: np.ndarray, k: int
               ) -> Tuple[List[int], List[float]]:
        """k nearest (indices, distances) in the legacy metric."""
        q = np.asarray(query, np.float32)  # host-sync-ok: query decode at the legacy REST boundary
        if self.distance == "cosine":
            q = q / np.maximum(np.linalg.norm(q), np.float32(1e-12))
        n = len(self.points)
        k_eff = min(int(k), n)
        d2, ids = self._engine.search(q, k_eff)
        d2 = np.asarray(d2, np.float64)  # host-sync-ok: legacy API returns host lists
        ids = np.asarray(ids)  # host-sync-ok: legacy API returns host lists
        keep = ids >= 0
        d2, ids = d2[keep], ids[keep]
        if self.distance == "cosine":
            dist = d2 / 2.0          # unit rows: L2^2 = 2(1 - cos)
        else:
            dist = np.sqrt(np.maximum(d2, 0.0))
        return [int(i) for i in ids], [float(d) for d in dist]  # host-sync-ok: the k ids/distances egress - the only per-query device fetch

    def start(self) -> "NearestNeighborsServer":
        from deeplearning4j_tpu.ui.server import UIServer
        from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
        self._ui = UIServer(port=self.port)
        self._ui.attach(InMemoryStatsStorage())
        self._ui.register_module(_KnnModule(self))
        self._ui.start()
        self.port = self._ui.port
        return self

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self):
        if self._ui is not None:
            self._ui.stop()
            self._ui = None
        self._engine.shutdown()
