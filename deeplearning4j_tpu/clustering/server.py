"""NearestNeighborsServer: REST k-NN serving.

Analog of the reference's deeplearning4j-nearestneighbor-server
(NearestNeighborsServer.java:42, a Play REST app — SURVEY §2.10). POST
/knn with {"vector": [...], "k": N} (query by vector) or {"index": i,
"k": N} (query by stored point) returns {"results": [{"index",
"distance"}...]}, mirroring the reference's NearestNeighborRequest/
NearestNeighborsResults DTOs.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from deeplearning4j_tpu.clustering.vptree import VPTree


class _Handler(BaseHTTPRequestHandler):
    tree: VPTree = None

    def log_message(self, *a):
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        if self.path != "/knn":
            self._json({"error": "not found"}, 404)
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(n) or b"{}")
            k = int(req.get("k", 5))
            if "vector" in req:
                q = np.asarray(req["vector"], np.float64)
            elif "index" in req:
                q = self.tree.points[int(req["index"])]
            else:
                raise ValueError("request needs 'vector' or 'index'")
            idxs, dists = self.tree.search(q, k)
            self._json({"results": [
                {"index": int(i), "distance": float(d)}
                for i, d in zip(idxs, dists)]})
        except (ValueError, KeyError, IndexError,
                json.JSONDecodeError) as e:
            self._json({"error": str(e)}, 400)


class NearestNeighborsServer:
    def __init__(self, points: np.ndarray, port: int = 0,
                 distance: str = "euclidean"):
        self.tree = VPTree(points, distance=distance)
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None

    def start(self) -> "NearestNeighborsServer":
        handler = type("BoundNN", (_Handler,), {"tree": self.tree})
        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port),
                                          handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        return self

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
