"""Clustering + nearest neighbors.

TPU-native analog of deeplearning4j-nearestneighbors-parent (SURVEY
§2.10): KMeans runs as jitted device iterations (distance matrix +
assignment matmuls on the MXU — the TPU replacement for the reference's
multi-threaded host loops); the space-partitioning trees (VPTree, KDTree,
SPTree) are host-side index structures, as in the reference.
"""

from deeplearning4j_tpu.clustering.kmeans import KMeansClustering
from deeplearning4j_tpu.clustering.vptree import VPTree
from deeplearning4j_tpu.clustering.kdtree import KDTree
from deeplearning4j_tpu.clustering.sptree import SpTree
from deeplearning4j_tpu.clustering.lsh import (
    RandomProjection,
    RandomProjectionLSH,
)

__all__ = ["KMeansClustering", "VPTree", "KDTree", "SpTree",
           "RandomProjectionLSH", "RandomProjection"]
