"""Model zoo.

Analog of deeplearning4j-zoo (SURVEY §2.6: ZooModel.java:23 + model/
AlexNet, Darknet19, LeNet, ResNet50, SimpleCNN, VGG16, VGG19,
TextGenerationLSTM, TinyYOLO...). Each zoo entry builds a ready
configuration/model for a given input shape + class count.

TPU-first notes: all convs NHWC; ResNet50 uses the standard bottleneck-v1
topology as a ComputationGraph (merge/elementwise vertices), compiled to a
single XLA program. bfloat16 compute is a flag away
(``compute_dtype="bfloat16"``) and is the benchmark configuration.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from deeplearning4j_tpu.models.computation_graph import ComputationGraph
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph.vertices import ElementWiseVertex
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.convolution import (
    ConvolutionLayer,
    ConvolutionMode,
    PoolingType,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.layers.feedforward import (
    ActivationLayer,
    DenseLayer,
    DropoutLayer,
)
from deeplearning4j_tpu.nn.layers.normalization import BatchNormalization
from deeplearning4j_tpu.nn.layers.output import (
    GlobalPoolingLayer,
    OutputLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.layers.recurrent import LSTM
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.initializers import WeightInit
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.optimize.updaters import Adam, Nesterovs, Updater


class ZooModel:
    """Base zoo entry (reference: ZooModel.java:23). ``init()`` returns a
    built, initialized model. Pretrained-weight loading hooks into the
    checkpoint loader when a weights file is present locally (zero-egress
    environment: no downloads; same cache contract as the fetchers)."""

    def conf(self):
        raise NotImplementedError

    def init(self):
        raise NotImplementedError

    def init_pretrained(self, path: Optional[str] = None):
        from deeplearning4j_tpu.models.serialization import (
            restore_computation_graph, restore_multi_layer_network)
        if path is None:
            raise FileNotFoundError(
                "no local pretrained weights; this environment has no "
                "network egress — place a checkpoint zip and pass its path")
        model = self.init()
        if isinstance(model, MultiLayerNetwork):
            return restore_multi_layer_network(path)
        return restore_computation_graph(path)


@dataclasses.dataclass
class LeNet(ZooModel):
    """reference: deeplearning4j-zoo/.../model/LeNet.java (BASELINE cfg 0)."""
    num_classes: int = 10
    height: int = 28
    width: int = 28
    channels: int = 1
    updater: Updater = dataclasses.field(default_factory=lambda: Adam(1e-3))
    seed: int = 123
    compute_dtype: str = "float32"

    def conf(self):
        return (NeuralNetConfiguration.Builder()
                .seed(self.seed)
                .updater(self.updater)
                .compute_dtype(self.compute_dtype)
                .list()
                .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5),
                                        activation=Activation.RELU,
                                        weight_init=WeightInit.HE_NORMAL))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5),
                                        activation=Activation.RELU,
                                        weight_init=WeightInit.HE_NORMAL))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=500, activation=Activation.RELU))
                .layer(OutputLayer(n_out=self.num_classes,
                                   loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.convolutional_flat(
                    self.height, self.width, self.channels))
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


@dataclasses.dataclass
class SimpleCNN(ZooModel):
    """reference: model/SimpleCNN.java — 4 conv blocks + dense."""
    num_classes: int = 10
    height: int = 48
    width: int = 48
    channels: int = 3
    seed: int = 123

    def conf(self):
        b = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(Adam(1e-3))
             .list())
        for n_out in (16, 32, 64, 128):
            b = (b.layer(ConvolutionLayer(
                    n_out=n_out, kernel_size=(3, 3),
                    convolution_mode=ConvolutionMode.SAME,
                    activation=Activation.IDENTITY))
                 .layer(BatchNormalization())
                 .layer(ConvolutionLayer(
                     n_out=n_out, kernel_size=(3, 3),
                     convolution_mode=ConvolutionMode.SAME,
                     activation=Activation.RELU))
                 .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2))))
        return (b.layer(DenseLayer(n_out=256, activation=Activation.RELU,
                                   dropout=0.5))
                .layer(OutputLayer(n_out=self.num_classes))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


@dataclasses.dataclass
class VGG16(ZooModel):
    """reference: model/VGG16.java (BASELINE cfg 1)."""
    num_classes: int = 200
    height: int = 224
    width: int = 224
    channels: int = 3
    seed: int = 123
    compute_dtype: str = "float32"

    def conf(self):
        b = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(Nesterovs(1e-2, 0.9))
             .compute_dtype(self.compute_dtype)
             .list())
        plan = [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]
        for n_out, reps in plan:
            for _ in range(reps):
                b = b.layer(ConvolutionLayer(
                    n_out=n_out, kernel_size=(3, 3),
                    convolution_mode=ConvolutionMode.SAME,
                    activation=Activation.RELU))
            b = b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        return (b.layer(DenseLayer(n_out=4096, activation=Activation.RELU,
                                   dropout=0.5))
                .layer(DenseLayer(n_out=4096, activation=Activation.RELU,
                                  dropout=0.5))
                .layer(OutputLayer(n_out=self.num_classes))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


@dataclasses.dataclass
class ResNet50(ZooModel):
    """reference: model/ResNet50.java (BASELINE cfgs 1 & 4) — bottleneck-v1
    ComputationGraph: conv1 7x7/2 → maxpool/2 → stages [3,4,6,3] →
    global avg pool → softmax."""
    num_classes: int = 200
    height: int = 224
    width: int = 224
    channels: int = 3
    seed: int = 123
    compute_dtype: str = "float32"
    updater: Updater = dataclasses.field(
        default_factory=lambda: Nesterovs(1e-2, 0.9))

    def conf(self):
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.updater)
             .compute_dtype(self.compute_dtype)
             .graph_builder()
             .add_inputs("in")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))

        def conv_bn(name, src, n_out, k, s, act=Activation.RELU):
            g.add_layer(f"{name}_conv", ConvolutionLayer(
                n_out=n_out, kernel_size=k, stride=s,
                convolution_mode=ConvolutionMode.SAME, has_bias=False,
                weight_init=WeightInit.HE_NORMAL,
                activation=Activation.IDENTITY), src)
            g.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_conv")
            if act is None:
                return f"{name}_bn"
            g.add_layer(f"{name}_act", ActivationLayer(activation=act),
                        f"{name}_bn")
            return f"{name}_act"

        def bottleneck(name, src, filters, stride, downsample):
            f1, f2, f3 = filters, filters, filters * 4
            x = conv_bn(f"{name}_a", src, f1, (1, 1), (stride, stride))
            x = conv_bn(f"{name}_b", x, f2, (3, 3), (1, 1))
            x = conv_bn(f"{name}_c", x, f3, (1, 1), (1, 1), act=None)
            if downsample:
                shortcut = conv_bn(f"{name}_ds", src, f3, (1, 1),
                                   (stride, stride), act=None)
            else:
                shortcut = src
            g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x,
                         shortcut)
            g.add_layer(f"{name}_out",
                        ActivationLayer(activation=Activation.RELU),
                        f"{name}_add")
            return f"{name}_out"

        x = conv_bn("conv1", "in", 64, (7, 7), (2, 2))
        g.add_layer("pool1", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), x)
        x = "pool1"
        stages = [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)]
        for si, (filters, blocks, first_stride) in enumerate(stages):
            for bi in range(blocks):
                stride = first_stride if bi == 0 else 1
                x = bottleneck(f"s{si}b{bi}", x, filters, stride, bi == 0)
        g.add_layer("avgpool", GlobalPoolingLayer(
            pooling_type=PoolingType.AVG), x)
        g.add_layer("out", OutputLayer(n_out=self.num_classes,
                                       loss=LossFunction.MCXENT,
                                       activation=Activation.SOFTMAX),
                    "avgpool")
        g.set_outputs("out")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()


@dataclasses.dataclass
class TextGenerationLSTM(ZooModel):
    """reference: model/TextGenerationLSTM.java — char-level 2xLSTM(256)."""
    vocab_size: int = 77
    timesteps: int = 60
    lstm_units: int = 256
    seed: int = 123

    def conf(self):
        return (NeuralNetConfiguration.Builder()
                .seed(self.seed)
                .updater(Adam(2e-3))
                .gradient_normalization("clip_value", 5.0)
                .list()
                .layer(LSTM(n_out=self.lstm_units,
                            activation=Activation.TANH))
                .layer(LSTM(n_out=self.lstm_units,
                            activation=Activation.TANH))
                .layer(RnnOutputLayer(n_out=self.vocab_size,
                                      loss=LossFunction.MCXENT,
                                      activation=Activation.SOFTMAX))
                .set_input_type(InputType.recurrent(self.vocab_size,
                                                    self.timesteps))
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


@dataclasses.dataclass
class AlexNet(ZooModel):
    """reference: model/AlexNet.java (single-stream variant)."""
    num_classes: int = 1000
    height: int = 224
    width: int = 224
    channels: int = 3
    seed: int = 123

    def conf(self):
        return (NeuralNetConfiguration.Builder()
                .seed(self.seed)
                .updater(Nesterovs(1e-2, 0.9))
                .list()
                .layer(ConvolutionLayer(n_out=96, kernel_size=(11, 11),
                                        stride=(4, 4),
                                        activation=Activation.RELU))
                .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=256, kernel_size=(5, 5),
                                        convolution_mode=ConvolutionMode.SAME,
                                        activation=Activation.RELU))
                .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                        convolution_mode=ConvolutionMode.SAME,
                                        activation=Activation.RELU))
                .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                        convolution_mode=ConvolutionMode.SAME,
                                        activation=Activation.RELU))
                .layer(ConvolutionLayer(n_out=256, kernel_size=(3, 3),
                                        convolution_mode=ConvolutionMode.SAME,
                                        activation=Activation.RELU))
                .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
                .layer(DenseLayer(n_out=4096, activation=Activation.RELU,
                                  dropout=0.5))
                .layer(DenseLayer(n_out=4096, activation=Activation.RELU,
                                  dropout=0.5))
                .layer(OutputLayer(n_out=self.num_classes))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()
