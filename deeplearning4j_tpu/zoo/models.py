"""Model zoo.

Analog of deeplearning4j-zoo (SURVEY §2.6: ZooModel.java:23 + model/
AlexNet, Darknet19, LeNet, ResNet50, SimpleCNN, VGG16, VGG19,
TextGenerationLSTM, TinyYOLO...). Each zoo entry builds a ready
configuration/model for a given input shape + class count.

TPU-first notes: all convs NHWC; ResNet50 uses the standard bottleneck-v1
topology as a ComputationGraph (merge/elementwise vertices), compiled to a
single XLA program. bfloat16 compute is a flag away
(``compute_dtype="bfloat16"``) and is the benchmark configuration.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Tuple

from deeplearning4j_tpu.models.computation_graph import ComputationGraph
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph.vertices import ElementWiseVertex
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.convolution import (
    ConvolutionLayer,
    ConvolutionMode,
    PoolingType,
    SpaceToDepthLayer,
    SubsamplingLayer,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.layers.feedforward import (
    ActivationLayer,
    DenseLayer,
    DropoutLayer,
)
from deeplearning4j_tpu.nn.layers.normalization import BatchNormalization
from deeplearning4j_tpu.nn.layers.output import (
    GlobalPoolingLayer,
    LossLayer,
    OutputLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.layers.recurrent import LSTM
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.initializers import WeightInit
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.optimize.updaters import Adam, Nesterovs, Updater


class ZooModel:
    """Base zoo entry (reference: ZooModel.java:23). ``init()`` returns a
    built, initialized model.

    ``init_pretrained`` implements the reference's download+checksum
    contract (ZooModel.initPretrained:51): fetch the published weights
    archive into the cache dir, verify its Adler32 checksum, restore.
    Zero-egress environments point ``url`` at a ``file://`` mirror (the
    path the tests exercise); a plain local ``path`` also works."""

    # subclasses may publish {url, checksum} per pretrained flavor the
    # way the reference's pretrainedUrl/pretrainedChecksum do
    PRETRAINED: dict = {}

    def conf(self):
        raise NotImplementedError

    def init(self):
        raise NotImplementedError

    def init_pretrained(self, path: Optional[str] = None,
                        url: Optional[str] = None,
                        checksum: Optional[int] = None,
                        flavor: str = "default"):
        from deeplearning4j_tpu.datasets.fetchers import (
            DATA_DIR, fetch_with_mirror)
        from deeplearning4j_tpu.models.serialization import (
            restore_computation_graph, restore_multi_layer_network)
        if path is None:
            if url is None and flavor in self.PRETRAINED:
                spec = self.PRETRAINED[flavor]
                url = spec.get("url")
                checksum = checksum if checksum is not None \
                    else spec.get("checksum")
                res = spec.get("resource")
                if url is None and res is not None:
                    # committed self-trained artifact shipped as package
                    # data (zero-egress stand-in for the reference's
                    # published downloads) — same checksum contract
                    cand = os.path.join(os.path.dirname(
                        os.path.abspath(__file__)), res)
                    if not os.path.exists(cand):
                        raise FileNotFoundError(
                            f"pretrained resource missing: {cand}")
                    import zlib as _z
                    v = 1
                    with open(cand, "rb") as f:
                        for chunk in iter(lambda: f.read(1 << 20), b""):
                            v = _z.adler32(chunk, v)
                    if checksum is not None and v != checksum:
                        raise IOError(
                            f"pretrained resource {res}: Adler32 {v} != "
                            f"expected {checksum}")
                    path = cand
            if path is None and url is None:
                raise FileNotFoundError(
                    "no pretrained weights source: pass path= to a local "
                    "checkpoint zip, or url= (file:// mirrors work in "
                    "zero-egress environments) + checksum=")
            if path is None:
                # cache key includes the url: without it, a later call
                # with a different mirror would silently reuse the first
                # download
                import zlib
                tag = f"{zlib.crc32(url.encode()):08x}"
                dest = os.path.join(
                    DATA_DIR, "pretrained",
                    f"{type(self).__name__}_{flavor}_{tag}.zip")
                path = fetch_with_mirror(url, dest,
                                         expected_checksum=checksum)
        # the checkpoint's stored configuration defines the restored
        # architecture (reference semantics: initPretrained returns the
        # published network as-is); dispatch by this zoo entry's config
        # class without paying a throwaway random init
        from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
        if isinstance(self.conf(), MultiLayerConfiguration):
            return restore_multi_layer_network(path)
        return restore_computation_graph(path)


@dataclasses.dataclass
class LeNet(ZooModel):
    """reference: deeplearning4j-zoo/.../model/LeNet.java (BASELINE cfg 0)."""
    # committed self-trained weights (≥98% on the real UCI digits test
    # split — tests/resources/pretrained/train_artifacts.py), the
    # zero-egress analog of the reference's published MNIST flavor
    PRETRAINED = {"digits": {"resource": "weights/lenet_digits.zip",
                             "checksum": 2574425481}}
    num_classes: int = 10
    height: int = 28
    width: int = 28
    channels: int = 1
    updater: Updater = dataclasses.field(default_factory=lambda: Adam(1e-3))
    seed: int = 123
    compute_dtype: str = "float32"

    def conf(self):
        return (NeuralNetConfiguration.Builder()
                .seed(self.seed)
                .updater(self.updater)
                .compute_dtype(self.compute_dtype)
                .list()
                .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5),
                                        activation=Activation.RELU,
                                        weight_init=WeightInit.HE_NORMAL))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5),
                                        activation=Activation.RELU,
                                        weight_init=WeightInit.HE_NORMAL))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=500, activation=Activation.RELU))
                .layer(OutputLayer(n_out=self.num_classes,
                                   loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.convolutional_flat(
                    self.height, self.width, self.channels))
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


@dataclasses.dataclass
class SimpleCNN(ZooModel):
    """reference: model/SimpleCNN.java — 4 conv blocks + dense."""
    # committed self-trained weights (≥95% on the real UCI digits test
    # split, NHWC 28x28x1 — tests/resources/pretrained/
    # train_artifacts.py); the online-learning demo model (ISSUE 10)
    PRETRAINED = {"digits": {"resource": "weights/simplecnn_digits.zip",
                             "checksum": 4047027733}}
    num_classes: int = 10
    height: int = 48
    width: int = 48
    channels: int = 3
    seed: int = 123

    def conf(self):
        b = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(Adam(1e-3))
             .list())
        for n_out in (16, 32, 64, 128):
            b = (b.layer(ConvolutionLayer(
                    n_out=n_out, kernel_size=(3, 3),
                    convolution_mode=ConvolutionMode.SAME,
                    activation=Activation.IDENTITY))
                 .layer(BatchNormalization())
                 .layer(ConvolutionLayer(
                     n_out=n_out, kernel_size=(3, 3),
                     convolution_mode=ConvolutionMode.SAME,
                     activation=Activation.RELU))
                 .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2))))
        return (b.layer(DenseLayer(n_out=256, activation=Activation.RELU,
                                   dropout=0.5))
                .layer(OutputLayer(n_out=self.num_classes))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


@dataclasses.dataclass
class VGG16(ZooModel):
    """reference: model/VGG16.java (BASELINE cfg 1)."""
    num_classes: int = 200
    height: int = 224
    width: int = 224
    channels: int = 3
    seed: int = 123
    compute_dtype: str = "float32"

    def conf(self):
        b = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(Nesterovs(1e-2, 0.9))
             .compute_dtype(self.compute_dtype)
             .list())
        plan = [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]
        for n_out, reps in plan:
            for _ in range(reps):
                b = b.layer(ConvolutionLayer(
                    n_out=n_out, kernel_size=(3, 3),
                    convolution_mode=ConvolutionMode.SAME,
                    activation=Activation.RELU))
            b = b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        return (b.layer(DenseLayer(n_out=4096, activation=Activation.RELU,
                                   dropout=0.5))
                .layer(DenseLayer(n_out=4096, activation=Activation.RELU,
                                  dropout=0.5))
                .layer(OutputLayer(n_out=self.num_classes))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


def fold_stem_weights(w7):
    """Fold 7×7/2 stem weights (7,7,C,O) HWIO into the exactly
    equivalent 4×4/1 space-to-depth parameterization (4,4,4C,O):
    ``Wf[ku, kv, (a·2+b)·C + c, o] = W7[2ku+a, 2kv+b, c, o]`` (zero
    where 2ku+a > 6). The channel slot order matches
    ``SpaceToDepthLayer(block_size=2)``'s (row, col, channel) packing,
    so restoring a trained conv1 into a ``s2d_stem=True`` ResNet50 (or
    back) is lossless — equivalence asserted in tests/test_zoo_extended."""
    import numpy as np
    w7 = np.asarray(w7)
    kh, kw, c, o = w7.shape
    wf = np.zeros((4, 4, 4 * c, o), w7.dtype)
    for ku in range(4):
        for a in range(2):
            u = 2 * ku + a
            if u >= kh:
                continue
            for kv in range(4):
                for b in range(2):
                    v = 2 * kv + b
                    if v >= kw:
                        continue
                    wf[ku, kv, (a * 2 + b) * c:(a * 2 + b + 1) * c] = \
                        w7[u, v]
    return wf


@dataclasses.dataclass
class ResNet50(ZooModel):
    """reference: model/ResNet50.java (BASELINE cfgs 1 & 4) — bottleneck-v1
    ComputationGraph: conv1 7x7/2 → maxpool/2 → stages [3,4,6,3] →
    global avg pool → softmax."""
    num_classes: int = 200
    height: int = 224
    width: int = 224
    channels: int = 3
    seed: int = 123
    compute_dtype: str = "float32"
    updater: Updater = dataclasses.field(
        default_factory=lambda: Nesterovs(1e-2, 0.9))
    # Build each bottleneck as one FusedBottleneckBlock (Pallas fused
    # conv+BN+ReLU kernels — ops/fused_conv.py): same math, BN stats and
    # normalize ride the conv HBM passes. The per-layer graph (default)
    # keeps conv/BN as separate layers, which the TP planner and
    # transfer-learning surgery operate on.
    fused_blocks: bool = False
    # implementation for fused blocks: "pallas" (custom kernels) or
    # "xla" (plain-XLA convs + Gram-matrix BN stats — see
    # ops/fused_conv.py conv_bn_stats_xla)
    fused_impl: str = "pallas"
    # Space-to-depth stem (round 5, VERDICT r4 #6): rearrange the input
    # H×W×3 → H/2×W/2×12 and replace the 7×7/2 conv1 with the EXACTLY
    # equivalent 4×4/1 conv on 12 channels (fold_stem_weights maps the
    # weights; equivalence-tested). Fattens the 3-channel stem
    # contraction the MXU underfills. Measured effect: PERF_ANALYSIS r5.
    s2d_stem: bool = False

    def conf(self):
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.updater)
             .compute_dtype(self.compute_dtype)
             .graph_builder()
             .add_inputs("in")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))

        def conv_bn(name, src, n_out, k, s, act=Activation.RELU):
            g.add_layer(f"{name}_conv", ConvolutionLayer(
                n_out=n_out, kernel_size=k, stride=s,
                convolution_mode=ConvolutionMode.SAME, has_bias=False,
                weight_init=WeightInit.HE_NORMAL,
                activation=Activation.IDENTITY), src)
            g.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_conv")
            if act is None:
                return f"{name}_bn"
            g.add_layer(f"{name}_act", ActivationLayer(activation=act),
                        f"{name}_bn")
            return f"{name}_act"

        def bottleneck(name, src, filters, stride, downsample):
            if self.fused_blocks:
                from deeplearning4j_tpu.nn.layers.fused import (
                    FusedBottleneckBlock)
                g.add_layer(name, FusedBottleneckBlock(
                    filters=filters, stride=stride, downsample=downsample,
                    impl=self.fused_impl),
                    src)
                return name
            f1, f2, f3 = filters, filters, filters * 4
            x = conv_bn(f"{name}_a", src, f1, (1, 1), (stride, stride))
            x = conv_bn(f"{name}_b", x, f2, (3, 3), (1, 1))
            x = conv_bn(f"{name}_c", x, f3, (1, 1), (1, 1), act=None)
            if downsample:
                shortcut = conv_bn(f"{name}_ds", src, f3, (1, 1),
                                   (stride, stride), act=None)
            else:
                shortcut = src
            g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x,
                         shortcut)
            g.add_layer(f"{name}_out",
                        ActivationLayer(activation=Activation.RELU),
                        f"{name}_add")
            return f"{name}_out"

        if self.s2d_stem:
            # 7×7/2 SAME on (H,W,3) ≡ 4×4/1 VALID on the s2d tensor
            # padded (1,2)×(1,2): y[i,j] = Σ x[2i+u-2, 2j+v-2]·W[u,v]
            # with u = 2ku+a becomes a stride-1 conv over the 2×2-block
            # channels — same math, fold_stem_weights carries weights
            # between the two parameterizations
            g.add_layer("s2d", SpaceToDepthLayer(block_size=2), "in")
            g.add_layer("s2d_pad", ZeroPaddingLayer(pad=(1, 2, 1, 2)),
                        "s2d")
            g.add_layer("conv1_conv", ConvolutionLayer(
                n_out=64, kernel_size=(4, 4), stride=(1, 1),
                convolution_mode=ConvolutionMode.TRUNCATE,
                padding=(0, 0), has_bias=False,
                weight_init=WeightInit.HE_NORMAL,
                activation=Activation.IDENTITY), "s2d_pad")
            g.add_layer("conv1_bn", BatchNormalization(), "conv1_conv")
            g.add_layer("conv1_act",
                        ActivationLayer(activation=Activation.RELU),
                        "conv1_bn")
            x = "conv1_act"
        else:
            x = conv_bn("conv1", "in", 64, (7, 7), (2, 2))
        g.add_layer("pool1", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), x)
        x = "pool1"
        stages = [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)]
        for si, (filters, blocks, first_stride) in enumerate(stages):
            for bi in range(blocks):
                stride = first_stride if bi == 0 else 1
                x = bottleneck(f"s{si}b{bi}", x, filters, stride, bi == 0)
        g.add_layer("avgpool", GlobalPoolingLayer(
            pooling_type=PoolingType.AVG), x)
        g.add_layer("out", OutputLayer(n_out=self.num_classes,
                                       loss=LossFunction.MCXENT,
                                       activation=Activation.SOFTMAX),
                    "avgpool")
        g.set_outputs("out")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()


@dataclasses.dataclass
class TextGenerationLSTM(ZooModel):
    """reference: model/TextGenerationLSTM.java — char-level 2xLSTM(256)."""
    # committed self-trained char-level weights (corpus + vocab:
    # tests/resources/pretrained/; weights/textgen_vocab.json maps
    # char → input index, 0 = unknown)
    PRETRAINED = {"default": {"resource": "weights/textgen_lstm.zip",
                              "checksum": 3656007127}}
    vocab_size: int = 77
    timesteps: int = 60
    lstm_units: int = 256
    seed: int = 123

    def conf(self):
        return (NeuralNetConfiguration.Builder()
                .seed(self.seed)
                .updater(Adam(2e-3))
                .gradient_normalization("clip_value", 5.0)
                .list()
                .layer(LSTM(n_out=self.lstm_units,
                            activation=Activation.TANH))
                .layer(LSTM(n_out=self.lstm_units,
                            activation=Activation.TANH))
                .layer(RnnOutputLayer(n_out=self.vocab_size,
                                      loss=LossFunction.MCXENT,
                                      activation=Activation.SOFTMAX))
                .set_input_type(InputType.recurrent(self.vocab_size,
                                                    self.timesteps))
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


@dataclasses.dataclass
class AlexNet(ZooModel):
    """reference: model/AlexNet.java (single-stream variant)."""
    num_classes: int = 1000
    height: int = 224
    width: int = 224
    channels: int = 3
    seed: int = 123

    def conf(self):
        return (NeuralNetConfiguration.Builder()
                .seed(self.seed)
                .updater(Nesterovs(1e-2, 0.9))
                .list()
                .layer(ConvolutionLayer(n_out=96, kernel_size=(11, 11),
                                        stride=(4, 4),
                                        activation=Activation.RELU))
                .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=256, kernel_size=(5, 5),
                                        convolution_mode=ConvolutionMode.SAME,
                                        activation=Activation.RELU))
                .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                        convolution_mode=ConvolutionMode.SAME,
                                        activation=Activation.RELU))
                .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                        convolution_mode=ConvolutionMode.SAME,
                                        activation=Activation.RELU))
                .layer(ConvolutionLayer(n_out=256, kernel_size=(3, 3),
                                        convolution_mode=ConvolutionMode.SAME,
                                        activation=Activation.RELU))
                .layer(SubsamplingLayer(kernel_size=(3, 3), stride=(2, 2)))
                .layer(DenseLayer(n_out=4096, activation=Activation.RELU,
                                  dropout=0.5))
                .layer(DenseLayer(n_out=4096, activation=Activation.RELU,
                                  dropout=0.5))
                .layer(OutputLayer(n_out=self.num_classes))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


@dataclasses.dataclass
class VGG19(ZooModel):
    """reference: model/VGG19.java — VGG16 with the deeper [2,2,4,4,4]
    conv plan."""
    num_classes: int = 1000
    height: int = 224
    width: int = 224
    channels: int = 3
    seed: int = 123
    compute_dtype: str = "float32"

    def conf(self):
        b = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(Nesterovs(1e-2, 0.9))
             .compute_dtype(self.compute_dtype)
             .list())
        plan = [(64, 2), (128, 2), (256, 4), (512, 4), (512, 4)]
        for n_out, reps in plan:
            for _ in range(reps):
                b = b.layer(ConvolutionLayer(
                    n_out=n_out, kernel_size=(3, 3),
                    convolution_mode=ConvolutionMode.SAME,
                    activation=Activation.RELU))
            b = b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        return (b.layer(DenseLayer(n_out=4096, activation=Activation.RELU,
                                   dropout=0.5))
                .layer(DenseLayer(n_out=4096, activation=Activation.RELU,
                                  dropout=0.5))
                .layer(OutputLayer(n_out=self.num_classes))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


def _darknet_block(b, n_out, kernel):
    """conv + BN + leaky-relu (reference: model/helper/DarknetHelper.java
    addLayers — conv/BN/LeakyReLU triple)."""
    return (b.layer(ConvolutionLayer(
                n_out=n_out, kernel_size=kernel,
                convolution_mode=ConvolutionMode.SAME, has_bias=False,
                activation=Activation.IDENTITY))
            .layer(BatchNormalization())
            .layer(ActivationLayer(activation=Activation.LEAKYRELU)))


@dataclasses.dataclass
class Darknet19(ZooModel):
    """reference: model/Darknet19.java — the YOLO2 classification
    backbone (19 convs, 1x1 bottlenecks between 3x3s)."""
    num_classes: int = 1000
    height: int = 224
    width: int = 224
    channels: int = 3
    seed: int = 123
    compute_dtype: str = "float32"

    def conf(self):
        b = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(Nesterovs(1e-3, 0.9))
             .compute_dtype(self.compute_dtype)
             .list())
        b = _darknet_block(b, 32, (3, 3))
        b = b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        b = _darknet_block(b, 64, (3, 3))
        b = b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        for mid, outer in ((64, 128), (128, 256)):
            b = _darknet_block(b, outer, (3, 3))
            b = _darknet_block(b, mid, (1, 1))
            b = _darknet_block(b, outer, (3, 3))
            b = b.layer(SubsamplingLayer(kernel_size=(2, 2),
                                         stride=(2, 2)))
        for mid, outer in ((256, 512), (512, 1024)):
            b = _darknet_block(b, outer, (3, 3))
            b = _darknet_block(b, mid, (1, 1))
            b = _darknet_block(b, outer, (3, 3))
            b = _darknet_block(b, mid, (1, 1))
            b = _darknet_block(b, outer, (3, 3))
            if outer == 512:
                b = b.layer(SubsamplingLayer(kernel_size=(2, 2),
                                             stride=(2, 2)))
        b = b.layer(ConvolutionLayer(n_out=self.num_classes,
                                     kernel_size=(1, 1),
                                     convolution_mode=ConvolutionMode.SAME,
                                     activation=Activation.IDENTITY))
        return (b.layer(GlobalPoolingLayer(pooling_type=PoolingType.AVG))
                .layer(LossLayer(loss=LossFunction.MCXENT,
                                 activation=Activation.SOFTMAX))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


@dataclasses.dataclass
class TinyYOLO(ZooModel):
    """reference: model/TinyYOLO.java — tiny-YOLOv2 detector: 6 darknet
    conv/pool stages then a 1x1 head into Yolo2OutputLayer. Default
    anchors are the reference's (in 13x13-grid units)."""
    num_classes: int = 20
    height: int = 416
    width: int = 416
    channels: int = 3
    boxes: Tuple = ((1.08, 1.19), (3.42, 4.41), (6.63, 11.38),
                    (9.42, 5.11), (16.62, 10.52))
    seed: int = 123
    compute_dtype: str = "float32"

    def conf(self):
        from deeplearning4j_tpu.nn.layers.objdetect import Yolo2OutputLayer
        b = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(Adam(1e-3))
             .compute_dtype(self.compute_dtype)
             .list())
        for n_out in (16, 32, 64, 128, 256):
            b = _darknet_block(b, n_out, (3, 3))
            b = b.layer(SubsamplingLayer(kernel_size=(2, 2),
                                         stride=(2, 2)))
        b = _darknet_block(b, 512, (3, 3))
        b = b.layer(SubsamplingLayer(
            kernel_size=(2, 2), stride=(1, 1),
            convolution_mode=ConvolutionMode.SAME))
        b = _darknet_block(b, 1024, (3, 3))
        b = _darknet_block(b, 1024, (3, 3))
        n_b = len(self.boxes)
        b = b.layer(ConvolutionLayer(
            n_out=n_b * (5 + self.num_classes), kernel_size=(1, 1),
            convolution_mode=ConvolutionMode.SAME,
            activation=Activation.IDENTITY))
        return (b.layer(Yolo2OutputLayer(boxes=self.boxes))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())

    def init(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


@dataclasses.dataclass
class YOLO2(ZooModel):
    """reference: model/YOLO2.java — Darknet19 backbone + passthrough:
    the 512-channel stage-5 map rides a SpaceToDepth into the head merge
    (reference uses a route/reorg pair; here MergeVertex + SpaceToDepth)."""
    num_classes: int = 20
    height: int = 416
    width: int = 416
    channels: int = 3
    boxes: Tuple = ((0.57273, 0.677385), (1.87446, 2.06253),
                    (3.33843, 5.47434), (7.88282, 3.52778),
                    (9.77052, 9.16828))
    seed: int = 123
    compute_dtype: str = "float32"

    def conf(self):
        from deeplearning4j_tpu.nn.graph.vertices import MergeVertex
        from deeplearning4j_tpu.nn.layers.convolution import (
            SpaceToDepthLayer)
        from deeplearning4j_tpu.nn.layers.objdetect import Yolo2OutputLayer
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(Adam(1e-3))
             .compute_dtype(self.compute_dtype)
             .graph_builder()
             .add_inputs("in")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))

        def block(name, src, n_out, kernel):
            g.add_layer(f"{name}_conv", ConvolutionLayer(
                n_out=n_out, kernel_size=kernel,
                convolution_mode=ConvolutionMode.SAME, has_bias=False,
                activation=Activation.IDENTITY), src)
            g.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_conv")
            g.add_layer(f"{name}_act",
                        ActivationLayer(activation=Activation.LEAKYRELU),
                        f"{name}_bn")
            return f"{name}_act"

        def pool(name, src):
            g.add_layer(name, SubsamplingLayer(kernel_size=(2, 2),
                                               stride=(2, 2)), src)
            return name

        x = block("c1", "in", 32, (3, 3))
        x = pool("p1", x)
        x = block("c2", x, 64, (3, 3))
        x = pool("p2", x)
        for i, (mid, outer) in enumerate(((64, 128), (128, 256))):
            x = block(f"s{i}a", x, outer, (3, 3))
            x = block(f"s{i}b", x, mid, (1, 1))
            x = block(f"s{i}c", x, outer, (3, 3))
            x = pool(f"s{i}p", x)
        # stage 5 (512): its output is the passthrough source
        x = block("s2a", x, 512, (3, 3))
        x = block("s2b", x, 256, (1, 1))
        x = block("s2c", x, 512, (3, 3))
        x = block("s2d", x, 256, (1, 1))
        passthrough = block("s2e", x, 512, (3, 3))
        x = pool("s2p", passthrough)
        # stage 6 (1024)
        x = block("s3a", x, 1024, (3, 3))
        x = block("s3b", x, 512, (1, 1))
        x = block("s3c", x, 1024, (3, 3))
        x = block("s3d", x, 512, (1, 1))
        x = block("s3e", x, 1024, (3, 3))
        # head
        x = block("h1", x, 1024, (3, 3))
        x = block("h2", x, 1024, (3, 3))
        g.add_layer("reorg", SpaceToDepthLayer(block_size=2), passthrough)
        g.add_vertex("cat", MergeVertex(), "reorg", "h2_act")
        x = block("h3", "cat", 1024, (3, 3))
        n_b = len(self.boxes)
        g.add_layer("head", ConvolutionLayer(
            n_out=n_b * (5 + self.num_classes), kernel_size=(1, 1),
            convolution_mode=ConvolutionMode.SAME,
            activation=Activation.IDENTITY), x)
        g.add_layer("yolo", Yolo2OutputLayer(boxes=self.boxes), "head")
        g.set_outputs("yolo")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()


@dataclasses.dataclass
class GoogLeNet(ZooModel):
    """reference: model/GoogLeNet.java — Inception-v1: stem + 9 inception
    modules (4-branch MergeVertex each) + avg-pool head."""
    num_classes: int = 1000
    height: int = 224
    width: int = 224
    channels: int = 3
    seed: int = 123
    compute_dtype: str = "float32"

    def conf(self):
        from deeplearning4j_tpu.nn.graph.vertices import MergeVertex
        from deeplearning4j_tpu.nn.layers.normalization import (
            LocalResponseNormalization)
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(Nesterovs(1e-2, 0.9))
             .compute_dtype(self.compute_dtype)
             .graph_builder()
             .add_inputs("in")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))

        def conv(name, src, n_out, k, s=(1, 1)):
            g.add_layer(name, ConvolutionLayer(
                n_out=n_out, kernel_size=k, stride=s,
                convolution_mode=ConvolutionMode.SAME,
                activation=Activation.RELU), src)
            return name

        def inception(name, src, c1, c3r, c3, c5r, c5, cp):
            """4 branches: 1x1 / 1x1->3x3 / 1x1->5x5 / pool->1x1
            (reference: GoogLeNet.java inception helper)."""
            b1 = conv(f"{name}_b1", src, c1, (1, 1))
            conv(f"{name}_b3r", src, c3r, (1, 1))
            b3 = conv(f"{name}_b3", f"{name}_b3r", c3, (3, 3))
            conv(f"{name}_b5r", src, c5r, (1, 1))
            b5 = conv(f"{name}_b5", f"{name}_b5r", c5, (5, 5))
            g.add_layer(f"{name}_pool", SubsamplingLayer(
                kernel_size=(3, 3), stride=(1, 1),
                convolution_mode=ConvolutionMode.SAME), src)
            bp = conv(f"{name}_bp", f"{name}_pool", cp, (1, 1))
            g.add_vertex(name, MergeVertex(), b1, b3, b5, bp)
            return name

        x = conv("conv1", "in", 64, (7, 7), (2, 2))
        g.add_layer("pool1", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), x)
        g.add_layer("lrn1", LocalResponseNormalization(), "pool1")
        x = conv("conv2r", "lrn1", 64, (1, 1))
        x = conv("conv2", x, 192, (3, 3))
        g.add_layer("lrn2", LocalResponseNormalization(), x)
        g.add_layer("pool2", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), "lrn2")
        x = inception("i3a", "pool2", 64, 96, 128, 16, 32, 32)
        x = inception("i3b", x, 128, 128, 192, 32, 96, 64)
        g.add_layer("pool3", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), x)
        x = inception("i4a", "pool3", 192, 96, 208, 16, 48, 64)
        x = inception("i4b", x, 160, 112, 224, 24, 64, 64)
        x = inception("i4c", x, 128, 128, 256, 24, 64, 64)
        x = inception("i4d", x, 112, 144, 288, 32, 64, 64)
        x = inception("i4e", x, 256, 160, 320, 32, 128, 128)
        g.add_layer("pool4", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), x)
        x = inception("i5a", "pool4", 256, 160, 320, 32, 128, 128)
        x = inception("i5b", x, 384, 192, 384, 48, 128, 128)
        g.add_layer("avgpool", GlobalPoolingLayer(
            pooling_type=PoolingType.AVG), x)
        g.add_layer("drop", DropoutLayer(dropout=0.4), "avgpool")
        g.add_layer("out", OutputLayer(n_out=self.num_classes,
                                       loss=LossFunction.MCXENT,
                                       activation=Activation.SOFTMAX),
                    "drop")
        g.set_outputs("out")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()


@dataclasses.dataclass
class InceptionResNetV1(ZooModel):
    """reference: model/InceptionResNetV1.java (+ helper/
    InceptionResNetHelper.java) — FaceNet-style embedding net: stem,
    5x block35, reduction-A, 10x block17, reduction-B, 5x block8,
    128-d L2-normalized embedding, center-loss softmax head."""
    num_classes: int = 1001
    embedding_size: int = 128
    height: int = 160
    width: int = 160
    channels: int = 3
    seed: int = 123
    compute_dtype: str = "float32"

    def conf(self):
        from deeplearning4j_tpu.nn.graph.vertices import (
            ElementWiseVertex, L2NormalizeVertex, MergeVertex, ScaleVertex)
        from deeplearning4j_tpu.nn.layers.output import (
            CenterLossOutputLayer)
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(Adam(1e-3))
             .compute_dtype(self.compute_dtype)
             .graph_builder()
             .add_inputs("in")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))

        def conv(name, src, n_out, k, s=(1, 1), act=Activation.RELU):
            g.add_layer(f"{name}_c", ConvolutionLayer(
                n_out=n_out, kernel_size=k, stride=s,
                convolution_mode=ConvolutionMode.SAME, has_bias=False,
                activation=Activation.IDENTITY), src)
            g.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_c")
            if act is None:
                return f"{name}_bn"
            g.add_layer(f"{name}_a", ActivationLayer(activation=act),
                        f"{name}_bn")
            return f"{name}_a"

        def residual(name, src, branches, n_channels, scale):
            """merge(branches) -> linear 1x1 up-projection -> scaled
            residual add -> relu (InceptionResNetHelper block pattern)."""
            g.add_vertex(f"{name}_cat", MergeVertex(), *branches)
            up = conv(f"{name}_up", f"{name}_cat", n_channels, (1, 1),
                      act=None)
            g.add_vertex(f"{name}_scale", ScaleVertex(scale=scale), up)
            g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), src,
                         f"{name}_scale")
            g.add_layer(f"{name}_out",
                        ActivationLayer(activation=Activation.RELU),
                        f"{name}_add")
            return f"{name}_out"

        def block35(name, src):
            b1 = conv(f"{name}_b1", src, 32, (1, 1))
            b2 = conv(f"{name}_b2b", conv(f"{name}_b2a", src, 32, (1, 1)),
                      32, (3, 3))
            b3 = conv(f"{name}_b3c",
                      conv(f"{name}_b3b",
                           conv(f"{name}_b3a", src, 32, (1, 1)), 32,
                           (3, 3)), 32, (3, 3))
            return residual(name, src, (b1, b2, b3), 256, 0.17)

        def block17(name, src):
            b1 = conv(f"{name}_b1", src, 128, (1, 1))
            b2 = conv(f"{name}_b2c",
                      conv(f"{name}_b2b",
                           conv(f"{name}_b2a", src, 128, (1, 1)), 128,
                           (1, 7)), 128, (7, 1))
            return residual(name, src, (b1, b2), 896, 0.10)

        def block8(name, src):
            b1 = conv(f"{name}_b1", src, 192, (1, 1))
            b2 = conv(f"{name}_b2c",
                      conv(f"{name}_b2b",
                           conv(f"{name}_b2a", src, 192, (1, 1)), 192,
                           (1, 3)), 192, (3, 1))
            return residual(name, src, (b1, b2), 1792, 0.20)

        # stem
        x = conv("stem1", "in", 32, (3, 3), (2, 2))
        x = conv("stem2", x, 32, (3, 3))
        x = conv("stem3", x, 64, (3, 3))
        g.add_layer("stem_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), x)
        x = conv("stem4", "stem_pool", 80, (1, 1))
        x = conv("stem5", x, 192, (3, 3))
        x = conv("stem6", x, 256, (3, 3), (2, 2))
        for i in range(5):
            x = block35(f"b35_{i}", x)
        # reduction-A -> 896 channels
        g.add_layer("redA_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), x)
        ra1 = conv("redA_b1", x, 384, (3, 3), (2, 2))
        ra2 = conv("redA_b2c",
                   conv("redA_b2b", conv("redA_b2a", x, 192, (1, 1)),
                        192, (3, 3)), 256, (3, 3), (2, 2))
        g.add_vertex("redA", MergeVertex(), "redA_pool", ra1, ra2)
        x = "redA"
        for i in range(10):
            x = block17(f"b17_{i}", x)
        # reduction-B -> 1792 channels
        g.add_layer("redB_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), x)
        rb1 = conv("redB_b1b", conv("redB_b1a", x, 256, (1, 1)), 384,
                   (3, 3), (2, 2))
        rb2 = conv("redB_b2b", conv("redB_b2a", x, 256, (1, 1)), 256,
                   (3, 3), (2, 2))
        rb3 = conv("redB_b3c",
                   conv("redB_b3b", conv("redB_b3a", x, 256, (1, 1)),
                        256, (3, 3)), 256, (3, 3), (2, 2))
        g.add_vertex("redB", MergeVertex(), "redB_pool", rb1, rb2, rb3)
        x = "redB"
        for i in range(5):
            x = block8(f"b8_{i}", x)
        g.add_layer("avgpool", GlobalPoolingLayer(
            pooling_type=PoolingType.AVG), x)
        g.add_layer("bottleneck", DenseLayer(
            n_out=self.embedding_size, activation=Activation.IDENTITY),
            "avgpool")
        g.add_vertex("embeddings", L2NormalizeVertex(), "bottleneck")
        g.add_layer("out", CenterLossOutputLayer(
            n_out=self.num_classes, loss=LossFunction.MCXENT,
            activation=Activation.SOFTMAX), "embeddings")
        g.set_outputs("out")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()


@dataclasses.dataclass
class FaceNetNN4Small2(ZooModel):
    """reference: model/FaceNetNN4Small2.java (+ helper/FaceNetHelper.java)
    — the NN4-small2 GoogLeNet-style face embedding net: stem, mixed
    3a/3b/3c/4a/4e/5a/5b inception blocks, 128-d L2-normalized embedding,
    center-loss softmax head."""
    num_classes: int = 5749
    embedding_size: int = 128
    height: int = 96
    width: int = 96
    channels: int = 3
    seed: int = 123
    compute_dtype: str = "float32"

    def conf(self):
        from deeplearning4j_tpu.nn.graph.vertices import (
            L2NormalizeVertex, MergeVertex)
        from deeplearning4j_tpu.nn.layers.normalization import (
            LocalResponseNormalization)
        from deeplearning4j_tpu.nn.layers.output import (
            CenterLossOutputLayer)
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(Adam(1e-3))
             .compute_dtype(self.compute_dtype)
             .graph_builder()
             .add_inputs("in")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))

        def conv(name, src, n_out, k, s=(1, 1)):
            g.add_layer(f"{name}_c", ConvolutionLayer(
                n_out=n_out, kernel_size=k, stride=s,
                convolution_mode=ConvolutionMode.SAME,
                activation=Activation.IDENTITY), src)
            g.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_c")
            g.add_layer(f"{name}_a",
                        ActivationLayer(activation=Activation.RELU),
                        f"{name}_bn")
            return f"{name}_a"

        def inception(name, src, c3r, c3, c5r, c5, cp, c1,
                      strided=False):
            """FaceNetHelper.appendGraph-style mixed block; ``strided``
            blocks (3c, 4e) drop the 1x1 branch and downsample."""
            stride = (2, 2) if strided else (1, 1)
            branches = []
            b3 = conv(f"{name}_3", conv(f"{name}_3r", src, c3r, (1, 1)),
                      c3, (3, 3), stride)
            branches.append(b3)
            if c5:
                b5 = conv(f"{name}_5",
                          conv(f"{name}_5r", src, c5r, (1, 1)), c5,
                          (5, 5), stride)
                branches.append(b5)
            g.add_layer(f"{name}_pool", SubsamplingLayer(
                kernel_size=(3, 3),
                stride=(2, 2) if strided else (1, 1),
                convolution_mode=ConvolutionMode.SAME), src)
            if cp:
                branches.append(conv(f"{name}_pp", f"{name}_pool", cp,
                                     (1, 1)))
            else:
                branches.append(f"{name}_pool")
            if c1:
                branches.append(conv(f"{name}_1", src, c1, (1, 1)))
            g.add_vertex(name, MergeVertex(), *branches)
            return name

        x = conv("conv1", "in", 64, (7, 7), (2, 2))
        g.add_layer("pool1", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), x)
        g.add_layer("lrn1", LocalResponseNormalization(), "pool1")
        x = conv("conv2", "lrn1", 64, (1, 1))
        x = conv("conv3", x, 192, (3, 3))
        g.add_layer("lrn2", LocalResponseNormalization(), x)
        g.add_layer("pool2", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), "lrn2")
        x = inception("mixed3a", "pool2", 96, 128, 16, 32, 32, 64)
        x = inception("mixed3b", x, 96, 128, 32, 64, 64, 64)
        x = inception("mixed3c", x, 128, 256, 32, 64, 0, 0, strided=True)
        x = inception("mixed4a", x, 96, 192, 32, 64, 128, 256)
        x = inception("mixed4e", x, 160, 256, 64, 128, 0, 0, strided=True)
        x = inception("mixed5a", x, 96, 384, 0, 0, 96, 256)
        x = inception("mixed5b", x, 96, 384, 0, 0, 96, 256)
        g.add_layer("avgpool", GlobalPoolingLayer(
            pooling_type=PoolingType.AVG), x)
        g.add_layer("bottleneck", DenseLayer(
            n_out=self.embedding_size, activation=Activation.IDENTITY),
            "avgpool")
        g.add_vertex("embeddings", L2NormalizeVertex(), "bottleneck")
        g.add_layer("out", CenterLossOutputLayer(
            n_out=self.num_classes, loss=LossFunction.MCXENT,
            activation=Activation.SOFTMAX), "embeddings")
        g.set_outputs("out")
        return g.build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()
