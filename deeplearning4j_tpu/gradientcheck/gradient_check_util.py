"""Finite-difference gradient checking — the correctness backbone.

Analog of the reference's ``GradientCheckUtil``
(deeplearning4j-nn/.../gradientcheck/GradientCheckUtil.java:54 —
checkGradients:109; formula (C(w+ε)−C(w−ε))/2ε per parameter with
relative-error thresholds, double precision). Sixteen reference test suites
hang off that one utility (SURVEY §4); ours serves the same role.

Implementation: runs under ``jax.experimental.enable_x64`` with the whole
parameter pytree cast to float64, compares ``jax.grad`` against central
differences per scalar parameter. Since jax.grad IS the production backward
path (there are no hand-written gradients to diverge), this validates layer
forward math, masking, and loss wiring end-to-end.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


def check_gradients(
    loss_fn: Callable,
    params,
    epsilon: float = 1e-6,
    max_rel_error: float = 1e-5,
    min_abs_error: float = 1e-8,
    max_params_per_leaf: int = 16,
    seed: int = 0,
    verbose: bool = True,
) -> bool:
    """Compare analytic vs numeric gradients.

    loss_fn(params) -> scalar. Subsamples up to ``max_params_per_leaf``
    scalar entries per leaf (the reference checks every parameter; sampling
    keeps CI fast at equal coverage confidence for randomly-initialized
    nets).
    """
    # jax >= 0.5 exposes jax.enable_x64; 0.4.x has it in experimental
    _enable_x64 = getattr(jax, "enable_x64", None)
    if _enable_x64 is None:
        from jax.experimental import enable_x64 as _enable_x64
    with _enable_x64(True):
        params64 = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a), jnp.float64)
            if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a,
            params)
        grad_fn = jax.grad(lambda p: jnp.asarray(loss_fn(p), jnp.float64))
        analytic = grad_fn(params64)

        flat_p, treedef = jax.tree_util.tree_flatten(params64)
        flat_g = jax.tree_util.tree_leaves(analytic)
        rng = np.random.default_rng(seed)
        total_checked = 0
        max_err = 0.0
        failures = []

        for li, (leaf, g) in enumerate(zip(flat_p, flat_g)):
            if not jnp.issubdtype(leaf.dtype, jnp.floating):
                continue
            n = int(np.prod(leaf.shape)) if leaf.shape else 1
            idxs = (np.arange(n) if n <= max_params_per_leaf
                    else rng.choice(n, max_params_per_leaf, replace=False))
            leaf_np = np.asarray(leaf).reshape(-1)
            g_np = np.asarray(g).reshape(-1)
            for idx in idxs:
                orig = leaf_np[idx]

                def loss_at(v):
                    leaf_mod = leaf_np.copy()
                    leaf_mod[idx] = v
                    new_leaf = jnp.asarray(leaf_mod.reshape(leaf.shape))
                    new_flat = list(flat_p)
                    new_flat[li] = new_leaf
                    p = jax.tree_util.tree_unflatten(treedef, new_flat)
                    return float(loss_fn(p))

                numeric = (loss_at(orig + epsilon) - loss_at(orig - epsilon)) \
                    / (2 * epsilon)
                an = float(g_np[idx])
                abs_err = abs(an - numeric)
                denom = max(abs(an), abs(numeric))
                rel_err = abs_err / denom if denom > 0 else 0.0
                total_checked += 1
                max_err = max(max_err, rel_err if abs_err > min_abs_error else 0.0)
                if rel_err > max_rel_error and abs_err > min_abs_error:
                    failures.append((li, int(idx), an, numeric, rel_err))

        if verbose and failures:
            for li, idx, an, nu, re in failures[:10]:
                print(f"  leaf {li} [{idx}]: analytic={an:.8g} "
                      f"numeric={nu:.8g} rel_err={re:.3g}")
        if verbose:
            print(f"gradient check: {total_checked} params checked, "
                  f"{len(failures)} failures, max rel err {max_err:.3g}")
        return len(failures) == 0


def check_model_gradients(model, dataset, **kwargs) -> bool:
    """Convenience wrapper: checks d(loss)/d(params) for a built model on one
    minibatch — the shape the reference's 16 gradient-check suites use."""
    if model.train_state is None:
        model.init()
    import jax.numpy as jnp
    from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork

    # Keep everything numpy-float64 here: jnp.asarray would truncate to f32
    # outside the enable_x64 scope that check_gradients opens.
    features = np.asarray(dataset.features, np.float64)
    labels = np.asarray(dataset.labels, np.float64)
    fmask = (None if dataset.features_mask is None
             else np.asarray(dataset.features_mask, np.float64))
    lmask = (None if dataset.labels_mask is None
             else np.asarray(dataset.labels_mask, np.float64))
    state = jax.tree_util.tree_map(
        lambda a: np.asarray(a, np.float64)
        if np.issubdtype(np.asarray(a).dtype, np.floating) else a,
        model.train_state.model_state)

    if isinstance(model, MultiLayerNetwork):
        def loss_fn(p):
            loss, _ = model._loss(p, state, features, labels, fmask, lmask,
                                  None, jnp.zeros((), jnp.int32))
            return loss
    else:
        def loss_fn(p):
            loss, _ = model._loss(p, state, (features,), (labels,),
                                  (fmask,) if fmask is not None else None,
                                  (lmask,) if lmask is not None else None,
                                  None, jnp.zeros((), jnp.int32))
            return loss

    return check_gradients(loss_fn, model.train_state.params, **kwargs)
