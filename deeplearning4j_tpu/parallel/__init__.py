"""Multi-device training and serving (mesh, wrappers, serving engine)."""

from deeplearning4j_tpu.parallel.inference import (
    InferenceMode,
    ParallelInference,
)
from deeplearning4j_tpu.parallel.serving import ServingEngine

__all__ = [
    "InferenceMode",
    "ParallelInference",
    "ServingEngine",
]
