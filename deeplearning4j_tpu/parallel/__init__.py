"""Multi-device training and serving (mesh, wrappers, serving engine,
fleet router, persisted AOT executable cache, multi-node cluster tier,
elastic fault tolerance)."""

from deeplearning4j_tpu.parallel.aot_cache import ArtifactStore
from deeplearning4j_tpu.parallel.cluster import (
    PEER_LOSS_EXIT_CODE,
    CollectiveWatchdog,
    classify_heartbeat_age,
)
from deeplearning4j_tpu.parallel.deadline import Deadline, DeadlineExceeded
from deeplearning4j_tpu.parallel.fleet import FleetRouter, ShedError
from deeplearning4j_tpu.parallel.inference import (
    InferenceMode,
    ParallelInference,
)
from deeplearning4j_tpu.parallel.node import (
    AutoScaler,
    NodeRegistry,
    ServingNode,
    install_sigterm_drain,
)
from deeplearning4j_tpu.parallel.quant import (
    CalibrationResult,
    PrecisionPolicy,
    QuantizationError,
    QuantizedModel,
    calibrate,
    quantize_model,
)
from deeplearning4j_tpu.parallel.remote import (
    CircuitBreaker,
    NoNodesError,
    RemoteDispatcher,
    RemoteError,
)
from deeplearning4j_tpu.parallel.serving import ServingEngine
from deeplearning4j_tpu.parallel.wrapper import ElasticOptions

__all__ = [
    "ArtifactStore",
    "AutoScaler",
    "CalibrationResult",
    "CircuitBreaker",
    "CollectiveWatchdog",
    "Deadline",
    "DeadlineExceeded",
    "ElasticOptions",
    "FleetRouter",
    "InferenceMode",
    "NoNodesError",
    "NodeRegistry",
    "ParallelInference",
    "PEER_LOSS_EXIT_CODE",
    "PrecisionPolicy",
    "QuantizationError",
    "QuantizedModel",
    "RemoteDispatcher",
    "RemoteError",
    "ServingEngine",
    "ServingNode",
    "ShedError",
    "calibrate",
    "classify_heartbeat_age",
    "install_sigterm_drain",
    "quantize_model",
]
