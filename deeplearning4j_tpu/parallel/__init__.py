"""Multi-device training and serving (mesh, wrappers, serving engine,
fleet router, persisted AOT executable cache)."""

from deeplearning4j_tpu.parallel.fleet import FleetRouter, ShedError
from deeplearning4j_tpu.parallel.inference import (
    InferenceMode,
    ParallelInference,
)
from deeplearning4j_tpu.parallel.serving import ServingEngine

__all__ = [
    "FleetRouter",
    "InferenceMode",
    "ParallelInference",
    "ServingEngine",
    "ShedError",
]
