"""Multi-device training and serving (mesh, wrappers, serving engine,
fleet router, persisted AOT executable cache, elastic fault
tolerance)."""

from deeplearning4j_tpu.parallel.cluster import (
    PEER_LOSS_EXIT_CODE,
    CollectiveWatchdog,
)
from deeplearning4j_tpu.parallel.fleet import FleetRouter, ShedError
from deeplearning4j_tpu.parallel.inference import (
    InferenceMode,
    ParallelInference,
)
from deeplearning4j_tpu.parallel.quant import (
    CalibrationResult,
    PrecisionPolicy,
    QuantizationError,
    QuantizedModel,
    calibrate,
    quantize_model,
)
from deeplearning4j_tpu.parallel.serving import ServingEngine
from deeplearning4j_tpu.parallel.wrapper import ElasticOptions

__all__ = [
    "CalibrationResult",
    "CollectiveWatchdog",
    "ElasticOptions",
    "FleetRouter",
    "InferenceMode",
    "ParallelInference",
    "PEER_LOSS_EXIT_CODE",
    "PrecisionPolicy",
    "QuantizationError",
    "QuantizedModel",
    "ServingEngine",
    "ShedError",
    "calibrate",
    "quantize_model",
]
