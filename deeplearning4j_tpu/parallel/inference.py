"""ParallelInference: thread-safe serving facade.

Analog of the reference's ParallelInference.java:35 (SURVEY §2.11):
``InferenceMode.BATCHED`` aggregates concurrent requests into one device
batch (observable queue, ParallelInference.java:55-65), INPLACE runs the
caller's request directly.

Since PR 5 the BATCHED path delegates to
``parallel/serving.py``'s ServingEngine — pipelined dispatch, committed
inference params, a bounded warmed bucket ladder, multi-replica fan-out
and tail-latency telemetry — keeping this class as the drop-in facade
matching the reference API. INPLACE remains a direct locked call but
gains the same request validation (non-empty batch) and oversized-request
clamp+split so it, too, never mints an unbounded executable per request
size.
"""

from __future__ import annotations

import enum
import threading
from typing import Optional

import numpy as np

from deeplearning4j_tpu.parallel.serving import ServingEngine


class InferenceMode(enum.Enum):
    INPLACE = "inplace"
    BATCHED = "batched"   # reference default (ParallelInference.java:55)


def _validate_request(x: np.ndarray) -> np.ndarray:
    if x.ndim == 0 or x.shape[0] == 0:
        raise ValueError(
            "features must be a non-empty batch (got shape "
            f"{x.shape}); a single example is shape (1, ...)")
    return x


class ParallelInference:
    """Facade over ServingEngine (BATCHED) / the model itself (INPLACE).

    Constructor keywords beyond the reference's four are forwarded to
    ServingEngine (``replicas=``, ``feature_shape=``, ``bf16=``, ...).
    """

    def __init__(self, model, inference_mode: InferenceMode =
                 InferenceMode.BATCHED, batch_limit: int = 32,
                 queue_limit: int = 64, timeout_ms: float = 5.0,
                 **engine_kwargs):
        self.model = model
        self.mode = inference_mode
        self.batch_limit = batch_limit
        self.timeout_ms = timeout_ms
        self._lock = threading.Lock()
        self.engine: Optional[ServingEngine] = None
        if self.mode == InferenceMode.BATCHED:
            self.engine = ServingEngine(
                model, batch_limit=batch_limit, queue_limit=queue_limit,
                timeout_ms=timeout_ms, **engine_kwargs)

    # ---- public API ------------------------------------------------------
    def output(self, features) -> np.ndarray:
        """Blocking inference (reference: ParallelInference.output:113)."""
        if self.mode == InferenceMode.BATCHED:
            return self.engine.output(features)
        x = _validate_request(np.asarray(features))  # host-sync-ok: inference host staging
        with self._lock:
            return self._output_inplace(x)

    def _output_inplace(self, x: np.ndarray) -> np.ndarray:
        """Direct call, but clamped to the pow2 ladder <= batch_limit:
        oversized requests split across dispatches instead of padding
        past the limit into a fresh executable per size."""
        outs = []
        for ofs in range(0, x.shape[0], self.batch_limit):
            chunk = x[ofs:ofs + self.batch_limit]
            n = chunk.shape[0]
            bucket = min(1 << (n - 1).bit_length(), self.batch_limit)
            if bucket > n:
                pad = np.repeat(chunk[-1:], bucket - n, axis=0)
                chunk = np.concatenate([chunk, pad], axis=0)
            outs.append(np.asarray(self.model.output(chunk))[:n])  # host-sync-ok: inference result returned as host array
        if len(outs) == 1:
            return outs[0]
        return np.concatenate(outs, axis=0)

    def shutdown(self):
        if self.engine is not None:
            self.engine.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
