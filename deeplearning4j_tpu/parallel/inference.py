"""ParallelInference: thread-safe serving with dynamic batching.

Analog of the reference's ParallelInference.java:35 (SURVEY §2.11):
``InferenceMode.BATCHED`` aggregates concurrent requests into one device
batch (observable queue, ParallelInference.java:55-65), INPLACE runs the
caller's request directly.

TPU-first adjustments: the reference pins one model replica per GPU and
round-robins requests; under XLA a single jitted forward already owns the
chip, so "workers" collapse into one dispatcher. Batches are padded to
power-of-two buckets so every request size reuses a cached executable
instead of triggering recompiles.
"""

from __future__ import annotations

import enum
import queue
import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Tuple

import numpy as np


class InferenceMode(enum.Enum):
    INPLACE = "inplace"
    BATCHED = "batched"   # reference default (ParallelInference.java:55)


class ParallelInference:
    def __init__(self, model, inference_mode: InferenceMode =
                 InferenceMode.BATCHED, batch_limit: int = 32,
                 queue_limit: int = 64, timeout_ms: float = 5.0):
        self.model = model
        self.mode = inference_mode
        self.batch_limit = batch_limit
        self.timeout_ms = timeout_ms
        self._queue: "queue.Queue[Tuple[np.ndarray, Future]]" = \
            queue.Queue(maxsize=queue_limit)
        self._shutdown = threading.Event()
        self._worker: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        if self.mode == InferenceMode.BATCHED:
            self._worker = threading.Thread(target=self._run, daemon=True)
            self._worker.start()

    # ---- public API ------------------------------------------------------
    def output(self, features) -> np.ndarray:
        """Blocking inference (reference: ParallelInference.output:113)."""
        x = np.asarray(features)  # host-sync-ok: inference host staging
        if x.ndim == 0:
            raise ValueError("features must have a batch dimension; got a"
                             " 0-d array")
        if self.mode == InferenceMode.INPLACE:
            with self._lock:
                return np.asarray(self.model.output(x))  # host-sync-ok: inference result returned as host array
        f: Future = Future()
        while True:
            if self._shutdown.is_set():
                raise RuntimeError("ParallelInference is shut down")
            try:
                # bounded wait so a full queue + dead worker can't block
                # the caller forever
                self._queue.put((x, f), timeout=0.1)
                break
            except queue.Full:
                continue
        if self._shutdown.is_set():
            # raced with shutdown(): the worker/drain may already be done
            # and will never pop this item — fail it ourselves
            self._drain()
        return f.result()

    def shutdown(self):
        self._shutdown.set()
        if self._worker is not None:
            self._worker.join(timeout=5)
        self._drain()

    def _drain(self):
        """Fail any still-queued request (post-shutdown)."""
        while True:
            try:
                _x, f = self._queue.get_nowait()
            except queue.Empty:
                break
            if not f.done():
                f.set_exception(
                    RuntimeError("ParallelInference shut down"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()

    # ---- batching worker -------------------------------------------------
    def _run(self):
        while not self._shutdown.is_set():
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            batch: List[Tuple[np.ndarray, Future]] = [first]
            try:
                total = first[0].shape[0]
                # one absolute aggregation deadline per batch; later
                # arrivals don't extend the first caller's latency window
                deadline = time.monotonic() + self.timeout_ms / 1000.0
                while total < self.batch_limit:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        item = self._queue.get(timeout=remaining)
                    except queue.Empty:
                        break
                    batch.append(item)
                    total += item[0].shape[0]
            except Exception as e:
                # a malformed request must fail its future, not kill the
                # worker thread (waiters would then hang forever)
                for _x, f in batch:
                    if not f.done():
                        f.set_exception(e)
                continue
            self._process(batch)

    def _process(self, batch):
        arrays = [x for x, _f in batch]
        futures = [f for _x, f in batch]
        try:
            x = np.concatenate(arrays, axis=0)
            n = x.shape[0]
            # pad to a power-of-two bucket: one cached executable per
            # bucket, never a recompile per request size
            bucket = 1 << (n - 1).bit_length()
            if bucket != n:
                pad = np.repeat(x[-1:], bucket - n, axis=0)
                x = np.concatenate([x, pad], axis=0)
            out = np.asarray(self.model.output(x))[:n]  # host-sync-ok: inference result returned as host array
            ofs = 0
            for arr, f in zip(arrays, futures):
                f.set_result(out[ofs:ofs + arr.shape[0]])
                ofs += arr.shape[0]
        except Exception as e:   # propagate to every waiter
            for f in futures:
                if not f.done():
                    f.set_exception(e)
