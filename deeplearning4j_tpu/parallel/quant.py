"""Post-training int8 quantization for serving: precision policy,
feeder-driven calibration, and the quantized inference builder.

``ops/quantize.py`` holds the numeric primitives; this module turns a
trained MultiLayerNetwork into a quantized ``build_inference_fn``
variant the ServingEngine can commit and AOT-compile like any other:

1. **PrecisionPolicy** generalizes the engine's old all-or-nothing
   ``bf16`` flag into f32 / bf16 / int8 per model, carrying the int8
   calibration recipe (method, sample stream, error budget).
2. **calibrate()** streams the policy's sample batches through the
   existing DeviceFeeder once, running a single jitted stats pass that
   taps the absmax of every quantizable layer's input. Scales are
   reduced host-side in float32 numpy so the same sample stream is
   bitwise deterministic across processes — ``CalibrationResult.hash()``
   feeds the AOT-cache fingerprint.
3. **quantize_model()** quantizes per-channel symmetric int8 weights,
   probes each layer's observed quantization error against the policy
   budget (layers that blow the budget stay f32 — per-layer fallback),
   and returns a QuantizedModel whose ``build_inference_fn`` replays
   the model's exact inference layer walk with int8 substitutions.

Only layers whose forward IS the dense matmul (DenseLayer and
subclasses that inherit its ``apply`` unchanged: OutputLayer,
RnnOutputLayer, ...) or the plain 2D convolution (exactly
ConvolutionLayer — Separable/Deconvolution subclasses rewire the
kernel layout) are candidates; everything else (LSTM, pooling,
preprocessors, ...) runs f32 unchanged.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.ops import quantize as qz

_MODES = ("f32", "bf16", "int8")
_CALIBRATIONS = ("absmax", "percentile")


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """Per-model serving precision. ``f32``/``bf16`` need no extras;
    ``int8`` carries the calibration recipe:

    - ``calibration``: "absmax" (max over every calibration batch) or
      "percentile" (the given percentile of per-batch absmaxima —
      clips rare outliers for tighter scales)
    - ``samples``: the calibration stream — an (N, ...) feature array,
      an iterable of feature arrays, or an iterable of DataSets (a
      DataSetIterator works as-is); batches stream through DeviceFeeder
    - ``error_budget``: max per-layer relative L2 error vs f32 before
      that layer falls back to f32
    """
    mode: str = "f32"
    calibration: str = "absmax"
    percentile: float = 99.9
    calib_batch_size: int = 32
    max_calib_batches: int = 16
    error_budget: float = 0.05
    samples: Any = dataclasses.field(default=None, repr=False,
                                     compare=False)

    def __post_init__(self):
        if self.mode not in _MODES:
            raise ValueError(f"mode must be one of {_MODES}, "
                             f"got {self.mode!r}")
        if self.calibration not in _CALIBRATIONS:
            raise ValueError(f"calibration must be one of {_CALIBRATIONS},"
                             f" got {self.calibration!r}")
        if not 0 < self.percentile <= 100:
            raise ValueError("percentile must be in (0, 100]")
        if self.calib_batch_size < 1 or self.max_calib_batches < 1:
            raise ValueError("calib_batch_size and max_calib_batches "
                             "must be >= 1")

    @property
    def tag(self) -> str:
        """The precision label used in cache keys, metrics and stats."""
        return self.mode

    @classmethod
    def f32(cls) -> "PrecisionPolicy":
        return cls(mode="f32")

    @classmethod
    def bf16(cls) -> "PrecisionPolicy":
        return cls(mode="bf16")

    @classmethod
    def int8(cls, samples, **kw) -> "PrecisionPolicy":
        return cls(mode="int8", samples=samples, **kw)


@dataclasses.dataclass(frozen=True)
class CalibrationResult:
    """Per-layer static activation scales from one calibration pass.
    ``hash()`` is the provenance key folded into the AOT-cache
    fingerprint: identical sample streams must produce identical
    hashes (scales are reduced in host f32 — bitwise deterministic)."""
    method: str
    percentile: float
    n_batches: int
    amax: Dict[str, float]           # calibrated |x| bound per layer input
    scales: Dict[str, float]         # activation scale per layer

    def hash(self) -> str:
        # float.hex() round-trips exactly — the hash changes iff a
        # scale's bits change
        payload = {
            "method": self.method,
            "percentile": float(np.float32(self.percentile)).hex(),  # host-sync-ok: python/np host floats, no device value in sight
            "n_batches": self.n_batches,
            "scales": {k: float(np.float32(v)).hex()  # host-sync-ok: scales are host f32 from calibration
                       for k, v in sorted(self.scales.items())},
        }
        return hashlib.sha256(
            json.dumps(payload, sort_keys=True).encode()).hexdigest()


class QuantizationError(ValueError):
    pass


# ---- layer classification ------------------------------------------------

def _dense_like(layer) -> bool:
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    return (isinstance(layer, DenseLayer)
            and type(layer).apply is DenseLayer.apply)


def _conv_like(layer) -> bool:
    from deeplearning4j_tpu.nn.layers.convolution import ConvolutionLayer
    return type(layer) is ConvolutionLayer


def _quant_kind(layer) -> Optional[str]:
    if _dense_like(layer):
        return "dense"
    if _conv_like(layer):
        return "conv"
    return None


def _quant_apply(layer, kind: str) -> Callable:
    """The int8 substitute for one layer's f32 ``apply`` (inference
    ctx only: no dropout, no state)."""
    if kind == "dense":
        def run(lp, x):
            y = qz.int8_dot(x, lp["W_q"], lp["w_scale"], lp["x_scale"])
            if layer.has_bias:
                y = y + lp["b"]
            return layer.activation.apply(y)
        return run
    from deeplearning4j_tpu.nn.layers.convolution import (
        DIMENSION_NUMBERS, _padding_arg, _pair)
    s, d, p = map(_pair, (layer.stride, layer.dilation, layer.padding))
    padding = _padding_arg(layer.convolution_mode, p)

    def run(lp, x):
        y = qz.int8_conv(x, lp["W_q"], lp["w_scale"], lp["x_scale"],
                         window_strides=s, padding=padding,
                         rhs_dilation=d,
                         dimension_numbers=DIMENSION_NUMBERS,
                         feature_group_count=layer.groups)
        if layer.has_bias:
            y = y + lp["b"]
        return layer.activation.apply(y)
    return run


def _require_mln(model):
    if not (hasattr(model, "layers") and hasattr(model, "_forward")
            and hasattr(model, "_preprocessors")):
        raise QuantizationError(
            "int8 quantization currently supports MultiLayerNetwork "
            f"only (got {type(model).__name__}); ComputationGraph "
            "models must serve at f32/bf16")


# ---- the shared inference layer walk -------------------------------------

def _inference_walk(model, params, model_state, x, fmask,
                    qmap: Dict[str, Callable]):
    """Replays build_inference_fn's exact walk (models/
    multi_layer_network.py): _forward(..., train=False, upto=n-1) then
    the output layer with mask=fmask — substituting ``qmap`` entries.
    With an empty qmap this is bitwise-identical to the f32 builder."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.models.base import cast_params, compute_cast
    from deeplearning4j_tpu.nn.inputs import RecurrentType
    from deeplearning4j_tpu.nn.layers.base import LayerContext
    g = model.conf.global_config
    x = compute_cast(jnp.asarray(x), g.compute_dtype)
    n = len(model.layers)
    for i in range(n):
        layer = model.layers[i]
        pp = model._preprocessors.get(i)
        if pp is not None:
            x = pp.apply(x)
        last = i == n - 1
        mask = fmask if (last or isinstance(model._input_types[i],
                                            RecurrentType)) else None
        ctx = LayerContext(train=False, rng=None, mask=mask)
        run = qmap.get(layer.name)
        if run is not None:
            x = run(params.get(layer.name, {}), x)
        else:
            lp = params.get(layer.name, {})
            if not last:
                # hidden layers go through the same working-copy cast +
                # (no-op at inference) weight-noise hook as _forward
                lp = cast_params(lp, g.compute_dtype)
                lp = layer.apply_weight_noise(lp, ctx, None)
            x, _ = layer.apply(lp, model_state.get(layer.name, {}), x,
                               ctx)
        if not last and model._tp_plan is not None:
            x = model._tp_plan.constrain(layer.name, x)
    return x


# ---- calibration ---------------------------------------------------------

def _calib_batches(policy: PrecisionPolicy) -> List[Any]:
    """Normalize the policy's sample stream to a bounded list of host
    DataSets (kept small: max_calib_batches x calib_batch_size)."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    src = policy.samples
    if src is None:
        raise QuantizationError(
            "PrecisionPolicy(mode='int8') needs calibration samples "
            "(PrecisionPolicy.int8(samples=...))")
    out: List[Any] = []
    if isinstance(src, np.ndarray) or hasattr(src, "shape"):
        arr = np.asarray(src)  # host-sync-ok: one-time calibration staging, offline
        b = min(policy.calib_batch_size, arr.shape[0])
        for i in range(0, arr.shape[0] - b + 1, b):
            out.append(DataSet(np.ascontiguousarray(arr[i:i + b])))
            if len(out) >= policy.max_calib_batches:
                break
    else:
        for item in src:
            if isinstance(item, DataSet):
                out.append(item)
            else:
                out.append(DataSet(np.asarray(item)))  # host-sync-ok: one-time calibration staging, offline
            if len(out) >= policy.max_calib_batches:
                break
    if not out:
        raise QuantizationError("calibration sample stream is empty")
    return out


def calibrate(model, policy: PrecisionPolicy, *, registry=None,
              tracer=None) -> CalibrationResult:
    """One pass through the DeviceFeeder over the policy's sample
    stream, collecting each quantizable layer's input absmax with a
    single jitted stats fn; scales reduce host-side in f32."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.datasets.feeder import DeviceFeeder
    _require_mln(model)
    if model.train_state is None:
        model.init()
    names = [l.name for l in model.layers if _quant_kind(l)]
    if not names:
        raise QuantizationError(
            f"{type(model).__name__} has no quantizable (dense/conv) "
            "layers")
    batches = _calib_batches(policy)

    def stats(params, mstate, x):
        # taps fills during trace: each quantizable layer's substitute
        # records its input absmax then runs the ORIGINAL f32 apply
        taps: Dict[str, Any] = {}
        qmap: Dict[str, Callable] = {}
        for nm in names:
            def run(lp, h, _layer=_layer_by_name(model, nm), _nm=nm):
                taps[_nm] = jnp.max(jnp.abs(h.astype(jnp.float32)))  # graftlint: disable=tracer-leak — taps is LOCAL to stats (rebuilt per trace) and returned via jnp.stack below; nothing escapes the trace
                return _tapped_apply(_layer, lp, h)
            qmap[nm] = run
        _inference_walk(model, params, mstate, x, None, qmap)
        return jnp.stack([taps[nm] for nm in names])

    stats_fn = jax.jit(stats)
    params = model.train_state.params
    mstate = model.train_state.model_state
    per_batch: List[np.ndarray] = []
    feeder = DeviceFeeder(iter(batches), depth=2, registry=registry,
                          tracer=tracer, session_id="quant-calib")
    for item in feeder:
        vec = stats_fn(params, mstate, item.features)
        per_batch.append(np.asarray(vec, np.float32))  # host-sync-ok: offline calibration reduce, one scalar vector per batch
    m = np.stack(per_batch)                    # (n_batches, n_layers) f32
    if policy.calibration == "percentile" and m.shape[0] > 1:
        col = np.percentile(m, policy.percentile, axis=0,
                            method="linear").astype(np.float32)
    else:
        col = np.max(m, axis=0)
    amax = {n: float(col[i]) for i, n in enumerate(names)}  # host-sync-ok: col is a host numpy reduction, already fetched
    scales = {n: float(qz.activation_scale(col[i]))  # host-sync-ok: host numpy, offline calibration
              for i, n in enumerate(names)}
    return CalibrationResult(method=policy.calibration,
                             percentile=policy.percentile,
                             n_batches=m.shape[0], amax=amax,
                             scales=scales)


def _layer_by_name(model, name):
    for l in model.layers:
        if l.name == name:
            return l
    raise KeyError(name)


def _tapped_apply(layer, lp, x):
    """The layer's ORIGINAL f32 apply under an inference ctx — the
    calibration substitute runs the same math as the f32 walk."""
    from deeplearning4j_tpu.nn.layers.base import LayerContext
    y, _ = layer.apply(lp, {}, x,
                       LayerContext(train=False, rng=None, mask=None))
    return y


# ---- quantization --------------------------------------------------------

@dataclasses.dataclass
class QuantizedModel:
    """A trained model plus its int8 serving artifacts: quantized
    params pytree, calibration, per-layer error report and the
    quantized inference builder."""
    model: Any
    policy: PrecisionPolicy
    calibration: CalibrationResult
    params: Any                       # quantized params pytree
    report: Dict[str, Dict[str, Any]]  # layer -> {kind, error, quantized}
    fallback: List[str]               # layers kept f32 (budget exceeded)

    @property
    def quantized_layers(self) -> List[str]:
        return [n for n, r in self.report.items() if r["quantized"]]

    def calibration_hash(self) -> str:
        """Provenance key for the AOT-cache fingerprint: calibration
        scales + the budget decisions actually baked into the fwd."""
        payload = {"calibration": self.calibration.hash(),
                   "error_budget": float(  # host-sync-ok: policy field is a host python float
                       np.float32(self.policy.error_budget)).hex(),
                   "fallback": sorted(self.fallback)}
        return hashlib.sha256(
            json.dumps(payload, sort_keys=True).encode()).hexdigest()

    def build_inference_fn(self):
        """Quantized ``(params, model_state, x, fmask) -> y`` — same
        contract as the model's own build_inference_fn, against
        ``self.params`` instead of the f32 train_state params."""
        qmap = {n: _quant_apply(_layer_by_name(self.model, n),
                                self.report[n]["kind"])
                for n in self.quantized_layers}
        model = self.model

        def fwd(params, model_state, x, fmask):
            return _inference_walk(model, params, model_state, x, fmask,
                                   qmap)
        return fwd


def _rel_l2(a, b) -> float:
    import jax.numpy as jnp
    num = jnp.linalg.norm((a - b).astype(jnp.float32).ravel())
    den = jnp.linalg.norm(b.astype(jnp.float32).ravel()) + 1e-12
    return float(num / den)  # host-sync-ok: offline per-layer error probe at quantize time


def quantize_model(model, policy: PrecisionPolicy, *, registry=None,
                   tracer=None,
                   calibration: Optional[CalibrationResult] = None
                   ) -> QuantizedModel:
    """Calibrate (unless a result is supplied), quantize per-channel
    int8 weights, and probe each candidate layer's quantization error
    on the first calibration batch: the probe walks the net once,
    feeding every layer the activations produced by the
    already-quantized prefix, so each accept/fallback decision sees
    realistic (error-carrying) inputs."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.inputs import RecurrentType
    from deeplearning4j_tpu.nn.layers.base import LayerContext
    from deeplearning4j_tpu.models.base import cast_params, compute_cast
    _require_mln(model)
    if policy.mode != "int8":
        raise QuantizationError(
            f"quantize_model needs an int8 policy, got {policy.mode!r}")
    if model.train_state is None:
        model.init()
    calib = calibration if calibration is not None else calibrate(
        model, policy, registry=registry, tracer=tracer)
    params = model.train_state.params
    mstate = model.train_state.model_state
    probe = np.asarray(_calib_batches(policy)[0].features)  # host-sync-ok: offline probe batch staging

    g = model.conf.global_config
    x = compute_cast(jnp.asarray(probe), g.compute_dtype)
    n = len(model.layers)
    params_q: Dict[str, Any] = {}
    report: Dict[str, Dict[str, Any]] = {}
    fallback: List[str] = []
    for i in range(n):
        layer = model.layers[i]
        pp = model._preprocessors.get(i)
        if pp is not None:
            x = pp.apply(x)
        last = i == n - 1
        mask = None                     # probe runs unmasked
        ctx = LayerContext(train=False, rng=None, mask=mask)
        lp = params.get(layer.name, {})
        kind = _quant_kind(layer)
        if kind is None or layer.name not in calib.scales:
            params_q[layer.name] = lp
            x, _ = layer.apply(
                lp if last else cast_params(lp, g.compute_dtype),
                mstate.get(layer.name, {}), x, ctx)
            continue
        w = np.asarray(lp["W"], np.float32)  # host-sync-ok: one-time weight fetch at quantize time
        w_q, w_scale = qz.quantize_weight(w)
        lq = {"W_q": jnp.asarray(w_q),
              "w_scale": jnp.asarray(w_scale),
              "x_scale": jnp.asarray(
                  np.float32(calib.scales[layer.name]))}
        if layer.has_bias and "b" in lp:
            lq["b"] = jnp.asarray(np.asarray(lp["b"], np.float32))  # host-sync-ok: one-time bias fetch at quantize time
        y_f, _ = layer.apply(lp, mstate.get(layer.name, {}), x, ctx)
        y_q = _quant_apply(layer, kind)(lq, x)
        err = _rel_l2(y_q, y_f)
        ok = err <= policy.error_budget
        report[layer.name] = {"kind": kind, "error": err,
                              "quantized": ok}
        if ok:
            params_q[layer.name] = lq
            x = y_q
        else:
            params_q[layer.name] = lp
            fallback.append(layer.name)
            x = y_f
    return QuantizedModel(model=model, policy=policy, calibration=calib,
                          params=params_q, report=report,
                          fallback=fallback)


def params_nbytes(params) -> int:
    """Total bytes of a committed params pytree — the params-resident
    term of the serving $/req proxy (int8 entries are ~1/4 of f32)."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        n = getattr(leaf, "nbytes", None)
        if n is None:
            n = np.asarray(leaf).nbytes  # host-sync-ok: metadata-only size probe at startup
        total += int(n)
    return total
