"""Parameter sharding rules — tensor parallelism over the ``model`` axis.

No reference analog (SURVEY §2.11: TP/PP/SP/EP are ABSENT in DL4J); designed
fresh for TPU: parameters get ``NamedSharding`` partition specs, and GSPMD
inserts the all-gathers/reduce-scatters over ICI.

Round-1 rule set (Megatron-style for dense stacks):
- Dense/Output `W` (in, out): shard `out` over ``model`` when divisible —
  column parallel; the following layer's `W` could be row-parallel, but
  plain column-parallel + XLA's sharding propagation is already correct and
  close to optimal for the zoo models.
- Conv kernels (h, w, i, o): shard `o` (output channels) over ``model``.
- Embedding tables (vocab, dim): shard `vocab` over ``model``.
- Biases/BN params: replicated (small).
Anything not divisible stays replicated.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def infer_param_shardings(params: Any, mesh: Mesh,
                          model_axis: str = MODEL_AXIS) -> Any:
    """Build a pytree of NamedShardings matching ``params``."""
    if model_axis in mesh.shape:
        m = int(mesh.shape[model_axis])
    else:
        m = 1

    def rule(path, leaf):
        if m <= 1:
            return NamedSharding(mesh, P())
        key = getattr(path[-1], "key", "")
        shape = getattr(leaf, "shape", ())
        if key in ("W", "pW") and len(shape) >= 2 and shape[-1] % m == 0:
            spec = [None] * (len(shape) - 1) + [model_axis]
            return NamedSharding(mesh, P(*spec))
        if key == "dW" and len(shape) == 4 and shape[-1] % m == 0:
            return NamedSharding(mesh, P(None, None, None, model_axis))
        if key in ("Wx", "Wh") and len(shape) == 2 and shape[-1] % m == 0:
            return NamedSharding(mesh, P(None, model_axis))
        return NamedSharding(mesh, P())

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    return jax.tree_util.tree_unflatten(treedef,
                                        [rule(p, l) for p, l in flat])


def batch_shardings(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(DATA_AXIS))


def apply_shardings(tree: Any, shardings: Any) -> Any:
    """device_put a pytree onto its shardings."""
    return jax.tree_util.tree_map(jax.device_put, tree, shardings)
