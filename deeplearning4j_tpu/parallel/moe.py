"""Expert parallelism: mixture-of-experts FFN with top-k routing.

ABSENT in the reference (SURVEY §2.11 row 7); designed fresh per SURVEY
§7.2 stage 7. GShard/Switch-style dense dispatch: routing builds
(tokens, experts, capacity) dispatch/combine tensors so the whole layer is
three einsums + the expert FFN — fully static shapes, MXU-friendly, no
gather/scatter. Expert parallelism is expressed the XLA-native way: the
expert-stacked weights and the (E, C, d) expert-batch tensor carry
sharding constraints on the ``expert`` mesh axis, and GSPMD inserts the
all-to-all dispatch/return collectives over ICI — no hand-written
communication (the reference's Aeron mesh analog is the compiler).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

EXPERT_AXIS = "expert"

_default_mesh: Optional[Mesh] = None
_default_axis: str = EXPERT_AXIS


def set_default_mesh(mesh: Optional[Mesh], axis: str = EXPERT_AXIS) -> None:
    """Install the mesh used for expert-sharding constraints. Training
    code sets this once; layers then shard without threading a mesh
    through the (serializable) layer configs."""
    global _default_mesh, _default_axis
    _default_mesh = mesh
    _default_axis = axis


def _constrain(x: jnp.ndarray, spec: P) -> jnp.ndarray:
    if _default_mesh is None or _default_axis not in _default_mesh.shape:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(_default_mesh, spec))


@dataclasses.dataclass
class MoEOutput:
    y: jnp.ndarray              # (tokens..., d_out) combined expert outputs
    aux_loss: jnp.ndarray       # load-balancing loss (scalar)
    router_z_loss: jnp.ndarray  # router logit magnitude penalty (scalar)


def route_top_k(logits: jnp.ndarray, k: int, capacity: int,
                token_mask: Optional[jnp.ndarray] = None):
    """Top-k routing → dense dispatch/combine tensors.

    logits: (T, E). token_mask: optional (T,) validity mask — masked
    (padding) tokens are never dispatched, consume no expert capacity,
    and are excluded from the aux/z statistics. Returns (dispatch
    (T,E,C) bool-ish float, combine (T,E,C) float, aux_loss, z_loss).
    Tokens overflowing an expert's capacity C are dropped (combine
    weight 0) — Switch semantics.
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), -1)
    tm = (jnp.ones((t,), jnp.float32) if token_mask is None
          else token_mask.reshape(-1).astype(jnp.float32))
    n_valid = jnp.maximum(jnp.sum(tm), 1.0)

    # aux loss (Switch eq.4): E * sum_e( frac_tokens_e * mean_prob_e ),
    # computed from the top-1 assignment over VALID tokens only.
    top1 = jnp.argmax(probs, -1)
    frac = jnp.sum(jax.nn.one_hot(top1, e, dtype=jnp.float32)
                   * tm[:, None], 0) / n_valid
    aux = e * jnp.sum(frac * jnp.sum(probs * tm[:, None], 0) / n_valid)
    z = jnp.sum(jax.nn.logsumexp(logits.astype(jnp.float32), -1) ** 2
                * tm) / n_valid

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    # Iterate the k choices (k is tiny and static); later choices see
    # occupancy from earlier ones via the running per-expert counts.
    counts = jnp.zeros((e,), jnp.int32)
    valid = tm > 0
    masked = probs * tm[:, None]
    for _ in range(k):
        choice = jnp.argmax(masked, -1)                     # (T,)
        gate = jnp.take_along_axis(masked, choice[:, None], 1)[:, 0]
        sel = jax.nn.one_hot(choice, e, dtype=jnp.int32)     # (T, E)
        # position of each token within its chosen expert's queue;
        # padding tokens don't advance the queue or claim a slot
        sel_eff = sel * valid[:, None].astype(jnp.int32)
        pos_in_expert = (jnp.cumsum(sel_eff, 0) - sel_eff) + counts[None, :]
        pos = jnp.sum(sel_eff * pos_in_expert, -1)           # (T,)
        keep = jnp.logical_and(pos < capacity, valid)
        oh_pos = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)
        d = (sel_eff.astype(jnp.float32)[:, :, None] * oh_pos[:, None, :]
             * keep[:, None, None])
        dispatch = dispatch + d
        combine = combine + d * gate[:, None, None]
        counts = counts + jnp.sum(sel_eff * keep[:, None].astype(jnp.int32),
                                  0)
        masked = masked * (1.0 - sel.astype(jnp.float32))    # exclude chosen
    return dispatch, combine, aux, z


def moe_ffn(x: jnp.ndarray,
            gate_w: jnp.ndarray,
            w_in: jnp.ndarray, b_in: jnp.ndarray,
            w_out: jnp.ndarray, b_out: jnp.ndarray,
            *,
            top_k: int = 2,
            capacity_factor: float = 1.25,
            activation=jax.nn.gelu,
            token_mask: Optional[jnp.ndarray] = None) -> MoEOutput:
    """Mixture-of-experts FFN over the last dim of ``x``.

    x: (..., d_model); gate_w: (d_model, E);
    w_in: (E, d_model, d_ff); b_in: (E, d_ff);
    w_out: (E, d_ff, d_model); b_out: (E, d_model).
    token_mask: optional validity mask broadcastable to x.shape[:-1]
    (padding tokens are not routed; their output is 0).
    """
    orig_shape = x.shape
    d = orig_shape[-1]
    xt = x.reshape(-1, d)
    t = xt.shape[0]
    e = gate_w.shape[-1]
    capacity = max(1, int(capacity_factor * top_k * t / e))

    flat_mask = None
    if token_mask is not None:
        flat_mask = jnp.broadcast_to(
            token_mask, orig_shape[:-1]).reshape(-1)

    logits = xt @ gate_w.astype(xt.dtype)
    dispatch, combine, aux, z = route_top_k(logits, top_k, capacity,
                                            token_mask=flat_mask)
    dispatch = dispatch.astype(xt.dtype)
    combine = combine.astype(xt.dtype)

    # (T,E,C),(T,d) -> (E,C,d): the all-to-all boundary under GSPMD.
    expert_in = jnp.einsum("tec,td->ecd", dispatch, xt)
    expert_in = _constrain(expert_in, P(_default_axis))
    w_in = _constrain(w_in, P(_default_axis))
    w_out = _constrain(w_out, P(_default_axis))

    h = activation(jnp.einsum("ecd,edf->ecf", expert_in, w_in)
                   + b_in[:, None, :].astype(xt.dtype))
    expert_out = (jnp.einsum("ecf,efd->ecd", h, w_out)
                  + b_out[:, None, :].astype(xt.dtype))
    expert_out = _constrain(expert_out, P(_default_axis))

    y = jnp.einsum("tec,ecd->td", combine, expert_out)
    return MoEOutput(y.reshape(orig_shape[:-1] + (y.shape[-1],)),
                     aux.astype(jnp.float32), z.astype(jnp.float32))
