"""FleetRouter: SLO-aware front door over per-model ServingEngine pools.

PR 5's ServingEngine is one process serving one model version; this is
the layer that makes a fleet of them operable (the serving analog of
DL4J's L7 frontends over ParallelInference — PAPER.md §1 layer map,
grown past the reference):

- **Admission control.** Every request passes ``admit()`` before it can
  touch an engine queue. A pool whose pending count (submitted, not yet
  answered) is at its bound sheds immediately — the caller gets a
  ``ShedError`` synchronously, never a Future that hangs behind a full
  queue.
- **SLO-aware shedding.** Each pool runs an AIMD controller over the
  *windowed* p99 from ``LatencyRing.delta_quantiles()`` (observations
  since the last tick only — the full 4096-sample ring would take
  minutes to forget a spike). p99 over the SLO → shed fraction steps up
  additively; back under → it decays multiplicatively. The fraction is
  capped below 1.0 so a recovering pool always sees enough traffic to
  measure itself.
- **Per-model pools, least-loaded dispatch.** A pool holds N engines of
  the active version; each request goes to the engine with the fewest
  in-flight requests.
- **Hot version swap + rollback.** ``swap()`` builds and *warms* the new
  version's engines first (with a persisted AOT cache this takes a
  fraction of a sweep — parallel/aot_cache.py), then switches the active
  pointer atomically and keeps the previous version warm as the rollback
  standby. ``rollback()`` switches back instantly. The zoo is a first-
  class model source: pools accept a built model, a ZooModel
  instance/class, a zoo entry name ("LeNet"), or a factory callable.

Environment knobs (all read at router construction; OBSERVABILITY.md):

- ``DL4J_FLEET_WINDOW_S``     controller tick period, s (default 1.0)
- ``DL4J_FLEET_SHED_STEP``    additive shed-fraction step (default 0.2)
- ``DL4J_FLEET_SHED_DECAY``   multiplicative decay under SLO (default 0.5)
- ``DL4J_FLEET_SHED_MAX``     shed-fraction cap < 1 (default 0.95)
- ``DL4J_FLEET_MAX_PENDING``  per-pool pending bound (default 256)

Prometheus series (rides the PR 2 registry, scraped at ``/metrics``):
``dl4j_fleet_admitted_total{model}``, ``dl4j_fleet_shed_total{model,
reason=queue|slo|deadline}``, ``dl4j_fleet_swap_total{model, event=swap|
rollback|param_swap|param_rollback}``, ``dl4j_fleet_pool_depth{model}``,
``dl4j_fleet_shed_fraction{model}``, ``dl4j_fleet_p99_ms{model}``,
``dl4j_fleet_pool_engines{model}``.
"""

from __future__ import annotations

import os
import random
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

from deeplearning4j_tpu.observe.latency import LatencyRing
from deeplearning4j_tpu.observe.registry import default_registry
from deeplearning4j_tpu.parallel.deadline import Deadline
from deeplearning4j_tpu.parallel.serving import ServingEngine


class ShedError(RuntimeError):
    """Request refused by admission control — raised synchronously from
    ``submit``/``output`` so a shed caller fails fast instead of holding
    a Future that will never resolve. ``reason`` is ``"queue"`` (pool
    pending bound hit), ``"slo"`` (p99-over-SLO shedding), or
    ``"deadline"`` (the request's deadline already expired at the front
    door — it never touches an engine queue, let alone the device)."""

    def __init__(self, model: str, reason: str, detail: str):
        super().__init__(
            f"request shed by fleet admission control "
            f"(model={model!r}, reason={reason}): {detail}")
        self.model = model
        self.reason = reason


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))  # host-sync-ok: env-var knob, trace-time constant
    except ValueError:
        return default


def _materialize(model, name: str):
    """Accept a built model, a ZooModel instance/class, a zoo entry
    name, or a zero-arg factory; return a built, initialized model."""
    if isinstance(model, str):
        from deeplearning4j_tpu.zoo import models as zoo_models
        cls = getattr(zoo_models, model, None)
        if cls is None:
            raise ValueError(f"pool {name!r}: no zoo model named "
                             f"{model!r}")
        model = cls
    if isinstance(model, type):
        model = model()
    if hasattr(model, "init") and not hasattr(model, "output") \
            and not hasattr(model, "build_inference_fn"):
        model = model.init()            # ZooModel entry
    elif callable(model) and not hasattr(model, "output") \
            and not hasattr(model, "build_inference_fn"):
        model = model()                 # factory
    return model


class ModelPool:
    """One model's replica pool: N engines of the active version plus an
    optional warm standby (the previous version, for rollback)."""

    def __init__(self, name: str, router: "FleetRouter",
                 engine_kwargs: Dict[str, Any], pool_size: int,
                 slo_ms: Optional[float], quant_gate=None):
        self.name = name
        self.router = router
        self.engine_kwargs = dict(engine_kwargs)
        self.pool_size = int(pool_size)
        self.slo_ms = slo_ms
        self.quant_gate = quant_gate
        self.gate_results: List[Any] = []   # GateResult per (re)build
        self.lock = threading.Lock()
        self.engines: List[ServingEngine] = []
        self.active_version: Optional[str] = None
        self.standby: Optional[Tuple[str, List[ServingEngine]]] = None
        # param-only standby: (version, host params, host model_state)
        # captured by promote_params before it overwrites the committed
        # params — the rollback target for the online-learning path
        self.param_standby: Optional[Tuple[Optional[str], Any, Any]] = \
            None
        self.ring = LatencyRing()
        self.pending = 0
        self.shed_fraction = 0.0
        self.windowed_p99_ms: Optional[float] = None
        self._last_tick = time.monotonic()
        self._rand = random.Random()

    # ---- admission -------------------------------------------------------
    def _tick_controller(self, now: float):
        """AIMD over the windowed p99 (caller holds ``self.lock``)."""
        r = self.router
        if now - self._last_tick < r.window_s:
            return
        self._last_tick = now
        q = self.ring.delta_quantiles((0.99,))
        if not q:
            # no traffic this window: decay toward open admission so an
            # idle (or fully-shed) pool can recover
            self.shed_fraction *= r.shed_decay
            if self.shed_fraction < 0.01:
                self.shed_fraction = 0.0
        else:
            self.windowed_p99_ms = q[0.99] * 1e3
            r._g_p99.set(self.windowed_p99_ms, model=self.name)
            if self.slo_ms is not None \
                    and self.windowed_p99_ms > self.slo_ms:
                self.shed_fraction = min(
                    r.shed_max, self.shed_fraction + r.shed_step)
            else:
                self.shed_fraction *= r.shed_decay
                if self.shed_fraction < 0.01:
                    self.shed_fraction = 0.0
        r._g_shed_fraction.set(self.shed_fraction, model=self.name)

    def admit(self, deadline: Optional[Deadline] = None):
        """Raise ``ShedError`` or return (never blocks, never queues).
        An already-expired ``deadline`` sheds here — reason
        ``"deadline"`` — before the request can consume a pending slot
        or an engine queue entry."""
        r = self.router
        if deadline is not None and deadline.expired:
            r._c_shed.inc(1.0, model=self.name, reason="deadline")
            raise ShedError(
                self.name, "deadline",
                "deadline expired before admission")
        with self.lock:
            self._tick_controller(time.monotonic())
            if self.pending >= r.max_pending:
                r._c_shed.inc(1.0, model=self.name, reason="queue")
                raise ShedError(
                    self.name, "queue",
                    f"{self.pending} pending >= bound {r.max_pending}")
            if self.shed_fraction > 0.0 \
                    and self._rand.random() < self.shed_fraction:
                r._c_shed.inc(1.0, model=self.name, reason="slo")
                raise ShedError(
                    self.name, "slo",
                    f"windowed p99 {self.windowed_p99_ms:.1f} ms over "
                    f"SLO {self.slo_ms:.1f} ms; shedding "
                    f"{self.shed_fraction:.0%} of arrivals")
            self.pending += 1  # graftlint: disable=release-discipline: released by submit()'s error path and the completion callback in _dispatch (cross-method by design)
            r._g_depth.set(self.pending, model=self.name)
        r._c_admitted.inc(1.0, model=self.name)

    # ---- dispatch --------------------------------------------------------
    def least_loaded(self) -> ServingEngine:
        with self.lock:
            return min(self.engines, key=lambda e: e.inflight)

    def submit(self, features,
               deadline: Optional[Deadline] = None) -> Future:
        self.admit(deadline)
        t0 = time.perf_counter()
        try:
            f = self.least_loaded().submit(features, deadline=deadline)
        except BaseException:
            with self.lock:
                self.pending -= 1
                self.router._g_depth.set(self.pending, model=self.name)
            raise

        def done(_f):
            self.ring.record(time.perf_counter() - t0)
            with self.lock:
                self.pending -= 1
                self.router._g_depth.set(self.pending, model=self.name)
        f.add_done_callback(done)
        return f

    def stats(self) -> Dict[str, Any]:
        with self.lock:
            engines = list(self.engines)
            out = {
                "active_version": self.active_version,
                "standby_version": self.standby[0] if self.standby
                else None,
                "param_standby_version": self.param_standby[0]
                if self.param_standby else None,
                "pool_size": len(engines),
                "pending": self.pending,
                "shed_fraction": self.shed_fraction,
                "windowed_p99_ms": self.windowed_p99_ms,
                "slo_ms": self.slo_ms,
            }
        out["requests"] = self.ring.count
        out["latency_ms"] = {f"p{int(k * 100)}": v * 1e3
                             for k, v in self.ring.quantiles().items()}
        out["engines"] = [{"session": e.session_id,
                           "precision": e.precision.tag,
                           "inflight": e.inflight,
                           "recompiles_after_warmup":
                               e.recompiles_after_warmup,
                           "warmup_s": e.warmup_seconds}
                          for e in engines]
        if self.gate_results:
            out["quant_gate"] = self.gate_results[-1].summary()
        return out


class GenerationPool:
    """Admission-controlled front for one GenerationEngine (decode
    serving — generation/engine.py). Shares ModelPool's AIMD controller
    verbatim, but the latency signal is the engine's per-TOKEN ring and
    the SLO is ``slo_token_ms``: decode sheds when the *token cadence*
    degrades, not when whole-sequence wall time (which scales with
    requested length) does. ``pending`` counts sequences from admission
    until their stream finishes — a long-lived stream holds its
    admission slot the whole way, so the queue bound caps concurrent
    sequences, not just the submit burst.

    The int8 accuracy story needs no gate here: the engine itself runs
    the decode-level quant gate (next-token agreement vs the f32 head)
    at construction and refuses to build on a miss, so an int8
    generation pool that exists has already passed."""

    def __init__(self, name: str, router: "FleetRouter", engine,
                 slo_token_ms: Optional[float] = None):
        self.name = name
        self.router = router
        self.engine = engine
        self.slo_ms = slo_token_ms
        self.ring = engine.token_ring   # recorded by the engine per tick
        self.lock = threading.Lock()
        self.pending = 0
        self.shed_fraction = 0.0
        self.windowed_p99_ms: Optional[float] = None
        self._last_tick = time.monotonic()
        self._rand = random.Random()

    # same AIMD + admission body as ModelPool — the fields line up by
    # construction, and sharing the code keeps the two front doors'
    # shedding behavior from drifting apart
    _tick_controller = ModelPool._tick_controller
    admit = ModelPool.admit

    def submit(self, prompt, deadline: Optional[Deadline] = None, **kw):
        """Admit, then queue on the engine; returns the
        GenerationStream. An engine-side queue-full becomes a
        ``ShedError(reason="queue")`` like any other admission refusal.
        """
        self.admit(deadline)
        r = self.router
        try:
            stream = self.engine.submit(prompt, deadline=deadline, **kw)
        except BaseException as e:
            with self.lock:
                self.pending -= 1
                r._g_depth.set(self.pending, model=self.name)
            if isinstance(e, RuntimeError) and "queue full" in str(e):
                r._c_shed.inc(1.0, model=self.name, reason="queue")
                raise ShedError(self.name, "queue", str(e))
            raise

        def done(_s):
            with self.lock:
                self.pending -= 1
                r._g_depth.set(self.pending, model=self.name)
        stream.add_done_callback(done)
        return stream

    def stats(self) -> Dict[str, Any]:
        with self.lock:
            out = {
                "pending": self.pending,
                "shed_fraction": self.shed_fraction,
                "windowed_token_p99_ms": self.windowed_p99_ms,
                "slo_token_ms": self.slo_ms,
            }
        out["engine"] = self.engine.stats()
        return out


class RetrievalPool:
    """Admission-controlled front for one RetrievalEngine (nearest-
    neighbor serving — retrieval/engine.py). Shares ModelPool's AIMD
    controller verbatim; the latency signal is the engine's per-QUERY
    ring and the SLO is whole-query wall time (fan-out + merge
    included). ``pending`` counts admitted searches until they return —
    search is synchronous, so the queue bound caps concurrent
    searches."""

    def __init__(self, name: str, router: "FleetRouter", engine,
                 slo_ms: Optional[float] = None):
        self.name = name
        self.router = router
        self.engine = engine
        self.slo_ms = slo_ms
        self.ring = engine.query_ring   # recorded by the engine per search
        self.lock = threading.Lock()
        self.pending = 0
        self.shed_fraction = 0.0
        self.windowed_p99_ms: Optional[float] = None
        self._last_tick = time.monotonic()
        self._rand = random.Random()

    # same AIMD + admission body as ModelPool (see GenerationPool's
    # note: sharing the code keeps the front doors' shedding behavior
    # from drifting apart)
    _tick_controller = ModelPool._tick_controller
    admit = ModelPool.admit

    def search(self, queries, k: int,
               mode: Optional[str] = None,
               deadline: Optional[Deadline] = None, **kw):
        """Admit, then run the engine search; returns
        ``(distances, ids)``. Synchronous — the admission slot is held
        for the whole fan-out + merge."""
        self.admit(deadline)
        r = self.router
        try:
            return self.engine.search(queries, k, mode=mode,
                                      deadline=deadline, **kw)
        finally:
            with self.lock:
                self.pending -= 1
                r._g_depth.set(self.pending, model=self.name)

    def stats(self) -> Dict[str, Any]:
        with self.lock:
            out = {
                "pending": self.pending,
                "shed_fraction": self.shed_fraction,
                "windowed_p99_ms": self.windowed_p99_ms,
                "slo_ms": self.slo_ms,
            }
        out["engine"] = self.engine.stats()
        return out


class FleetRouter:
    """Front door over named ModelPools. Thread-safe."""

    def __init__(self, *, slo_ms: Optional[float] = None,
                 max_pending: Optional[int] = None,
                 window_s: Optional[float] = None,
                 aot_cache_dir: Optional[str] = None,
                 tuned_config=None,
                 registry=None, session_id: str = "fleet"):
        self.slo_ms = slo_ms
        self.session_id = session_id
        self.aot_cache_dir = aot_cache_dir
        # threaded into every pool's engines (unless the pool's own
        # engine_kwargs override): one tuned artifact sizes the fleet
        self.tuned_config = tuned_config
        self.registry = registry if registry is not None \
            else default_registry()
        self.window_s = window_s if window_s is not None \
            else _env_float("DL4J_FLEET_WINDOW_S", 1.0)
        self.shed_step = _env_float("DL4J_FLEET_SHED_STEP", 0.2)
        self.shed_decay = _env_float("DL4J_FLEET_SHED_DECAY", 0.5)
        self.shed_max = min(0.999,
                            _env_float("DL4J_FLEET_SHED_MAX", 0.95))
        self.max_pending = int(max_pending) if max_pending is not None \
            else int(_env_float("DL4J_FLEET_MAX_PENDING", 256))
        self._pools: Dict[str, ModelPool] = {}
        self._gen_pools: Dict[str, GenerationPool] = {}
        self._retr_pools: Dict[str, RetrievalPool] = {}
        self._pools_lock = threading.Lock()
        self._shutdown = False

        reg = self.registry
        self._c_admitted = reg.counter(
            "dl4j_fleet_admitted_total",
            "requests admitted past the fleet front door, per model")
        self._c_shed = reg.counter(
            "dl4j_fleet_shed_total",
            "requests shed by admission control, per model; reason="
            "queue (pending bound) | slo (p99-over-SLO shedding) | "
            "deadline (expired before admission)")
        self._c_swap = reg.counter(
            "dl4j_fleet_swap_total",
            "model-version swaps, per model; event=swap|rollback")
        self._c_gate = reg.counter(
            "dl4j_fleet_quant_gate_total",
            "quantization accuracy-gate runs before a version is "
            "admitted, per model; outcome=pass|fail")
        self._g_depth = reg.gauge(
            "dl4j_fleet_pool_depth",
            "requests submitted to a pool and not yet answered")
        self._g_shed_fraction = reg.gauge(
            "dl4j_fleet_shed_fraction",
            "current SLO-shedding fraction of the pool's arrivals")
        self._g_p99 = reg.gauge(
            "dl4j_fleet_p99_ms",
            "windowed p99 over the last controller tick's completions")
        self._g_engines = reg.gauge(
            "dl4j_fleet_pool_engines",
            "engines in the pool's active version")

    # ---- pool management -------------------------------------------------
    def _run_quant_gate(self, name: str, model, version: str,
                        engine_kwargs: Dict[str, Any], quant_gate):
        """The hard accuracy gate on the warm-swap path: an int8 pool
        with a gate configured must pass its quantized-vs-f32 budget
        BEFORE any engine is built — a failing version never warms,
        never flips, and the active version is untouched. Returns the
        GateResult (None when not applicable)."""
        precision = engine_kwargs.get("precision")
        if quant_gate is None \
                or getattr(precision, "mode", precision) != "int8":
            return None
        from deeplearning4j_tpu.evaluation.quant_gate import (
            QuantGateError, enforce_quant_gate)
        try:
            result = enforce_quant_gate(
                model, precision, quant_gate,
                model_name=f"{name}:{version}", registry=self.registry)
        except QuantGateError:
            self._c_gate.inc(1.0, model=name, outcome="fail")
            raise
        self._c_gate.inc(1.0, model=name, outcome="pass")
        return result

    def _build_engines(self, name: str, model, version: str,
                       engine_kwargs: Dict[str, Any], pool_size: int,
                       quant_gate=None
                       ) -> Tuple[List[ServingEngine], Any]:
        model = _materialize(model, name)
        gate_result = self._run_quant_gate(name, model, version,
                                           engine_kwargs, quant_gate)
        engines = []
        kw = dict(engine_kwargs)
        if self.aot_cache_dir is not None:
            kw.setdefault("aot_cache_dir",
                          os.path.join(self.aot_cache_dir, name))
        if self.tuned_config is not None:
            kw.setdefault("tuned_config", self.tuned_config)
        kw.setdefault("registry", self.registry)
        for i in range(pool_size):
            engines.append(ServingEngine(
                model, model_version=version,
                session_id=f"{self.session_id}-{name}-{version}-{i}",
                **kw))
        return engines, gate_result

    def add_pool(self, name: str, model, *, version: str = "v1",
                 pool_size: int = 1, slo_ms: Optional[float] = None,
                 quant_gate=None, **engine_kwargs) -> ModelPool:
        """Create and warm a pool. ``model`` may be a built model, a
        ZooModel instance/class, a zoo entry name, or a factory.
        ``quant_gate`` (a QuantGate) makes the int8 accuracy gate a
        hard precondition for this pool — at creation AND at every
        ``swap`` — when the engines run precision int8."""
        if pool_size < 1:
            raise ValueError("pool_size must be >= 1")
        with self._pools_lock:
            if name in self._pools:
                raise ValueError(f"pool {name!r} already exists")
        pool = ModelPool(name, self, engine_kwargs, pool_size,
                         slo_ms if slo_ms is not None else self.slo_ms,
                         quant_gate=quant_gate)
        pool.engines, gate_result = self._build_engines(
            name, model, version, engine_kwargs, pool_size,
            quant_gate=quant_gate)
        if gate_result is not None:
            pool.gate_results.append(gate_result)
        pool.active_version = version
        with self._pools_lock:
            self._pools[name] = pool
        self._g_engines.set(pool_size, model=name)
        self._g_depth.set(0.0, model=name)
        self._c_admitted.inc(0.0, model=name)
        return pool

    def pool(self, name: Optional[str] = None) -> ModelPool:
        with self._pools_lock:
            if name is None:
                if len(self._pools) != 1:
                    raise ValueError(
                        "model name required: the router serves "
                        f"{sorted(self._pools)}")
                return next(iter(self._pools.values()))
            p = self._pools.get(name)
        if p is None:
            raise ValueError(f"no pool named {name!r}; have "
                             f"{sorted(self._pools)}")
        return p

    @property
    def pools(self) -> Dict[str, ModelPool]:
        with self._pools_lock:
            return dict(self._pools)

    # ---- serving ---------------------------------------------------------
    def submit(self, features, model: Optional[str] = None,
               deadline: Optional[Deadline] = None) -> Future:
        if self._shutdown:
            raise RuntimeError("FleetRouter is shut down")
        return self.pool(model).submit(features, deadline=deadline)

    def output(self, features, model: Optional[str] = None,
               deadline: Optional[Deadline] = None):
        return self.submit(features, model=model,
                           deadline=deadline).result()

    # ---- generative serving ----------------------------------------------
    def add_generation_pool(self, name: str, engine, *,
                            slo_token_ms: Optional[float] = None
                            ) -> GenerationPool:
        """Register a GenerationEngine behind the same admission front
        door as the predict pools (shared ``dl4j_fleet_*`` series, same
        env knobs). ``slo_token_ms`` arms AIMD shedding over the
        engine's windowed per-token p99."""
        with self._pools_lock:
            if name in self._gen_pools or name in self._pools:
                raise ValueError(f"pool {name!r} already exists")
        pool = GenerationPool(name, self, engine,
                              slo_token_ms=slo_token_ms)
        with self._pools_lock:
            self._gen_pools[name] = pool
        self._g_depth.set(0.0, model=name)
        self._c_admitted.inc(0.0, model=name)
        return pool

    def generation_pool(self, name: Optional[str] = None
                        ) -> GenerationPool:
        with self._pools_lock:
            if name is None:
                if len(self._gen_pools) != 1:
                    raise ValueError(
                        "model name required: the router serves "
                        f"generation pools {sorted(self._gen_pools)}")
                return next(iter(self._gen_pools.values()))
            p = self._gen_pools.get(name)
        if p is None:
            raise ValueError(f"no generation pool named {name!r}; "
                             f"have {sorted(self._gen_pools)}")
        return p

    @property
    def generation_pools(self) -> Dict[str, GenerationPool]:
        with self._pools_lock:
            return dict(self._gen_pools)

    def generate(self, prompt, model: Optional[str] = None,
                 deadline: Optional[Deadline] = None, **kw):
        """Admission-controlled decode submit; returns the stream.

        A ``session=`` token routes with affinity when no model is
        named: the pool already holding the carry locally (device tier
        beats host tier) wins, so multi-turn sessions keep resuming
        without a store round-trip; a cold token lands on any pool
        with a session store, which resumes it from the shared
        checkpoint — the cross-node path."""
        if self._shutdown:
            raise RuntimeError("FleetRouter is shut down")
        if model is None and kw.get("session") is not None:
            pool = self._session_affinity(kw["session"])
            if pool is not None:
                return pool.submit(prompt, deadline=deadline, **kw)
        return self.generation_pool(model).submit(
            prompt, deadline=deadline, **kw)

    def _session_affinity(self, token: str
                          ) -> Optional[GenerationPool]:
        with self._pools_lock:
            pools = list(self._gen_pools.values())
        tier_rank = {"device": 3, "host": 2}
        best, best_rank = None, 0
        for p in pools:
            store = getattr(p.engine, "session_store", None)
            if store is None:
                continue
            rank = tier_rank.get(store.resident(token), 1)
            if rank > best_rank:
                best, best_rank = p, rank
        return best

    # ---- retrieval serving -----------------------------------------------
    def add_retrieval_pool(self, name: str, engine, *,
                           slo_ms: Optional[float] = None
                           ) -> RetrievalPool:
        """Register a RetrievalEngine behind the same admission front
        door as the predict pools (shared ``dl4j_fleet_*`` series, same
        env knobs). ``slo_ms`` arms AIMD shedding over the engine's
        windowed per-query p99."""
        with self._pools_lock:
            if name in self._retr_pools or name in self._pools \
                    or name in self._gen_pools:
                raise ValueError(f"pool {name!r} already exists")
        pool = RetrievalPool(name, self, engine, slo_ms=slo_ms)
        with self._pools_lock:
            self._retr_pools[name] = pool
        self._g_depth.set(0.0, model=name)
        self._c_admitted.inc(0.0, model=name)
        return pool

    def retrieval_pool(self, name: Optional[str] = None
                       ) -> RetrievalPool:
        with self._pools_lock:
            if name is None:
                if len(self._retr_pools) != 1:
                    raise ValueError(
                        "model name required: the router serves "
                        f"retrieval pools {sorted(self._retr_pools)}")
                return next(iter(self._retr_pools.values()))
            p = self._retr_pools.get(name)
        if p is None:
            raise ValueError(f"no retrieval pool named {name!r}; "
                             f"have {sorted(self._retr_pools)}")
        return p

    @property
    def retrieval_pools(self) -> Dict[str, RetrievalPool]:
        with self._pools_lock:
            return dict(self._retr_pools)

    def neighbors(self, queries, k: int,
                  model: Optional[str] = None,
                  mode: Optional[str] = None,
                  deadline: Optional[Deadline] = None, **kw):
        """Admission-controlled nearest-neighbor search; returns
        ``(distances, ids)``."""
        if self._shutdown:
            raise RuntimeError("FleetRouter is shut down")
        return self.retrieval_pool(model).search(
            queries, k, mode=mode, deadline=deadline, **kw)

    # ---- version lifecycle -----------------------------------------------
    def swap(self, name: str, model, version: str) -> ModelPool:
        """A/B weight swap: build + warm ``version``'s engines, switch
        the active pointer atomically, keep the previous version warm as
        the rollback standby, and shut down anything older. In-flight
        requests on the old version complete normally. A pool created
        with ``quant_gate`` re-runs the accuracy gate here: a failing
        quantized version raises before any engine is built and the
        active version keeps serving."""
        pool = self.pool(name)
        new_engines, gate_result = self._build_engines(
            name, model, version, pool.engine_kwargs, pool.pool_size,
            quant_gate=pool.quant_gate)
        if gate_result is not None:
            pool.gate_results.append(gate_result)
        with pool.lock:
            retired = pool.standby
            pool.standby = (pool.active_version, pool.engines)
            pool.engines = new_engines
            pool.active_version = version
            # stale latencies must not drive the new version's shedding
            pool.ring.reset()
        self._c_swap.inc(1.0, model=name, event="swap")
        self._g_engines.set(len(new_engines), model=name)
        if retired is not None:
            for e in retired[1]:
                e.shutdown()
        return pool

    def rollback(self, name: str) -> ModelPool:
        """Switch back to the standby version (the one ``swap`` retired
        to warm standby). The rolled-back-from version becomes the new
        standby, so a flapping rollout can flip repeatedly."""
        pool = self.pool(name)
        with pool.lock:
            if pool.standby is None:
                raise RuntimeError(
                    f"pool {name!r} has no standby version to roll "
                    "back to")
            (pool.active_version, pool.engines), pool.standby = \
                pool.standby, (pool.active_version, pool.engines)
            pool.ring.reset()
        self._c_swap.inc(1.0, model=name, event="rollback")
        self._g_engines.set(len(pool.engines), model=name)
        return pool

    # ---- param-only promotion (online learning) --------------------------
    def promote_params(self, name: str, params, model_state=None, *,
                       version: Optional[str] = None) -> ModelPool:
        """Param-only hot promotion: push new weights into the pool's
        warm engines via ``ServingEngine.swap_params`` — **zero
        recompiles**, no new engines, no warmup sweep. The previous
        committed params are captured host-side first and kept as
        ``pool.param_standby`` (the ``rollback_params`` target).

        Each engine's swap is individually atomic; across a multi-
        engine pool there is a brief window where engines serve
        different param versions (same structure, so every request
        still completes normally). Structural validation happens on the
        first engine before anything is overwritten — a mismatched
        candidate raises with the whole pool untouched."""
        pool = self.pool(name)
        with pool.lock:
            engines = list(pool.engines)
            old_version = pool.active_version
        if not engines:
            raise RuntimeError(f"pool {name!r} has no engines")
        standby_params, standby_mstate = engines[0].committed_host()
        for e in engines:
            e.swap_params(params, model_state, version=version)
        with pool.lock:
            pool.param_standby = (old_version, standby_params,
                                  standby_mstate)
            if version is not None:
                pool.active_version = version
            # pre-promotion latencies must not drive the new params'
            # shedding / regression verdicts
            pool.ring.reset()
        self._c_swap.inc(1.0, model=name, event="param_swap")
        return pool

    def rollback_params(self, name: str) -> ModelPool:
        """Restore the ``param_standby`` captured by the last
        ``promote_params`` — bitwise-identical host copies pushed back
        through the same warm executables. The rolled-back-from params
        become the new standby, so a flapping promotion can flip
        repeatedly."""
        pool = self.pool(name)
        with pool.lock:
            standby = pool.param_standby
            engines = list(pool.engines)
            old_version = pool.active_version
        if standby is None:
            raise RuntimeError(
                f"pool {name!r} has no param standby to roll back to")
        if not engines:
            raise RuntimeError(f"pool {name!r} has no engines")
        sv, sp, sm = standby
        current_params, current_mstate = engines[0].committed_host()
        for e in engines:
            e.swap_params(sp, sm, version=sv)
        with pool.lock:
            pool.param_standby = (old_version, current_params,
                                  current_mstate)
            if sv is not None:
                pool.active_version = sv
            pool.ring.reset()
        self._c_swap.inc(1.0, model=name, event="param_rollback")
        return pool

    # ---- introspection ---------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        out = {
            "session": self.session_id,
            "slo_ms": self.slo_ms,
            "max_pending": self.max_pending,
            "window_s": self.window_s,
            "pools": {name: p.stats()
                      for name, p in self.pools.items()},
        }
        gen = self.generation_pools
        if gen:
            out["generation"] = {name: p.stats()
                                 for name, p in gen.items()}
        retr = self.retrieval_pools
        if retr:
            out["retrieval"] = {name: p.stats()
                                for name, p in retr.items()}
        return out

    def assert_warm(self):
        """Every engine in every pool (active + standby) holds the
        zero-live-compile contract."""
        for pool in self.pools.values():
            with pool.lock:
                engines = list(pool.engines)
                if pool.standby is not None:
                    engines += list(pool.standby[1])
            for e in engines:
                e.assert_warm()
        for gp in self.generation_pools.values():
            gp.engine.assert_warm()
        for rp in self.retrieval_pools.values():
            rp.engine.assert_warm()

    # ---- lifecycle -------------------------------------------------------
    def shutdown(self):
        self._shutdown = True
        for pool in self.pools.values():
            with pool.lock:
                engines = list(pool.engines)
                if pool.standby is not None:
                    engines += list(pool.standby[1])
                pool.standby = None
            for e in engines:
                e.shutdown()
        for gp in self.generation_pools.values():
            gp.engine.shutdown()
        for rp in self.retrieval_pools.values():
            rp.engine.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
