"""ServingEngine: pipelined batched inference with warmed bucket
executables and multi-replica fan-out.

The seed dispatcher (parallel/inference.py pre-PR5) host-synced on
the model output fetch inside its batching loop, so the queue
drained at device-roundtrip latency, every bucket paid first-request
compile cost, and a request larger than ``batch_limit`` minted an
unbounded set of pow2 executables. This engine replaces it with five
coordinated pieces:

1. **Pipelined dispatch.** The dispatcher thread issues the compiled
   forward and hands the still-on-device result (plus its waiters) to a
   completion thread over a bounded pipe; JAX async dispatch means batch
   N+1 is being formed and issued while batch N computes and its
   device→host fetch completes — the same double-buffer discipline as
   ``datasets/feeder.py``. The pipe's bound doubles as the aggregation
   policy: while the device is busy (pipe full) the dispatcher keeps
   coalescing arrivals up to ``timeout_ms``; the moment a slot frees it
   dispatches what it has. The seed's fixed aggregation window — which
   idled the device for the full ``timeout_ms`` whenever offered load
   sat below ``batch_limit`` — survives only as the upper bound.
2. **Committed inference params.** Parameters and model state are
   ``device_put`` once at engine start (optionally cast to bf16), per
   replica and — for the sharded path — replicated over the mesh. No
   per-call reliance on the global trace cache keyed off
   ``model.train_state``: the engine owns an explicit per-bucket
   executable table (AOT ``jit.lower(...).compile()``, falling back to
   the jitted call where AOT is unavailable).
3. **Bounded bucket ladder + request splitting.** Batches pad to the
   smallest power-of-two bucket in ``[min_bucket, batch_limit]``;
   oversized requests are split across dispatches at ``output()`` and
   reassembled, so the executable table is bounded by the ladder no
   matter what arrives. A warmup sweep over the ladder at start means
   no live request ever pays a compile (``recompiles_after_warmup``
   asserts it; the RecompileWatchdog sees every dispatch signature).
4. **Multi-replica fan-out.** With R > 1 visible devices, full
   ``batch_limit`` buckets shard data-parallel across the mesh
   (parallel/mesh.py); partial buckets round-robin whole replicas.
   Per-replica dispatch and busy-time counters feed utilization gauges.
5. **Tail-latency observability.** Per-request ``queue_wait`` and
   per-batch ``batch_form``/``dispatch``/``device``/``fetch`` spans ride
   the SpanTracer; streaming p50/p95/p99 (observe/latency.py), in-flight
   depth, queue depth, batch occupancy and ``dl4j_serving_*`` series
   publish to the Prometheus registry scraped at ``/metrics``.

The reference analog is ParallelInference.java:35 (SURVEY §2.11) — its
model-per-GPU workers become replicas here; ``parallel/inference.py``
keeps the ParallelInference facade on top of this engine.

Numerical contract: a request's rows are computed at the bucket shape
and sliced back, so padded and split requests are bitwise-equal to the
direct ``model.output`` call. A request CO-BATCHED with other callers
runs at whatever bucket the batch lands in; on backends whose matmul
kernel selection depends on the batch dimension (CPU gemv vs gemm)
that can shift results by ~1 ulp vs the exact-size direct call.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, NamedTuple, Optional, Tuple, Union

import numpy as np

from deeplearning4j_tpu.chaos.hook import chaos_site
from deeplearning4j_tpu.observe.latency import LatencyRing
from deeplearning4j_tpu.observe.recompile import RecompileWatchdog
from deeplearning4j_tpu.observe.registry import default_registry
from deeplearning4j_tpu.observe.tracer import NULL_TRACER
from deeplearning4j_tpu.parallel.deadline import (Deadline,
                                                  DeadlineExceeded)

MESH = "mesh"            # dispatch-target key for the sharded full bucket


class _Request(NamedTuple):
    """One enqueued chunk: host features, its waiter, arrival time,
    and the caller's remaining-budget deadline (None = unbounded)."""
    x: np.ndarray
    future: Future
    t_enqueue: float
    deadline: Optional[Deadline] = None


class _InFlight(NamedTuple):
    """A dispatched batch travelling dispatcher -> completion thread."""
    out: Any                 # device-resident result (un-fetched)
    requests: List[_Request]
    n_real: int
    bucket: int
    where: Union[int, str]
    t_dispatched: float


class ServingEngine:
    """Thread-safe batched inference over one model's committed params.

    Parameters
    ----------
    model : MultiLayerNetwork / single-io ComputationGraph (must expose
        ``build_inference_fn``)
    batch_limit : max examples per dispatch; also the ladder's top bucket
    queue_limit : bound on queued request chunks (producers block)
    timeout_ms : UPPER bound on batch aggregation; the pipelined engine
        only waits at all while the completion pipe is full
    depth : in-flight batches handed to the completion thread (the
        double-buffer depth; 1 = aggregate exactly while device is busy)
    pipelined : False reproduces the seed's blocking dispatcher (fixed
        aggregation window + inline fetch) — kept for the benchmark A/B
    replicas : device count to serve on; "auto" = all visible devices
    feature_shape : per-example feature shape (no batch dim); providing
        it (with ``dtype``) enables the warmup sweep at start
    dtype : feature dtype requests are cast to (default float32)
    precision : a ``PrecisionPolicy`` (or its mode string) selecting the
        committed-params precision: "f32" (default), "bf16" (cast the
        inference copy to bfloat16), or "int8" (post-training quantized
        via parallel/quant.py — the policy must carry calibration
        ``samples``; the model's train_state is untouched in all modes)
    bf16 : DEPRECATED — the pre-PrecisionPolicy spelling of
        ``precision=PrecisionPolicy.bf16()``; passing it warns
    warmup : compile the whole bucket ladder at start (default: True
        when ``feature_shape`` is known)
    aot_cache_dir : persist the warmed executable table here
        (parallel/aot_cache.py): the first process exports + saves the
        ladder after its sweep; later processes reach ``assert_warm()``
        in a fraction of the sweep time by deserializing StableHLO blobs
        and hitting the XLA persistent compilation cache. Any
        fingerprint mismatch (weights, jaxlib, backend, shapes) falls
        through to live compile.
    model_version : opaque version string folded into the cache
        fingerprint (the fleet router's swap path sets it)
    """

    def __init__(self, model, *, batch_limit: Optional[int] = None,
                 queue_limit: int = 128, timeout_ms: float = 5.0,
                 depth: int = 1, pipelined: bool = True,
                 replicas: Union[int, str] = 1,
                 min_bucket: int = 1,
                 feature_shape: Optional[Tuple[int, ...]] = None,
                 dtype: Any = np.float32, bf16: bool = False,
                 precision: Any = None,
                 warmup: Optional[bool] = None,
                 aot_cache_dir: Optional[str] = None,
                 model_version: Optional[str] = None,
                 tuned_config=None,
                 tracer=None, registry=None, watchdog=None,
                 session_id: str = "serve"):
        import jax
        # explicit batch_limit > TunedConfig (this engine's, else the
        # process-wide one) > the committed default of 32 — the autotune
        # resolution ladder; an engine that never sees a tuned config
        # behaves exactly as before
        from deeplearning4j_tpu.optimize.autotune import resolve_tuned
        batch_limit = int(resolve_tuned(batch_limit, tuned_config,
                                        "serving.batch_limit"))
        self.tuned_config = tuned_config
        if batch_limit < 1:
            raise ValueError("batch_limit must be >= 1")
        if not 1 <= min_bucket <= batch_limit:
            raise ValueError("need 1 <= min_bucket <= batch_limit")
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self.model = model
        self.batch_limit = int(batch_limit)
        self.timeout_ms = float(timeout_ms)  # host-sync-ok: Python config scalar, not a device value
        self.depth = int(depth)
        self.pipelined = bool(pipelined)
        self.session_id = session_id
        self.dtype = np.dtype(dtype)
        self.feature_shape = (None if feature_shape is None
                              else tuple(feature_shape))
        from deeplearning4j_tpu.parallel.quant import PrecisionPolicy
        if precision is None:
            if bf16:
                import warnings
                warnings.warn(
                    "ServingEngine(bf16=True) is deprecated; pass "
                    "precision=PrecisionPolicy.bf16() instead",
                    DeprecationWarning, stacklevel=2)
                precision = PrecisionPolicy.bf16()
            else:
                precision = PrecisionPolicy.f32()
        else:
            if bf16:
                raise ValueError(
                    "pass either precision= or the deprecated bf16= "
                    "flag, not both")
            if isinstance(precision, str):
                precision = PrecisionPolicy(mode=precision)
        self.precision = precision
        self._ptag = precision.tag
        self.bf16 = precision.mode == "bf16"   # back-compat attribute
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.registry = registry if registry is not None \
            else default_registry()
        self.watchdog = watchdog if watchdog is not None else \
            RecompileWatchdog(self.registry, session_id=session_id)
        self.latency = LatencyRing()

        devs = jax.devices()
        n = len(devs) if replicas == "auto" else int(replicas)
        if not 1 <= n <= len(devs):
            raise ValueError(f"replicas={replicas!r} but {len(devs)} "
                             "devices are visible")
        self.devices = devs[:n]
        self.n_replicas = n

        # bounded pow2 ladder: min_bucket..batch_limit (limit included
        # even when it is not itself a power of two)
        ladder, b = [], 1 << (min_bucket - 1).bit_length()
        while b < self.batch_limit:
            ladder.append(b)
            b <<= 1
        ladder.append(self.batch_limit)
        self.ladder = ladder

        # ---- metrics -----------------------------------------------------
        reg = self.registry
        self._c_requests = reg.counter(
            "dl4j_serving_requests_total",
            "inference requests accepted by the serving engine")
        self._c_batches = reg.counter(
            "dl4j_serving_batches_total",
            "device batches dispatched by the serving engine")
        self._c_compiles = reg.counter(
            "dl4j_serving_compiles_total",
            "bucket executables compiled, by phase (warmup|live); a "
            "nonzero live count means a request paid a compile")
        self._g_inflight = reg.gauge(
            "dl4j_serving_inflight",
            "requests accepted but not yet answered")
        self._g_queue = reg.gauge(
            "dl4j_serving_queue_depth",
            "request chunks waiting for the dispatcher")
        self._g_occupancy = reg.gauge(
            "dl4j_serving_batch_occupancy",
            "real examples / bucket size of the last dispatched batch")
        self._g_latency = reg.gauge(
            "dl4j_serving_latency_ms",
            "streaming request latency quantiles over the last 4096 "
            "requests")
        self._c_replica_disp = reg.counter(
            "dl4j_serving_replica_dispatches_total",
            "batches dispatched per replica ('mesh' = sharded full "
            "buckets across all replicas)")
        self._c_replica_busy = reg.counter(
            "dl4j_serving_replica_busy_ms",
            "cumulative ms a replica spent computing dispatched batches")
        self._g_precision = reg.gauge(
            "dl4j_serving_precision",
            "1 for the engine's active precision label (f32|bf16|int8)")
        self._g_quant_err = reg.gauge(
            "dl4j_quant_layer_error",
            "per-layer relative L2 quantization error observed on the "
            "calibration probe batch (int8 engines only; layers over "
            "the policy budget fell back to f32)")
        self._c_deadline_shed = reg.counter(
            "dl4j_serving_deadline_shed_total",
            "requests shed because their deadline expired before "
            "device dispatch; stage=ingress|batch")
        self._c_requests.inc(0.0, session=session_id, precision=self._ptag)
        self._c_batches.inc(0.0, session=session_id, precision=self._ptag)
        self._c_compiles.inc(0.0, session=session_id, precision=self._ptag, phase="live")
        self._g_inflight.set(0.0, session=session_id, precision=self._ptag)
        self._g_precision.set(1.0, session=session_id,
                              precision=self._ptag)
        # $/req proxy accumulators (benchmarks/serving.py --precision-ab)
        self.dispatch_count = 0
        self.device_ms_total = 0.0

        # ---- committed inference params ----------------------------------
        # Duck-typed models exposing only .output() (pre-engine callers,
        # test doubles) skip the committed-params/AOT machinery and run
        # the legacy direct call under the same batching discipline.
        self._committed: Dict[Union[int, str], Any] = {}
        self._batch_sharding = None
        self._jit = None
        self.quantized = None        # QuantizedModel for int8 engines
        self._calib_hash: Optional[str] = None
        if hasattr(model, "build_inference_fn"):
            if model.train_state is None:
                model.init()
            params = model.train_state.params
            mstate = model.train_state.model_state
            if self.precision.mode == "int8":
                from deeplearning4j_tpu.parallel.quant import (
                    quantize_model)
                qm = quantize_model(model, self.precision,
                                    registry=self.registry,
                                    tracer=self.tracer)
                self.quantized = qm
                self._calib_hash = qm.calibration_hash()
                params = qm.params
                fwd = qm.build_inference_fn()
                for lname, rep in qm.report.items():
                    self._g_quant_err.set(
                        rep["error"], session=session_id, layer=lname,
                        quantized=str(rep["quantized"]).lower())
            else:
                if self.bf16:
                    import jax.numpy as jnp
                    params = jax.tree_util.tree_map(
                        lambda a: a.astype(jnp.bfloat16)
                        if jnp.issubdtype(a.dtype, jnp.floating) else a,
                        params)
                fwd = model.build_inference_fn()
            self._jit = jax.jit(lambda p, s, x: fwd(p, s, x, None))
            # one committed (params, model_state) copy per replica; plus
            # a mesh-replicated copy backing the sharded full-bucket path
            for r, dev in enumerate(self.devices):
                self._committed[r] = jax.device_put((params, mstate),
                                                    dev)
            if self.n_replicas > 1:
                from deeplearning4j_tpu.parallel.mesh import (
                    DATA_AXIS, batch_sharding, create_mesh, replicated)
                mesh = create_mesh({DATA_AXIS: self.n_replicas},
                                   self.devices)
                self._committed[MESH] = jax.device_put(
                    (params, mstate), replicated(mesh))
                self._batch_sharding = batch_sharding(mesh)
        elif self.n_replicas > 1 or self.precision.mode != "f32":
            raise ValueError(
                f"replicas > 1 / precision={self.precision.mode!r} "
                "need a model exposing build_inference_fn (committed "
                "per-replica params); "
                f"{type(model).__name__} only has .output")

        # ---- persisted AOT executable cache ------------------------------
        self.aot_cache = None
        self.model_version = model_version
        self._loaded_exports: Dict[int, Any] = {}
        self._cache_fp = None
        self._c_aot = reg.counter(
            "dl4j_serving_aot_cache_total",
            "persisted AOT executable cache events: hit = bucket "
            "loaded from a StableHLO blob, miss = fell through to live "
            "trace, save = bucket persisted after warmup")
        if aot_cache_dir is not None and self._jit is not None \
                and self.feature_shape is not None:
            from deeplearning4j_tpu.parallel.aot_cache import (
                AOTExecutableCache, fingerprint)
            self.aot_cache = AOTExecutableCache(aot_cache_dir)
            params0, mstate0 = self._committed[0]
            self._cache_fp = fingerprint(
                params0, mstate0, feature_shape=self.feature_shape,
                dtype=self.dtype, ladder=self.ladder,
                precision=self._ptag, calibration=self._calib_hash,
                model_version=model_version)
            self._loaded_exports = self.aot_cache.try_load(self._cache_fp)
            if (self.aot_cache.state == "mismatch"
                    and self.precision.mode == "int8"):
                # a rejected quant cache is worth a breadcrumb: the
                # divergence reason (stale calibration? precision?)
                # rides into any later crash dump's context.json
                from deeplearning4j_tpu.observe.flight_recorder import (
                    default_flight_recorder)
                rec = default_flight_recorder()
                if rec is not None:
                    rec.note(f"aot_cache_rejected_{session_id}", {
                        "dir": str(aot_cache_dir),
                        "precision": self._ptag,
                        "calibration": self._calib_hash,
                        "reason": self.aot_cache.reason,
                    })

        # ---- dispatch machinery ------------------------------------------
        # executable table keyed (bucket, target, precision): precision
        # is per-engine today, but first-class in the key so quant and
        # f32 executables of co-resident engines can never collide
        self._exe: Dict[Tuple[int, Union[int, str], str], Any] = {}
        self._exe_lock = threading.Lock()
        self._chaos_dispatch = chaos_site("serve.dispatch")
        self._warmed = False
        self._post_warmup_compiles = 0
        self.param_swaps = 0
        self._rr = 0                       # round-robin replica cursor
        self._inflight_count = 0
        self._count_lock = threading.Lock()
        self._queue: "queue.Queue[_Request]" = queue.Queue(
            maxsize=queue_limit)
        # aggregation overflow; shared between the dispatcher
        # (_form_batch) and caller threads (_drain_queue via a shutdown
        # race, stats) — every touch goes through _carry_lock or the
        # parked request can be dropped or double-failed
        self._carry: Optional[_Request] = None
        self._carry_lock = threading.Lock()
        self._completions: "queue.Queue[Optional[_InFlight]]" = \
            queue.Queue(maxsize=self.depth)
        self._shutdown = threading.Event()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name=f"serving-dispatch-{session_id}")
        self._completer: Optional[threading.Thread] = None
        if self.pipelined:
            self._completer = threading.Thread(
                target=self._complete_loop, daemon=True,
                name=f"serving-complete-{session_id}")

        do_warmup = (self.feature_shape is not None if warmup is None
                     else bool(warmup))
        self.warmup_seconds = 0.0
        self.cache_save_seconds = 0.0
        if do_warmup:
            if self.feature_shape is None:
                raise ValueError("warmup needs feature_shape (and dtype)")
            t0 = time.perf_counter()
            self._warmup_sweep()
            self.warmup_seconds = time.perf_counter() - t0
            if (self.aot_cache is not None
                    and self.aot_cache.state in ("cold", "mismatch")):
                self.save_aot_cache()
        self._warmed = True
        self._dispatcher.start()
        if self._completer is not None:
            self._completer.start()

    # ---- bucket ladder ---------------------------------------------------
    def bucket_of(self, n: int) -> int:
        """Smallest ladder bucket >= n (n must be <= batch_limit)."""
        for b in self.ladder:
            if b >= n:
                return b
        raise ValueError(f"batch of {n} exceeds batch_limit "
                         f"{self.batch_limit}")

    def _target_for(self, bucket: int) -> Union[int, str]:
        """Full buckets shard across the mesh; everything else
        round-robins whole replicas."""
        if (bucket == self.batch_limit and self.n_replicas > 1
                and bucket % self.n_replicas == 0):
            return MESH
        t = self._rr % self.n_replicas
        self._rr += 1
        return t

    # ---- executables -----------------------------------------------------
    def _place(self, x: np.ndarray, where: Union[int, str]):
        import jax
        if where == MESH:
            return jax.device_put(x, self._batch_sharding)
        return jax.device_put(x, self.devices[where])

    def _get_exe(self, bucket: int, where: Union[int, str]):
        key = (bucket, where, self._ptag)
        exe = self._exe.get(key)
        if exe is not None:
            return exe
        with self._exe_lock:
            exe = self._exe.get(key)
            if exe is not None:
                return exe
            import jax
            params, mstate = self._committed[where]
            x = self._place(np.zeros((bucket,) + self.feature_shape,
                                     self.dtype), where)
            exe = None
            exp = (self._loaded_exports.get(bucket)
                   if where != MESH else None)
            if exp is not None:
                # persisted-cache path: compile the deserialized
                # StableHLO wrapper (no model re-trace; the XLA compile
                # itself is a persistent-cache disk hit, primed at save)
                try:
                    exe = jax.jit(exp.call).lower(params, mstate,
                                                  x).compile()
                    self.aot_cache.hits += 1
                    self._c_aot.inc(1.0, session=self.session_id, precision=self._ptag,
                                    event="hit")
                except Exception:
                    self.aot_cache.misses += 1
                    self._c_aot.inc(1.0, session=self.session_id, precision=self._ptag,
                                    event="miss")
            if exe is None:
                try:
                    exe = self._jit.lower(params, mstate, x).compile()
                except Exception:
                    # AOT unavailable (older jax / exotic shardings):
                    # the jitted call still caches one executable per
                    # signature
                    exe = self._jit
            self._exe[key] = exe
            phase = "warmup" if not self._warmed else "live"
            if self._warmed:
                self._post_warmup_compiles += 1
            self._c_compiles.inc(1.0, session=self.session_id, precision=self._ptag,
                                 phase=phase)
            self.tracer.instant("serve_compile", cat="serve",
                                bucket=bucket, where=str(where),
                                phase=phase)
            return exe

    def _warmup_sweep(self):
        """Compile the whole ladder for every dispatch target the live
        traffic can hit, so no request ever pays a compile."""
        t0 = time.perf_counter()
        for bucket in self.ladder:
            targets: List[Union[int, str]]
            if (bucket == self.batch_limit and self.n_replicas > 1
                    and bucket % self.n_replicas == 0):
                targets = [MESH]
            else:
                targets = list(range(self.n_replicas))
            for where in targets:
                x = np.zeros((bucket,) + self.feature_shape, self.dtype)
                out = self._run(x, bucket, where)
                # block so compile cost lands here, not on a request
                if hasattr(out, "block_until_ready"):
                    out.block_until_ready()  # host-sync-ok: warmup sweep is pre-traffic by design
        self.tracer.add_span("serve_warmup", t0, time.perf_counter(),
                             cat="serve", buckets=len(self.ladder),
                             replicas=self.n_replicas)

    def _run(self, x: np.ndarray, bucket: int, where: Union[int, str]):
        """Issue the compiled forward for one padded batch; returns the
        device-resident (un-fetched) result."""
        if x.dtype != self.dtype:
            x = x.astype(self.dtype)
        self.watchdog.observe(f"serve_fwd_{self._ptag}_b{bucket}", x)
        if self._jit is None:        # legacy duck-typed model
            return self.model.output(x)
        exe = self._get_exe(bucket, where)
        params, mstate = self._committed[where]
        return exe(params, mstate, self._place(x, where))

    # ---- public API ------------------------------------------------------
    def submit(self, features,
               deadline: Optional[Deadline] = None) -> Future:
        """Enqueue a request; the Future resolves to the (N, ...) host
        output. Oversized requests split across dispatches and
        reassemble transparently. An expired ``deadline`` sheds
        synchronously (DeadlineExceeded, never enqueued); one that
        expires while queued sheds at batch forming — either way the
        request never reaches the device."""
        x = np.asarray(features)  # host-sync-ok: serving ingress stages request features on host
        if x.ndim == 0 or x.shape[0] == 0:
            raise ValueError(
                "features must be a non-empty batch (got shape "
                f"{x.shape}); a single example is shape (1, ...)")
        if self.feature_shape is None:
            # first request fixes the wire contract
            self.feature_shape = x.shape[1:]
            if self.dtype is None:
                self.dtype = x.dtype
        elif x.shape[1:] != self.feature_shape:
            raise ValueError(
                f"request feature shape {x.shape[1:]} does not match "
                f"the engine's {self.feature_shape}")
        if x.dtype != self.dtype:
            x = x.astype(self.dtype)
        if self._shutdown.is_set():
            raise RuntimeError("ServingEngine is shut down")
        if deadline is not None and deadline.expired:
            self._c_deadline_shed.inc(1.0, session=self.session_id,
                                      precision=self._ptag,
                                      stage="ingress")
            raise DeadlineExceeded(
                "serving: deadline expired at ingress")
        chunks = [x[i:i + self.batch_limit]
                  for i in range(0, x.shape[0], self.batch_limit)]
        self._c_requests.inc(1.0, session=self.session_id, precision=self._ptag)
        with self._count_lock:
            self._inflight_count += 1  # graftlint: disable=release-discipline: released by the _track/_join_futures done-callbacks (cross-method by design); the error edge below releases inline
            self._g_inflight.set(self._inflight_count,
                                 session=self.session_id, precision=self._ptag)
        try:
            futures = [self._enqueue(c, deadline) for c in chunks]
        except BaseException:
            # _enqueue can raise on the shutdown race; without this
            # release the count never comes down and least-loaded
            # routing starves the engine forever
            with self._count_lock:
                self._inflight_count -= 1
                self._g_inflight.set(self._inflight_count,
                                     session=self.session_id,
                                     precision=self._ptag)
            raise
        if len(futures) == 1:
            self._track(futures[0])
            return futures[0]
        return self._join_futures(futures)

    def output(self, features,
               deadline: Optional[Deadline] = None) -> np.ndarray:
        """Blocking inference (reference: ParallelInference.output:113)."""
        return self.submit(features, deadline=deadline).result()

    def _enqueue(self, chunk: np.ndarray,
                 deadline: Optional[Deadline] = None) -> Future:
        f: Future = Future()
        req = _Request(chunk, f, time.perf_counter(), deadline)
        while True:
            if self._shutdown.is_set():
                raise RuntimeError("ServingEngine is shut down")
            try:
                # bounded wait so a full queue + dead worker can't block
                # the caller forever
                self._queue.put(req, timeout=0.1)
                break
            except queue.Full:
                continue
        self._g_queue.set(self._queue.qsize(), session=self.session_id, precision=self._ptag)
        if self._shutdown.is_set():
            # raced with shutdown(): the dispatcher may never pop this
            self._drain_queue()
        return f

    def _track(self, f: Future):
        def done(_):
            with self._count_lock:
                self._inflight_count -= 1
                self._g_inflight.set(self._inflight_count,
                                     session=self.session_id, precision=self._ptag)
        f.add_done_callback(done)

    def _join_futures(self, parts: List[Future]) -> Future:
        """One Future over a split request: concatenated result in chunk
        order, or the first chunk failure."""
        outer: Future = Future()
        self._track(outer)
        remaining = [len(parts)]
        lock = threading.Lock()

        def on_done(_f):
            with lock:
                remaining[0] -= 1
                last = remaining[0] == 0
            if not last or outer.done():
                return
            try:
                outer.set_result(
                    np.concatenate([p.result() for p in parts], axis=0))
            except Exception as e:
                outer.set_exception(e)
        for p in parts:
            p.add_done_callback(on_done)
        return outer

    @property
    def inflight(self) -> int:
        """Requests accepted but not yet answered (the fleet router's
        least-loaded dispatch key)."""
        return self._inflight_count

    def _peek_carry(self) -> Optional[_Request]:
        with self._carry_lock:
            return self._carry

    @property
    def params_resident_bytes(self) -> int:
        """Bytes of ONE committed params copy (int8 engines ~1/4 of
        f32) — the params term of the $/req proxy."""
        if not self._committed:
            return 0
        from deeplearning4j_tpu.parallel.quant import params_nbytes
        return params_nbytes(self._committed[0][0])

    def stats(self) -> Dict[str, Any]:
        """Point-in-time snapshot for the CLI / UI module."""
        q = self.latency.quantiles()
        out = {
            "session": self.session_id,
            "replicas": self.n_replicas,
            "ladder": list(self.ladder),
            "pipelined": self.pipelined,
            "precision": self._ptag,
            "params_resident_bytes": self.params_resident_bytes,
            "batches": self.dispatch_count,
            "device_ms_total": self.device_ms_total,
            "requests": self.latency.count,
            "inflight": self._inflight_count,
            # a carried-over request parked in self._carry is waiting
            # for the dispatcher exactly like a queued one — count it
            "queue_depth": self._queue.qsize()
            + (1 if self._peek_carry() is not None else 0),
            "recompiles_after_warmup": self._post_warmup_compiles,
            "warmup_s": self.warmup_seconds,
            "latency_ms": {f"p{int(k * 100)}": v * 1e3
                           for k, v in q.items()},
        }
        if self.aot_cache is not None:
            out["aot_cache"] = self.aot_cache.stats()
        if self.quantized is not None:
            out["quant"] = {
                "calibration": self._calib_hash,
                "error_budget": self.precision.error_budget,
                "fallback": list(self.quantized.fallback),
                "layers": {n: r["error"]
                           for n, r in self.quantized.report.items()},
            }
        return out

    def save_aot_cache(self) -> int:
        """Export + persist the warmed executable table (called
        automatically after the warmup sweep when the cache was cold or
        stale; callable explicitly after e.g. a weight update). Returns
        the number of buckets saved."""
        if (self.aot_cache is None or self._jit is None
                or self.feature_shape is None):
            return 0
        t0 = time.perf_counter()
        example = np.zeros((1,) + self.feature_shape, self.dtype)
        n = self.aot_cache.save(self._jit, self._committed[0],
                                self._cache_fp, self.ladder, example)
        self.cache_save_seconds = time.perf_counter() - t0
        if n:
            self._c_aot.inc(float(n),  # host-sync-ok: python int bucket count, not a device value
                            session=self.session_id, precision=self._ptag,
                            event="save")
        return n

    @property
    def recompiles_after_warmup(self) -> int:
        return self._post_warmup_compiles

    # ---- param-only hot swap ---------------------------------------------
    def committed_host(self) -> Tuple[Any, Any]:
        """Host copies of the committed ``(params, model_state)`` for
        replica 0 — the rollback standby snapshot. ``np.array`` copies,
        never views: on the CPU backend ``device_get`` can alias the
        live buffers, and a standby that shares storage with params
        about to be overwritten is no standby at all."""
        if not self._committed:
            raise ValueError(
                "legacy .output-only engines have no committed params")
        import jax
        return jax.tree_util.tree_map(
            lambda a: np.array(a, copy=True),
            jax.device_get(self._committed[0]))

    def swap_params(self, params, model_state=None, *,
                    version: Optional[str] = None) -> None:
        """Atomically replace the committed inference params without
        touching the executable table.

        Params are **traced arguments** of every bucket executable (not
        baked constants), so as long as the new tree matches the old
        one structurally — same treedef, same leaf shapes/dtypes — the
        warm AOT executables serve the new weights with **zero
        recompiles**. Structure is validated up front and a mismatch
        raises before anything is committed; the swap itself is one
        dict-reference assignment, so a dispatch racing the swap sees
        either the old committed set or the new one, never a mix.

        int8 engines refuse: quantized params bake calibration scales,
        so new weights need requantization (build a new engine — the
        fleet's warm-first ``swap`` path).
        """
        import jax
        if self._jit is None:
            raise ValueError(
                "legacy .output-only model: no committed params to swap")
        if self.precision.mode == "int8":
            raise ValueError(
                "int8 engines cannot hot-swap params (weights bake "
                "calibration scales); build a new engine and use the "
                "fleet swap path")
        old_params, old_mstate = self._committed[0]
        if model_state is None:
            model_state = old_mstate
        if self.bf16:
            import jax.numpy as jnp
            params = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16)
                if jnp.issubdtype(np.asarray(a).dtype,  # host-sync-ok: incoming host candidate, dtype probe only
                                  np.floating)
                else a, params)
        old_leaves, old_def = jax.tree_util.tree_flatten(
            (old_params, old_mstate))
        new_leaves, new_def = jax.tree_util.tree_flatten(
            (params, model_state))
        if new_def != old_def:
            raise ValueError(
                "swap_params: tree structure mismatch vs committed "
                f"params ({new_def} != {old_def}); a structural change "
                "invalidates the warm executables — use the fleet's "
                "full swap instead")
        for i, (o, nl) in enumerate(zip(old_leaves, new_leaves)):
            os_, ns = np.shape(o), np.shape(nl)
            od = o.dtype if hasattr(o, "dtype") \
                else np.asarray(o).dtype  # host-sync-ok: plain-python leaf, structural check
            nd = nl.dtype if hasattr(nl, "dtype") \
                else np.asarray(nl).dtype  # host-sync-ok: plain-python leaf, structural check
            if os_ != ns or od != nd:
                raise ValueError(
                    f"swap_params: leaf {i} is {ns}/{nd}, committed "
                    f"expects {os_}/{od}; shape/dtype changes "
                    "invalidate the warm executables")
        new_committed: Dict[Union[int, str], Any] = {}
        for r, dev in enumerate(self.devices):
            new_committed[r] = jax.device_put((params, model_state),
                                              dev)
        if MESH in self._committed:
            # reuse the live replicated sharding rather than rebuilding
            # the mesh — same placement, no new compile keys
            shd = jax.tree_util.tree_leaves(
                self._committed[MESH])[0].sharding
            new_committed[MESH] = jax.device_put(
                (params, model_state), shd)
        # single reference assignment = the atomic commit point
        self._committed = new_committed
        if version is not None:
            self.model_version = version
        self.param_swaps += 1

    def assert_warm(self):
        """Raise when any live request paid a compile after the warmup
        sweep — the zero-recompile serving contract."""
        if self._post_warmup_compiles:
            raise AssertionError(
                f"{self._post_warmup_compiles} bucket executables were "
                "compiled by live traffic after warmup; widen the warmup"
                " sweep (feature_shape/min_bucket/batch_limit)")
        if self.watchdog.count() > 0:
            raise AssertionError(
                "RecompileWatchdog saw new dispatch signatures after "
                f"first compile: {self.watchdog.events}")

    # ---- dispatcher ------------------------------------------------------
    def _form_batch(self) -> Optional[List[_Request]]:
        with self._carry_lock:
            first, self._carry = self._carry, None
        if first is None:
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                return None
        batch = [first]
        total = first.x.shape[0]
        deadline = time.monotonic() + self.timeout_ms / 1000.0
        while total < self.batch_limit:
            if self.pipelined:
                # backpressure aggregation: only wait for stragglers
                # while the completion pipe is full (device busy) —
                # never idle a free device on the timer
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    rem = deadline - time.monotonic()
                    if rem <= 0 or not self._completions.full():
                        break
                    try:
                        item = self._queue.get(timeout=min(rem, 0.001))
                    except queue.Empty:
                        continue
            else:
                # the seed's fixed window: one absolute aggregation
                # deadline per batch
                rem = deadline - time.monotonic()
                if rem <= 0:
                    break
                try:
                    item = self._queue.get(timeout=rem)
                except queue.Empty:
                    break
            if total + item.x.shape[0] > self.batch_limit:
                # doesn't fit: hold it for the next batch (the seed
                # padded past the limit instead — minting an executable
                # per overflow size)
                with self._carry_lock:
                    self._carry = item
                break
            batch.append(item)
            total += item.x.shape[0]
        return batch

    def _shed_expired(self,
                      batch: List[_Request]) -> List[_Request]:
        """Drop requests whose deadline expired while they queued —
        the last gate before the device; the waiter gets
        DeadlineExceeded instead of a stale answer."""
        live = []
        for req in batch:
            if req.deadline is not None and req.deadline.expired:
                self._c_deadline_shed.inc(
                    1.0, session=self.session_id,
                    precision=self._ptag, stage="batch")
                if not req.future.done():
                    req.future.set_exception(DeadlineExceeded(
                        "serving: deadline expired while queued"))
            else:
                live.append(req)
        return live

    def _dispatch_loop(self):
        while not self._shutdown.is_set():
            t_form0 = time.perf_counter()
            batch = self._form_batch()
            if batch:
                batch = self._shed_expired(batch)
            if not batch:
                continue
            self._g_queue.set(self._queue.qsize(),
                              session=self.session_id, precision=self._ptag)
            try:
                inflight = self._dispatch(batch, t_form0)
            except Exception as e:
                # a malformed batch must fail its waiters, not kill the
                # dispatcher (they would hang forever)
                for req in batch:
                    if not req.future.done():
                        req.future.set_exception(e)
                continue
            if not self.pipelined:
                self._complete(inflight)
                continue
            while True:
                try:
                    self._completions.put(inflight, timeout=0.1)
                    break
                except queue.Full:
                    if (self._completer is None
                            or not self._completer.is_alive()):
                        err = RuntimeError(
                            "serving completion thread died")
                        for req in inflight.requests:
                            if not req.future.done():
                                req.future.set_exception(err)
                        break

    def _dispatch(self, batch: List[_Request],
                  t_form0: float) -> _InFlight:
        tracer = self.tracer
        n = sum(req.x.shape[0] for req in batch)
        bucket = self.bucket_of(n)
        # write requests straight into one bucket-sized staging buffer
        # (a fresh one per dispatch: the CPU backend zero-copy adopts
        # numpy buffers, so reuse would corrupt in-flight batches)
        x = np.empty((bucket,) + batch[0].x.shape[1:], self.dtype)
        ofs = 0
        for req in batch:
            k = req.x.shape[0]
            x[ofs:ofs + k] = req.x
            ofs += k
        if bucket > n:
            # duplicate the last row (finite activations) — padded rows
            # are sliced off before waiters see the result
            x[n:] = x[n - 1]
        t_formed = time.perf_counter()
        for req in batch:
            tracer.add_span("queue_wait", req.t_enqueue, t_form0,
                            cat="serve")
        tracer.add_span("batch_form", t_form0, t_formed, cat="serve",
                        n=n, bucket=bucket)
        where = self._target_for(bucket)
        if self._chaos_dispatch is not None:
            self._chaos_dispatch.fail(arg=str(where))
        out = self._run(x, bucket, where)
        t_dispatched = time.perf_counter()
        tracer.add_span("dispatch", t_formed, t_dispatched, cat="serve",
                        where=str(where))
        self._c_batches.inc(1.0, session=self.session_id, precision=self._ptag)
        self.dispatch_count += 1
        self._c_replica_disp.inc(1.0, session=self.session_id, precision=self._ptag,
                                 replica=str(where))
        self._g_occupancy.set(n / bucket, session=self.session_id, precision=self._ptag)
        return _InFlight(out, batch, n, bucket, where, t_dispatched)

    # ---- completion ------------------------------------------------------
    def _complete_loop(self):
        while True:
            item = self._completions.get()
            if item is None:
                return
            self._complete(item)

    def _complete(self, inflight: _InFlight):
        tracer = self.tracer
        try:
            if hasattr(inflight.out, "block_until_ready"):
                inflight.out.block_until_ready()  # host-sync-ok: completion thread absorbs the device wait off the dispatch path
            t_ready = time.perf_counter()
            host = np.asarray(inflight.out)  # host-sync-ok: completion-thread fetch is the one place results come to host
            t_fetched = time.perf_counter()
            tracer.add_span("device", inflight.t_dispatched, t_ready,
                            cat="serve", where=str(inflight.where))
            tracer.add_span("fetch", t_ready, t_fetched, cat="serve",
                            bytes=host.nbytes)
            self._c_replica_busy.inc(
                (t_ready - inflight.t_dispatched) * 1e3,
                session=self.session_id, precision=self._ptag, replica=str(inflight.where))
            self.device_ms_total += (t_ready
                                     - inflight.t_dispatched) * 1e3
            ofs = 0
            now = time.perf_counter()
            for req in inflight.requests:
                k = req.x.shape[0]
                if not req.future.done():
                    req.future.set_result(host[ofs:ofs + k])
                ofs += k
                self.latency.record(now - req.t_enqueue)
            self._publish_latency()
        except Exception as e:    # propagate to every waiter
            for req in inflight.requests:
                if not req.future.done():
                    req.future.set_exception(e)

    def _publish_latency(self):
        q = self.latency.quantiles()
        for qq, v in q.items():
            self._g_latency.set(v * 1e3, session=self.session_id, precision=self._ptag,
                                quantile=f"p{int(qq * 100)}")

    # ---- lifecycle -------------------------------------------------------
    def shutdown(self):
        if self._shutdown.is_set():
            return
        self._shutdown.set()
        self._dispatcher.join(timeout=5)
        if self._completer is not None:
            # sentinel after the dispatcher stops feeding; the completer
            # drains in-flight batches first (their results are valid)
            while self._completer.is_alive():
                try:
                    self._completions.put(None, timeout=0.1)
                    break
                except queue.Full:
                    continue
            self._completer.join(timeout=5)
        self._drain_queue()

    def _drain_queue(self):
        """Fail any still-queued request (post-shutdown)."""
        with self._carry_lock:
            carried, self._carry = self._carry, None
        if carried is not None and not carried.future.done():
            carried.future.set_exception(
                RuntimeError("ServingEngine shut down"))
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if not req.future.done():
                req.future.set_exception(
                    RuntimeError("ServingEngine shut down"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
