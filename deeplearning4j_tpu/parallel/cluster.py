"""Cluster-scale training — the Spark layer, redesigned for TPU pods.

The reference's cluster stack (SURVEY §2.11, §3.4) is Spark for
orchestration plus either synchronous parameter averaging
(``dl4j-spark/.../paramavg/ParameterAveragingTrainingMaster.java:62``) or an
Aeron-UDP gradient-sharing mesh
(``dl4j-spark-parameterserver/.../training/SharedTrainingMaster.java:57``).
On TPU the interconnect replaces all of that machinery: every process
(TPU-VM worker) joins one ``jax.distributed`` job, the global ``Mesh`` spans
all slices, and XLA routes collectives over ICI within a slice and DCN
across slices — there is no driver/executor asymmetry and no parameter
server (SURVEY §5.8).

What survives from the reference design, faithfully:
- the **TrainingMaster SPI** (``dl4j-spark/.../api/TrainingMaster.java:28``)
  as the strategy object that owns the distributed fit loop;
- **ParameterAveragingTrainingMaster** semantics — every worker runs
  ``averaging_frequency`` local optimizer steps on its own shard, then
  params AND updater state are averaged (local SGD; the treeAggregate at
  aggregation_depth becomes a single ICI pmean, the knob is kept as a
  no-op for config parity);
- **SharedTrainingMaster** semantics — synchronous gradient all-reduce
  every step (the threshold-compression knobs configure the optional
  DCN codec from :mod:`deeplearning4j_tpu.parallel.compression`);
- **collectTrainingStats** — timed phase events (split / fit / aggregate)
  with a JSON/HTML timeline export
  (``dl4j-spark/.../stats/StatsUtils.java``).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import List, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.datasets.dataset import DataSet, DataSetIterator
from deeplearning4j_tpu.evaluation.evaluation import Evaluation
from deeplearning4j_tpu.parallel.compression import ThresholdSchedule
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, create_mesh
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper, TrainingMode


# --------------------------------------------------------------------------
# Training stats / timeline (CommonSparkTrainingStats + StatsUtils analog)
# --------------------------------------------------------------------------

@dataclass
class EventStats:
    """One timed phase event (dl4j-spark/.../stats/BaseEventStats.java).
    TPU VMs share NTP-disciplined clocks, so no NTPTimeSource is needed
    (reference: dl4j-spark/.../time/NTPTimeSource.java)."""
    name: str
    start_ms: float
    duration_ms: float
    worker: int = 0


class TrainingStats:
    def __init__(self):
        self.events: List[EventStats] = []
        self._t0 = time.perf_counter()

    def time(self, name: str):
        stats = self

        class _Ctx:
            def __enter__(self_inner):
                self_inner.start = time.perf_counter()
                return self_inner

            def __exit__(self_inner, *exc):
                now = time.perf_counter()
                stats.events.append(EventStats(
                    name, (self_inner.start - stats._t0) * 1000,
                    (now - self_inner.start) * 1000))
                return False
        return _Ctx()

    def as_json(self) -> str:
        return json.dumps([e.__dict__ for e in self.events])

    def export_timeline_html(self, path: str):
        """Minimal HTML timeline (StatsUtils.exportStatsAsHtml analog)."""
        rows = []
        total = max((e.start_ms + e.duration_ms for e in self.events),
                    default=1.0)
        for e in self.events:
            left = 100.0 * e.start_ms / total
            width = max(0.2, 100.0 * e.duration_ms / total)
            rows.append(
                f'<div class="row"><span class="lbl">{e.name}'
                f' ({e.duration_ms:.1f} ms)</span>'
                f'<div class="bar" style="margin-left:{left:.2f}%;'
                f'width:{width:.2f}%"></div></div>')
        html = ("<html><head><style>.row{margin:2px;font:12px monospace}"
                ".bar{background:#4a90d9;height:10px;display:inline-block}"
                ".lbl{display:inline-block;width:340px}</style></head>"
                "<body><h3>Training timeline</h3>" + "".join(rows)
                + "</body></html>")
        with open(path, "w") as f:
            f.write(html)


# --------------------------------------------------------------------------
# TrainingMaster SPI
# --------------------------------------------------------------------------

class TrainingMaster:
    """Strategy object owning the distributed fit loop
    (dl4j-spark/.../api/TrainingMaster.java:28)."""

    def __init__(self, workers: Optional[int] = None,
                 batch_size_per_worker: int = 16,
                 collect_training_stats: bool = False,
                 mesh: Optional[Mesh] = None):
        self.batch_size_per_worker = batch_size_per_worker
        self.mesh = mesh if mesh is not None else (
            create_mesh({DATA_AXIS: workers},
                        jax.devices()[:workers]) if workers
            else create_mesh())
        self.stats: Optional[TrainingStats] = (
            TrainingStats() if collect_training_stats else None)

    @property
    def num_workers(self) -> int:
        return int(self.mesh.shape[DATA_AXIS])

    def execute_training(self, net, iterator: DataSetIterator,
                         epochs: int = 1):
        raise NotImplementedError

    def delete_temp_files(self):
        """Export-approach temp cleanup is a no-op: there is no RDD export
        staging (reference: TrainingMaster.deleteTempFiles)."""


class ParameterAveragingTrainingMaster(TrainingMaster):
    """Synchronous parameter averaging == local SGD over the data axis.

    Reference math (ParameterAveragingTrainingMaster.java:287-298,635,654):
    each of N workers fits ``averaging_frequency`` minibatches of
    ``batch_size_per_worker``, then params + updater state are averaged and
    re-broadcast. Here the average is a ``lax.pmean`` inside one compiled
    step (ParallelWrapper AVERAGING mode), and the re-broadcast is implicit
    in SPMD replication. ``aggregation_depth`` and ``rdd_training_approach``
    are accepted for config parity but change nothing: a treeAggregate
    schedule is XLA's problem now.
    """

    def __init__(self, averaging_frequency: int = 5,
                 aggregation_depth: int = 2,
                 average_updaters: bool = True,
                 repartition_strategy: str = "balanced",
                 **kw):
        super().__init__(**kw)
        self.averaging_frequency = averaging_frequency
        self.aggregation_depth = aggregation_depth
        self.average_updaters = average_updaters
        self.repartition_strategy = repartition_strategy

    class Builder:
        def __init__(self, batch_size_per_worker: int = 16):
            self._kw = {"batch_size_per_worker": batch_size_per_worker}
            self._avg_freq = 5
            self._agg_depth = 2

        def averaging_frequency(self, k):
            self._avg_freq = k
            return self

        def aggregation_depth(self, d):
            self._agg_depth = d
            return self

        def workers(self, n):
            self._kw["workers"] = n
            return self

        def collect_training_stats(self, flag: bool):
            self._kw["collect_training_stats"] = flag
            return self

        def build(self):
            return ParameterAveragingTrainingMaster(
                averaging_frequency=self._avg_freq,
                aggregation_depth=self._agg_depth, **self._kw)

    def execute_training(self, net, iterator, epochs: int = 1):
        wrapper = ParallelWrapper(
            net, mesh=self.mesh, mode=TrainingMode.AVERAGING,
            averaging_frequency=self.averaging_frequency,
            average_updaters=self.average_updaters)
        if self.stats is not None:
            with self.stats.time("ParameterAveragingMaster fit"):
                wrapper.fit(iterator, epochs)
        else:
            wrapper.fit(iterator, epochs)
        return net


class SharedTrainingMaster(TrainingMaster):
    """Gradient-sharing == synchronous all-reduce data parallelism.

    The reference's async Aeron mesh with 1-bit threshold compression
    (SharedTrainingMaster.java:57, SilentTrainingDriver.java:122-178)
    exists because commodity UDP networking cannot carry dense gradients
    every step; ICI can, so the TPU-native design is a plain synchronous
    psum emitted by XLA inside the backward pass. The threshold-schedule
    knobs (:72-107) are kept and configure the optional host-side DCN
    codec (compression.EncodedGradientsAccumulator) for multi-slice jobs
    where cross-slice bandwidth is scarce.
    """

    def __init__(self, threshold: float = 1e-3, min_threshold: float = 1e-5,
                 threshold_step: float = 2.0, step_trigger: float = 0.05,
                 step_delay: int = 50, shake_frequency: int = 0, **kw):
        super().__init__(**kw)
        self.threshold_schedule = ThresholdSchedule(
            threshold=threshold, min_threshold=min_threshold,
            threshold_step=threshold_step, step_trigger=step_trigger,
            step_delay=step_delay, shake_frequency=shake_frequency)

    class Builder:
        def __init__(self, threshold: float = 1e-3):
            self._kw = {"threshold": threshold}

        def min_threshold(self, v):
            self._kw["min_threshold"] = v
            return self

        def threshold_step(self, v):
            self._kw["threshold_step"] = v
            return self

        def shake_frequency(self, v):
            self._kw["shake_frequency"] = v
            return self

        def workers(self, n):
            self._kw["workers"] = n
            return self

        def batch_size_per_worker(self, n):
            self._kw["batch_size_per_worker"] = n
            return self

        def collect_training_stats(self, flag: bool):
            self._kw["collect_training_stats"] = flag
            return self

        def build(self):
            return SharedTrainingMaster(**self._kw)

    def execute_training(self, net, iterator, epochs: int = 1):
        wrapper = ParallelWrapper(
            net, mesh=self.mesh, mode=TrainingMode.SHARED_GRADIENTS)
        if self.stats is not None:
            with self.stats.time("SharedTrainingMaster fit"):
                wrapper.fit(iterator, epochs)
        else:
            wrapper.fit(iterator, epochs)
        return net


# --------------------------------------------------------------------------
# SparkDl4jMultiLayer / SparkComputationGraph analogs
# --------------------------------------------------------------------------

class DistributedNetwork:
    """Wraps (network, TrainingMaster) — the SparkDl4jMultiLayer /
    SparkComputationGraph surface (spark/impl/multilayer/
    SparkDl4jMultiLayer.java:71: fit:214 delegates to
    trainingMaster.executeTraining:218; distributed evaluation in
    impl/multilayer/evaluation/)."""

    def __init__(self, network, training_master: TrainingMaster):
        self.network = network
        self.training_master = training_master
        if network.train_state is None:
            network.init()

    def fit(self, iterator: DataSetIterator, epochs: int = 1):
        return self.training_master.execute_training(
            self.network, iterator, epochs)

    def evaluate(self, iterator: DataSetIterator,
                 num_classes: Optional[int] = None) -> Evaluation:
        """Data-parallel evaluation: batches are sharded over the data
        axis of the master's mesh, per-shard forward runs SPMD, metric
        accumulation happens on host (the reference tree-aggregates
        per-partition Evaluation objects — IEvaluateFlatMapFunction)."""
        mesh = self.training_master.mesh
        batch_sh = NamedSharding(mesh, P(DATA_AXIS))
        ev = Evaluation(num_classes)
        w = self.training_master.num_workers
        for batch in iterator:
            feats = np.asarray(batch.features)  # host-sync-ok: eval host staging
            labels = np.asarray(batch.labels)  # host-sync-ok: eval host staging
            n = feats.shape[0]
            pad = (-n) % w
            if pad:
                feats = np.concatenate(
                    [feats, np.repeat(feats[-1:], pad, axis=0)], axis=0)
            x = jax.device_put(feats, batch_sh)
            preds = np.asarray(self.network.output(x))[:n]  # host-sync-ok: eval output consumed on host
            ev.eval(labels, preds, mask=batch.labels_mask)
        iterator.reset()
        return ev

    def get_network(self):
        return self.network

    @property
    def stats(self) -> Optional[TrainingStats]:
        return self.training_master.stats


# Aliases mirroring the reference entry-point names.
SparkDl4jMultiLayer = DistributedNetwork
SparkComputationGraph = DistributedNetwork
