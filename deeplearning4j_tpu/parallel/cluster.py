"""Cluster-scale training — the Spark layer, redesigned for TPU pods.

The reference's cluster stack (SURVEY §2.11, §3.4) is Spark for
orchestration plus either synchronous parameter averaging
(``dl4j-spark/.../paramavg/ParameterAveragingTrainingMaster.java:62``) or an
Aeron-UDP gradient-sharing mesh
(``dl4j-spark-parameterserver/.../training/SharedTrainingMaster.java:57``).
On TPU the interconnect replaces all of that machinery: every process
(TPU-VM worker) joins one ``jax.distributed`` job, the global ``Mesh`` spans
all slices, and XLA routes collectives over ICI within a slice and DCN
across slices — there is no driver/executor asymmetry and no parameter
server (SURVEY §5.8).

What survives from the reference design, faithfully:
- the **TrainingMaster SPI** (``dl4j-spark/.../api/TrainingMaster.java:28``)
  as the strategy object that owns the distributed fit loop;
- **ParameterAveragingTrainingMaster** semantics — every worker runs
  ``averaging_frequency`` local optimizer steps on its own shard, then
  params AND updater state are averaged (local SGD; the treeAggregate at
  aggregation_depth becomes a single ICI pmean, the knob is kept as a
  no-op for config parity);
- **SharedTrainingMaster** semantics — synchronous gradient all-reduce
  every step (the threshold-compression knobs configure the optional
  DCN codec from :mod:`deeplearning4j_tpu.parallel.compression`);
- **collectTrainingStats** — timed phase events (split / fit / aggregate)
  with a JSON/HTML timeline export
  (``dl4j-spark/.../stats/StatsUtils.java``).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.datasets.dataset import DataSet, DataSetIterator
from deeplearning4j_tpu.evaluation.evaluation import Evaluation
from deeplearning4j_tpu.parallel.compression import ThresholdSchedule
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, create_mesh
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper, TrainingMode


# --------------------------------------------------------------------------
# Training stats / timeline (CommonSparkTrainingStats + StatsUtils analog)
# --------------------------------------------------------------------------

@dataclass
class EventStats:
    """One timed phase event (dl4j-spark/.../stats/BaseEventStats.java).
    TPU VMs share NTP-disciplined clocks, so no NTPTimeSource is needed
    (reference: dl4j-spark/.../time/NTPTimeSource.java)."""
    name: str
    start_ms: float
    duration_ms: float
    worker: int = 0


class TrainingStats:
    def __init__(self):
        self.events: List[EventStats] = []
        self._t0 = time.perf_counter()

    def time(self, name: str):
        stats = self

        class _Ctx:
            def __enter__(self_inner):
                self_inner.start = time.perf_counter()
                return self_inner

            def __exit__(self_inner, *exc):
                now = time.perf_counter()
                stats.events.append(EventStats(
                    name, (self_inner.start - stats._t0) * 1000,
                    (now - self_inner.start) * 1000))
                return False
        return _Ctx()

    def as_json(self) -> str:
        return json.dumps([e.__dict__ for e in self.events])

    def export_timeline_html(self, path: str):
        """Minimal HTML timeline (StatsUtils.exportStatsAsHtml analog)."""
        rows = []
        total = max((e.start_ms + e.duration_ms for e in self.events),
                    default=1.0)
        for e in self.events:
            left = 100.0 * e.start_ms / total
            width = max(0.2, 100.0 * e.duration_ms / total)
            rows.append(
                f'<div class="row"><span class="lbl">{e.name}'
                f' ({e.duration_ms:.1f} ms)</span>'
                f'<div class="bar" style="margin-left:{left:.2f}%;'
                f'width:{width:.2f}%"></div></div>')
        html = ("<html><head><style>.row{margin:2px;font:12px monospace}"
                ".bar{background:#4a90d9;height:10px;display:inline-block}"
                ".lbl{display:inline-block;width:340px}</style></head>"
                "<body><h3>Training timeline</h3>" + "".join(rows)
                + "</body></html>")
        with open(path, "w") as f:  # graftlint: disable=atomic-write,chaos-hygiene: one-shot operator report, not a store file other processes poll or soak runs exercise
            f.write(html)


# --------------------------------------------------------------------------
# TrainingMaster SPI
# --------------------------------------------------------------------------

class TrainingMaster:
    """Strategy object owning the distributed fit loop
    (dl4j-spark/.../api/TrainingMaster.java:28)."""

    def __init__(self, workers: Optional[int] = None,
                 batch_size_per_worker: int = 16,
                 collect_training_stats: bool = False,
                 mesh: Optional[Mesh] = None):
        self.batch_size_per_worker = batch_size_per_worker
        self.mesh = mesh if mesh is not None else (
            create_mesh({DATA_AXIS: workers},
                        jax.devices()[:workers]) if workers
            else create_mesh())
        self.stats: Optional[TrainingStats] = (
            TrainingStats() if collect_training_stats else None)

    @property
    def num_workers(self) -> int:
        return int(self.mesh.shape[DATA_AXIS])

    def execute_training(self, net, iterator: DataSetIterator,
                         epochs: int = 1):
        raise NotImplementedError

    def delete_temp_files(self):
        """Export-approach temp cleanup is a no-op: there is no RDD export
        staging (reference: TrainingMaster.deleteTempFiles)."""


class ParameterAveragingTrainingMaster(TrainingMaster):
    """Synchronous parameter averaging == local SGD over the data axis.

    Reference math (ParameterAveragingTrainingMaster.java:287-298,635,654):
    each of N workers fits ``averaging_frequency`` minibatches of
    ``batch_size_per_worker``, then params + updater state are averaged and
    re-broadcast. Here the average is a ``lax.pmean`` inside one compiled
    step (ParallelWrapper AVERAGING mode), and the re-broadcast is implicit
    in SPMD replication. ``aggregation_depth`` and ``rdd_training_approach``
    are accepted for config parity but change nothing: a treeAggregate
    schedule is XLA's problem now.
    """

    def __init__(self, averaging_frequency: int = 5,
                 aggregation_depth: int = 2,
                 average_updaters: bool = True,
                 repartition_strategy: str = "balanced",
                 **kw):
        super().__init__(**kw)
        self.averaging_frequency = averaging_frequency
        self.aggregation_depth = aggregation_depth
        self.average_updaters = average_updaters
        self.repartition_strategy = repartition_strategy

    class Builder:
        def __init__(self, batch_size_per_worker: int = 16):
            self._kw = {"batch_size_per_worker": batch_size_per_worker}
            self._avg_freq = 5
            self._agg_depth = 2

        def averaging_frequency(self, k):
            self._avg_freq = k
            return self

        def aggregation_depth(self, d):
            self._agg_depth = d
            return self

        def workers(self, n):
            self._kw["workers"] = n
            return self

        def collect_training_stats(self, flag: bool):
            self._kw["collect_training_stats"] = flag
            return self

        def build(self):
            return ParameterAveragingTrainingMaster(
                averaging_frequency=self._avg_freq,
                aggregation_depth=self._agg_depth, **self._kw)

    def execute_training(self, net, iterator, epochs: int = 1):
        wrapper = ParallelWrapper(
            net, mesh=self.mesh, mode=TrainingMode.AVERAGING,
            averaging_frequency=self.averaging_frequency,
            average_updaters=self.average_updaters)
        if self.stats is not None:
            with self.stats.time("ParameterAveragingMaster fit"):
                wrapper.fit(iterator, epochs)
        else:
            wrapper.fit(iterator, epochs)
        return net


class SharedTrainingMaster(TrainingMaster):
    """Gradient-sharing == synchronous all-reduce data parallelism.

    The reference's async Aeron mesh with 1-bit threshold compression
    (SharedTrainingMaster.java:57, SilentTrainingDriver.java:122-178)
    exists because commodity UDP networking cannot carry dense gradients
    every step; ICI can, so the TPU-native design is a plain synchronous
    psum emitted by XLA inside the backward pass. The threshold-schedule
    knobs (:72-107) are kept and configure the optional host-side DCN
    codec (compression.EncodedGradientsAccumulator) for multi-slice jobs
    where cross-slice bandwidth is scarce.
    """

    def __init__(self, threshold: float = 1e-3, min_threshold: float = 1e-5,
                 threshold_step: float = 2.0, step_trigger: float = 0.05,
                 step_delay: int = 50, shake_frequency: int = 0, **kw):
        super().__init__(**kw)
        self.threshold_schedule = ThresholdSchedule(
            threshold=threshold, min_threshold=min_threshold,
            threshold_step=threshold_step, step_trigger=step_trigger,
            step_delay=step_delay, shake_frequency=shake_frequency)

    class Builder:
        def __init__(self, threshold: float = 1e-3):
            self._kw = {"threshold": threshold}

        def min_threshold(self, v):
            self._kw["min_threshold"] = v
            return self

        def threshold_step(self, v):
            self._kw["threshold_step"] = v
            return self

        def shake_frequency(self, v):
            self._kw["shake_frequency"] = v
            return self

        def workers(self, n):
            self._kw["workers"] = n
            return self

        def batch_size_per_worker(self, n):
            self._kw["batch_size_per_worker"] = n
            return self

        def collect_training_stats(self, flag: bool):
            self._kw["collect_training_stats"] = flag
            return self

        def build(self):
            return SharedTrainingMaster(**self._kw)

    def execute_training(self, net, iterator, epochs: int = 1):
        wrapper = ParallelWrapper(
            net, mesh=self.mesh, mode=TrainingMode.SHARED_GRADIENTS)
        if self.stats is not None:
            with self.stats.time("SharedTrainingMaster fit"):
                wrapper.fit(iterator, epochs)
        else:
            wrapper.fit(iterator, epochs)
        return net


# --------------------------------------------------------------------------
# SparkDl4jMultiLayer / SparkComputationGraph analogs
# --------------------------------------------------------------------------

class DistributedNetwork:
    """Wraps (network, TrainingMaster) — the SparkDl4jMultiLayer /
    SparkComputationGraph surface (spark/impl/multilayer/
    SparkDl4jMultiLayer.java:71: fit:214 delegates to
    trainingMaster.executeTraining:218; distributed evaluation in
    impl/multilayer/evaluation/)."""

    def __init__(self, network, training_master: TrainingMaster):
        self.network = network
        self.training_master = training_master
        if network.train_state is None:
            network.init()

    def fit(self, iterator: DataSetIterator, epochs: int = 1):
        return self.training_master.execute_training(
            self.network, iterator, epochs)

    def evaluate(self, iterator: DataSetIterator,
                 num_classes: Optional[int] = None) -> Evaluation:
        """Data-parallel evaluation: batches are sharded over the data
        axis of the master's mesh, per-shard forward runs SPMD, metric
        accumulation happens on host (the reference tree-aggregates
        per-partition Evaluation objects — IEvaluateFlatMapFunction)."""
        mesh = self.training_master.mesh
        batch_sh = NamedSharding(mesh, P(DATA_AXIS))
        ev = Evaluation(num_classes)
        w = self.training_master.num_workers
        for batch in iterator:
            feats = np.asarray(batch.features)  # host-sync-ok: eval host staging
            labels = np.asarray(batch.labels)  # host-sync-ok: eval host staging
            n = feats.shape[0]
            pad = (-n) % w
            if pad:
                feats = np.concatenate(
                    [feats, np.repeat(feats[-1:], pad, axis=0)], axis=0)
            x = jax.device_put(feats, batch_sh)
            preds = np.asarray(self.network.output(x))[:n]  # host-sync-ok: eval output consumed on host
            ev.eval(labels, preds, mask=batch.labels_mask)
        iterator.reset()
        return ev

    def get_network(self):
        return self.network

    @property
    def stats(self) -> Optional[TrainingStats]:
        return self.training_master.stats


# Aliases mirroring the reference entry-point names.
SparkDl4jMultiLayer = DistributedNetwork
SparkComputationGraph = DistributedNetwork


# --------------------------------------------------------------------------
# Collective failure detection (heartbeat watchdog)
# --------------------------------------------------------------------------

#: Exit status a worker uses when it abandons a hung collective after
#: detecting a dead peer. Distinct from ordinary crash codes so the
#: relauncher can tell "peer died, resume me" from "I am the bug".
PEER_LOSS_EXIT_CODE = 43

#: Marker file the watchdog drops next to the checkpoints on peer loss.
PEER_LOSS_MARKER = "PEER_LOSS.json"


def classify_heartbeat_age(age: Optional[float], dead_after_s: float,
                           slow_after_s: Optional[float] = None) -> str:
    """Classify a heartbeat's age: ``"alive"`` | ``"slow"`` | ``"dead"``.

    The one authoritative statement of the staleness boundary, shared by
    the watchdog's ``dead_peers`` and the serving NodeRegistry
    (parallel/node.py) so the two tiers can never disagree off-by-one:

    - ``age is None`` (file never appeared / unreadable) -> ``"dead"``;
    - ``age``  > ``dead_after_s``  (strictly past)        -> ``"dead"``;
    - ``age`` >= ``slow_after_s``  (at or past)           -> ``"slow"``;
    - otherwise                                           -> ``"alive"``.

    A heartbeat EXACTLY at a threshold is always given the less severe
    class: exactly at ``dead_after_s`` is slow, not dead — a beat is a
    point-in-time sample, so "age == horizon" means the peer beat
    exactly one horizon ago and may be about to beat again; only
    strictly-past evidence may kill it. ``slow_after_s`` defaults to
    ``dead_after_s`` (the single-threshold watchdog case).
    """
    if age is None or age > dead_after_s:
        return "dead"
    if age >= (dead_after_s if slow_after_s is None else slow_after_s):
        return "slow"
    return "alive"


class CollectiveWatchdog:
    """Heartbeat/deadline watchdog around the collective path.

    XLA collectives have no per-op timeout on most backends: when a peer
    process dies mid-all-reduce the survivors block in
    ``block_until_ready`` forever (or until a transport-level error
    surfaces minutes later). The reference stack sidesteps this with
    Aeron session keepalives (PAPER.md §1 L5); here each process writes
    a small heartbeat file (``hb_{rank}.json``: rank, wall time, host
    iteration) to a shared directory every ``interval_s``, and a monitor
    thread watches any collective the caller marks in-flight via
    :meth:`guard`.

    Classification — the whole point is telling a *dead* peer from a
    *slow* one:

    - in-flight past ``deadline_s`` AND some peer's heartbeat is older
      than ``dead_after_s`` (or its file never appeared) -> **peer
      loss**: best-effort emergency checkpoint
      (:func:`~deeplearning4j_tpu.parallel.checkpoint.save_sharded`
      with ``emergency=True`` — barrier-free, the dead peer can never
      join a barrier again), a flight-recorder dump with reason
      ``peer_loss`` (dead ranks + heartbeat ages in ``context.json``),
      a resumable ``PEER_LOSS.json`` marker next to the checkpoints,
      then ``os._exit(PEER_LOSS_EXIT_CODE)`` (unless
      ``exit_on_loss=False``).
    - in-flight past ``deadline_s`` but every peer is still beating ->
      **straggler**: warn once, bump
      ``dl4j_elastic_straggler_waits_total``, extend the deadline and
      keep waiting — killing a job because one host hit a GC pause is
      the failure mode this class exists to avoid.

    The same classifier is exposed as :meth:`on_collective_error` for
    backends whose transport *does* raise (gloo on CPU): the training
    loop's except-path calls it to decide whether an exception is
    peer loss (handled: marker + dump + emergency save, returns True)
    or the caller's own bug (returns False).
    """

    def __init__(self, heartbeat_dir: str, *,
                 rank: Optional[int] = None,
                 n_ranks: Optional[int] = None,
                 interval_s: float = 0.25,
                 deadline_s: float = 60.0,
                 dead_after_s: float = 2.0,
                 model=None,
                 checkpoint_dir: Optional[str] = None,
                 on_peer_loss: Optional[Callable[[Dict], None]] = None,
                 exit_on_loss: bool = True):
        self.heartbeat_dir = heartbeat_dir
        self.rank = jax.process_index() if rank is None else int(rank)
        self.n_ranks = (jax.process_count() if n_ranks is None
                        else int(n_ranks))
        self.interval_s = float(interval_s)  # host-sync-ok: python config scalar
        self.deadline_s = float(deadline_s)  # host-sync-ok: python config scalar
        self.dead_after_s = float(dead_after_s)  # host-sync-ok: python config scalar
        self.model = model
        self.checkpoint_dir = checkpoint_dir
        self.on_peer_loss = on_peer_loss
        self.exit_on_loss = exit_on_loss
        self.iteration = 0          # mirrored into the heartbeat file
        self.straggler_waits = 0
        self.peer_loss_event: Optional[Dict] = None
        self._inflight_since: Optional[float] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._beat_thread: Optional[threading.Thread] = None
        self._mon_thread: Optional[threading.Thread] = None
        self._warned_straggler = False
        os.makedirs(heartbeat_dir, exist_ok=True)

    # ---- lifecycle ------------------------------------------------------
    def start(self) -> "CollectiveWatchdog":
        if self._beat_thread is not None:
            return self
        self._stop.clear()
        self._beat()                # first beat before anyone waits on us
        self._beat_thread = threading.Thread(
            target=self._beat_loop, name="dl4j-heartbeat", daemon=True)
        self._mon_thread = threading.Thread(
            target=self._monitor_loop, name="dl4j-collective-watchdog",
            daemon=True)
        self._beat_thread.start()
        self._mon_thread.start()
        return self

    def stop(self):
        self._stop.set()
        for t in (self._beat_thread, self._mon_thread):
            if t is not None:
                t.join(timeout=5 * self.interval_s + 1.0)
        self._beat_thread = self._mon_thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc_info):
        self.stop()

    # ---- heartbeat writer ----------------------------------------------
    def _beat_path(self, rank: int) -> str:
        return os.path.join(self.heartbeat_dir, f"hb_{rank}.json")

    def _beat(self):
        payload = json.dumps({"rank": self.rank, "time": time.time(),
                              "iteration": self.iteration})
        try:
            fd, tmp = tempfile.mkstemp(dir=self.heartbeat_dir,
                                       prefix=f".hb_{self.rank}_")
            with os.fdopen(fd, "w") as f:  # graftlint: disable=chaos-hygiene: the heartbeat IS the failure-detection channel; peer-loss plans exercise it by killing the writer, not by torn writes
                f.write(payload)
            os.replace(tmp, self._beat_path(self.rank))  # atomic
        except OSError:
            pass            # a full/slow disk must not kill the beat

    def _beat_loop(self):
        while not self._stop.wait(self.interval_s):
            self._beat()

    # ---- in-flight window ----------------------------------------------
    @contextmanager
    def guard(self, iteration: Optional[int] = None):
        """Mark a blocking collective in-flight; the monitor thread only
        arms while inside this window, so host-side work (ETL, logging)
        can take arbitrarily long without tripping the deadline."""
        if iteration is not None:
            self.iteration = int(iteration)
        with self._lock:
            self._inflight_since = time.time()
            self._warned_straggler = False
        try:
            yield
        finally:
            with self._lock:
                self._inflight_since = None

    # ---- peer classification -------------------------------------------
    def _peer_ages(self) -> Dict[int, Optional[float]]:
        """Age of each peer's last heartbeat in seconds; None when the
        file never appeared (process died before its first beat, or a
        misconfigured heartbeat_dir)."""
        now = time.time()
        ages: Dict[int, Optional[float]] = {}
        for r in range(self.n_ranks):
            if r == self.rank:
                continue
            try:
                with open(self._beat_path(r)) as f:
                    ages[r] = now - float(json.load(f)["time"])  # host-sync-ok: heartbeat file timestamp
            except (OSError, ValueError, KeyError):
                ages[r] = None
        return ages

    def dead_peers(self) -> Dict[int, Optional[float]]:
        """Peers whose heartbeat is stale STRICTLY past ``dead_after_s``
        (or missing entirely) — :func:`classify_heartbeat_age` owns the
        boundary; exactly-at-threshold is slow, not dead."""
        return {r: age for r, age in self._peer_ages().items()
                if classify_heartbeat_age(age, self.dead_after_s)
                == "dead"}

    # ---- monitor --------------------------------------------------------
    def _monitor_loop(self):
        while not self._stop.wait(self.interval_s):
            with self._lock:
                since = self._inflight_since
            if since is None:
                continue
            waited = time.time() - since
            # A peer whose heartbeat WAS present and has gone stale is
            # conclusively dead — classify after a couple of beats
            # in-flight instead of waiting out the straggler deadline.
            # External watchdogs race us here (the jax coordination
            # service SIGABRTs survivors ~10 s after a peer dies), so
            # late classification means no forensics at all. Peers with
            # NO heartbeat file keep the full deadline: that can be a
            # slow start, not a death.
            if waited >= 2 * self.interval_s:
                dead = {r: a for r, a in self.dead_peers().items()
                        if a is not None}
                if dead:
                    self._handle_peer_loss(dead)
                    return          # never reached when exit_on_loss
            if waited < self.deadline_s:
                continue
            dead = self.dead_peers()
            if dead:
                self._handle_peer_loss(dead)
                return              # never reached when exit_on_loss
            # Everyone is alive -> straggler. Extend the window rather
            # than spinning a warning per poll tick.
            with self._lock:
                self.straggler_waits += 1
                self._inflight_since = time.time()
                warn = not self._warned_straggler
                self._warned_straggler = True
            self._bump_counter("dl4j_elastic_straggler_waits_total")
            if warn:
                print(f"[rank {self.rank}] collective watchdog: "
                      f"collective in-flight > {self.deadline_s:.1f}s "
                      "but all peers are beating — straggler, "
                      "extending deadline", flush=True)

    # ---- peer-loss handling --------------------------------------------
    def _handle_peer_loss(self, dead: Dict[int, Optional[float]],
                          exc: Optional[BaseException] = None,
                          exit_ok: bool = True):
        event = {
            "reason": "peer_loss",
            "rank": self.rank,
            "n_ranks": self.n_ranks,
            "iteration": self.iteration,
            "dead_ranks": sorted(dead),
            "heartbeat_age_s": {str(r): a for r, a in dead.items()},
            "time": time.time(),
        }
        self.peer_loss_event = event
        self._bump_counter("dl4j_elastic_peer_loss_total")
        ckpt = self._emergency_checkpoint()
        if ckpt is not None:
            event["emergency_checkpoint"] = ckpt
        event["resume_from"] = self._latest_committed()
        self._write_marker(event)
        self._record_dump(event, exc)
        if self.on_peer_loss is not None:
            try:
                self.on_peer_loss(event)
            except Exception:
                pass        # a hook bug must not mask the peer loss
        will_exit = exit_ok and self.exit_on_loss
        print(f"[rank {self.rank}] collective watchdog: peer(s) "
              f"{sorted(dead)} lost (heartbeat stale) — emergency "
              f"checkpoint {'written to ' + ckpt if ckpt else 'skipped'}"
              + (f", exiting {PEER_LOSS_EXIT_CODE}" if will_exit
                 else ""), flush=True)
        if will_exit:
            os._exit(PEER_LOSS_EXIT_CODE)

    def _emergency_checkpoint(self) -> Optional[str]:
        if self.checkpoint_dir is None or self.model is None:
            return None
        ts = getattr(self.model, "train_state", None)
        if ts is None:
            return None
        try:
            from deeplearning4j_tpu.parallel.checkpoint import \
                save_sharded
            return save_sharded(ts, self.checkpoint_dir, emergency=True)
        except BaseException:
            return None     # best-effort: state may be poisoned

    def _latest_committed(self) -> Optional[str]:
        if self.checkpoint_dir is None:
            return None
        try:
            from deeplearning4j_tpu.parallel.checkpoint import \
                latest_checkpoint
            return latest_checkpoint(self.checkpoint_dir)
        except Exception:
            return None

    def _write_marker(self, event: Dict):
        where = self.checkpoint_dir or self.heartbeat_dir
        try:
            os.makedirs(where, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=where, prefix=".peer_loss_")
            with os.fdopen(fd, "w") as f:  # graftlint: disable=chaos-hygiene: post-mortem marker written while the cluster is already failing; injecting here only masks the fault under test
                json.dump(event, f, indent=1)
            os.replace(tmp, os.path.join(
                where, f"{PEER_LOSS_MARKER}.{self.rank}"))
        except OSError:
            pass

    def _record_dump(self, event: Dict,
                     exc: Optional[BaseException] = None):
        try:
            rec = None
            if self.model is not None and \
                    hasattr(self.model, "_recorder"):
                rec = self.model._recorder()
            if rec is None:
                from deeplearning4j_tpu.observe.flight_recorder import \
                    default_flight_recorder
                rec = default_flight_recorder()
            if rec is not None:
                rec.record_crash(self.model, reason="peer_loss",
                                 exc=exc, extra=event)
        except Exception:
            pass

    @staticmethod
    def _bump_counter(name: str):
        try:
            from deeplearning4j_tpu.observe.registry import \
                default_registry
            default_registry().counter(
                name, "collective watchdog events").inc()
        except Exception:
            pass

    # ---- exception-path classifier -------------------------------------
    def on_collective_error(self, exc: BaseException) -> bool:
        """Classify an exception raised *out of* a collective (backends
        like gloo on CPU fail fast instead of hanging). Returns True —
        and runs the full peer-loss path (marker, dump, emergency save)
        WITHOUT exiting, so the caller controls its exit code — when a
        peer's heartbeat is stale; False when everyone is alive (the
        error is the caller's own bug and should propagate untouched).
        """
        # Give a just-died peer's heartbeat time to go stale: the
        # transport error typically races the dead_after_s horizon.
        horizon = time.time() + self.dead_after_s + 2 * self.interval_s
        while True:
            dead = self.dead_peers()
            if dead:
                self._handle_peer_loss(dead, exc=exc, exit_ok=False)
                return True
            if time.time() >= horizon:
                return False
            time.sleep(self.interval_s)
