"""MagicQueue — device-affinity-aware batch distribution.

Analog of the reference's ``MagicQueue``
(deeplearning4j-core/.../parallelism/MagicQueue.java — SURVEY §2.2): a
queue that fans incoming minibatches out to per-device buckets so each
worker always dequeues data already resident on *its* device. The
reference relocates buffers via the CUDA AffinityManager; here enqueue
triggers an async ``jax.device_put`` onto the bucket's device, so the
host→HBM copy overlaps the producer loop and workers dequeue
device-resident arrays (the infeed side of SPMD training; SURVEY §2.14
"AffinityManager → device mesh addressing").
"""

from __future__ import annotations

import queue
import threading
from typing import List, Optional, Sequence

import jax

from deeplearning4j_tpu.datasets.dataset import DataSet


class MagicQueue:
    """Round-robin per-device buckets with async device placement.

    Modes (reference: MagicQueue.Mode): SEQUENTIAL hands each batch to
    the next device in turn (data parallelism); THROUGHPUT replicates
    every batch to all devices (each worker sees the full stream).
    """

    SEQUENTIAL = "sequential"
    THROUGHPUT = "throughput"

    def __init__(self, devices: Optional[Sequence] = None,
                 mode: str = SEQUENTIAL, capacity: int = 8):
        self.devices = list(devices) if devices else list(jax.devices())
        if mode not in (self.SEQUENTIAL, self.THROUGHPUT):
            raise ValueError(f"unknown mode {mode!r}")
        self.mode = mode
        self._buckets: List[queue.Queue] = [
            queue.Queue(maxsize=capacity) for _ in self.devices]
        self._next = 0
        self._lock = threading.Lock()

    def _place(self, batch: DataSet, device) -> DataSet:
        put = lambda a: None if a is None else jax.device_put(a, device)
        return DataSet(put(batch.features), put(batch.labels),
                       put(batch.features_mask), put(batch.labels_mask))

    def add(self, batch: DataSet) -> None:
        """Producer side: place + enqueue (async; device_put does not
        block on the copy)."""
        if self.mode == self.THROUGHPUT:
            for i, dev in enumerate(self.devices):
                self._buckets[i].put(self._place(batch, dev))
            return
        with self._lock:
            i = self._next
            self._next = (self._next + 1) % len(self.devices)
        self._buckets[i].put(self._place(batch, self.devices[i]))

    def poll(self, device_index: int, timeout: float = 1.0
             ) -> Optional[DataSet]:
        """Worker side: dequeue the next batch resident on this device."""
        try:
            return self._buckets[device_index].get(timeout=timeout)
        except queue.Empty:
            return None

    def size(self, device_index: Optional[int] = None) -> int:
        if device_index is not None:
            return self._buckets[device_index].qsize()
        return sum(b.qsize() for b in self._buckets)
