"""Pipeline parallelism: GPipe-style microbatched stage execution.

ABSENT in the reference (SURVEY §2.11 row 7 — no PP/TP/SP/EP anywhere);
designed fresh for TPU per SURVEY §7.2 stage 7 / §7.3 item 4. The design is
the canonical TPU pipelining recipe (scaling-book style): the ``pipe`` mesh
axis holds one pipeline *stage* per device slice; activations move
stage-to-stage with ``lax.ppermute`` hops over ICI neighbours; a
``lax.scan`` over ticks runs ``num_microbatches + num_stages - 1`` steps
(the GPipe bubble). Everything is pure, differentiable jax: ``jax.grad``
through this function IS the backward pipeline (the VJP of ``ppermute`` is
the reverse permute, so the cool-down schedule falls out of autodiff — no
hand-written 1F1B machinery).

Constraints (standard for SPMD pipelining):
- stages are *homogeneous*: one ``stage_fn`` whose params are stacked with
  a leading ``num_stages`` dim (the transformer-block case). Heterogeneous
  first/last layers (embed/unembed) stay outside the pipelined region.
- activation shape is identical at every stage boundary.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

PIPE_AXIS = "pipe"


def stack_stage_params(params_per_stage: Sequence[Any]) -> Any:
    """Stack a list of per-stage parameter pytrees (identical structure)
    into one pytree with a leading ``num_stages`` dim — the layout
    ``pipeline_apply`` expects (shard dim 0 over the pipe axis)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, 0), *params_per_stage)


def _pipeline_local(stacked_params, x_mb, stage_fn, axis_name: str,
                    num_microbatches: int):
    """Per-device body under shard_map.

    stacked_params: this stage's params, leading dim 1 (shard of the stack).
    x_mb: (num_microbatches, mb, ...) — full microbatch stream (replicated;
          only stage 0 reads it).
    """
    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    my_params = jax.tree_util.tree_map(lambda a: a[0], stacked_params)

    mb_shape = x_mb.shape[1:]
    n_ticks = num_microbatches + n_stages - 1

    # stage i sends to i+1; the wraparound last→0 edge carries garbage that
    # stage 0 never reads (it always selects from the input stream).
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    out0 = jnp.zeros((num_microbatches,) + mb_shape, x_mb.dtype)
    recv0 = jnp.zeros(mb_shape, x_mb.dtype)

    def tick(carry, t):
        recv, out = carry
        # Stage 0 ingests microbatch t (clamped; ticks ≥ M recompute the
        # last microbatch into the bubble — discarded downstream).
        inp = lax.dynamic_index_in_dim(
            x_mb, jnp.minimum(t, num_microbatches - 1), 0, keepdims=False)
        x_in = jnp.where(stage == 0, inp, recv)
        y = stage_fn(my_params, x_in)
        # Last stage records microbatch (t - (n_stages-1)) once warm.
        mb_idx = jnp.clip(t - (n_stages - 1), 0, num_microbatches - 1)
        record = jnp.logical_and(stage == n_stages - 1, t >= n_stages - 1)
        cur = lax.dynamic_index_in_dim(out, mb_idx, 0, keepdims=False)
        out = lax.dynamic_update_index_in_dim(
            out, jnp.where(record, y, cur), mb_idx, 0)
        recv = lax.ppermute(y, axis_name, perm)
        return (recv, out), None

    (_, out), _ = lax.scan(tick, (recv0, out0), jnp.arange(n_ticks))
    # Replicate the last stage's output buffer to every stage (psum of a
    # one-hot-selected buffer == broadcast from last stage).
    out = lax.psum(jnp.where(stage == n_stages - 1, out,
                             jnp.zeros_like(out)), axis_name)
    return out


def pipeline_apply(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stacked_params: Any,
                   x: jnp.ndarray,
                   mesh: Mesh,
                   *,
                   axis: str = PIPE_AXIS,
                   num_microbatches: Optional[int] = None) -> jnp.ndarray:
    """Run ``x`` through ``num_stages`` copies of ``stage_fn`` pipelined
    over ``mesh[axis]``.

    stage_fn: (stage_params, activation(mb, ...)) -> activation(mb, ...).
    stacked_params: pytree, leaves with leading dim == mesh.shape[axis].
    x: (batch, ...) global batch; split into ``num_microbatches`` equal
       microbatches along dim 0 (default: one per stage).
    Returns stage_fn^S applied to x, shape (batch, ...), replicated over
    the pipe axis.
    """
    n_stages = mesh.shape[axis]
    m = num_microbatches or n_stages
    if x.shape[0] % m != 0:
        raise ValueError(f"batch {x.shape[0]} not divisible into {m}"
                         " microbatches")
    x_mb = x.reshape((m, x.shape[0] // m) + x.shape[1:])

    pspec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    fn = jax.shard_map(
        lambda p, xm: _pipeline_local(p, xm, stage_fn, axis, m),
        mesh=mesh, in_specs=(pspec, P()), out_specs=P(),
        check_vma=False)
    out_mb = fn(stacked_params, x_mb)
    return out_mb.reshape((x.shape[0],) + out_mb.shape[2:])
