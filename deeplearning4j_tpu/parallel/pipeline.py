"""Pipeline parallelism: microbatched stage execution over the ``pipe`` axis.

ABSENT in the reference (SURVEY §2.11 row 7 — no PP/TP/SP/EP anywhere);
designed fresh for TPU per SURVEY §7.2 stage 7 / §7.3 item 4. The design is
the canonical TPU pipelining recipe (scaling-book style): the ``pipe`` mesh
axis holds pipeline *stages*; activations move stage-to-stage with
``lax.ppermute`` hops over ICI neighbours; a ``lax.scan`` over ticks runs
the schedule. Everything is pure, differentiable jax: ``jax.grad`` through
this function IS the backward pipeline (the VJP of ``ppermute`` is the
reverse permute, so the cool-down schedule falls out of autodiff — no
hand-written backward machinery).

Two schedules:

- **GPipe** (``repeats=1``): M microbatches through S stages,
  ``M + S - 1`` ticks, bubble fraction ``(S-1)/(M+S-1)``.
- **Circular / interleaved** (``repeats=R > 1``): each device holds R
  *non-adjacent* stages (device d owns global stages d, S+d, 2S+d, …) and
  microbatches recirculate around the ring R times — the interleaved-1F1B
  layout (Megatron "virtual pipeline"). For a fixed per-device parameter
  budget this divides the bubble by R: ``R*S`` layers cost
  ``R*M + S - 1`` ticks instead of the ``M + R*S - 1`` a GPipe pipeline of
  ``R*S`` devices would need.

1F1B's *memory* motivation (don't hold every microbatch's activations) is
answered the XLA way: ``remat=True`` wraps the stage in ``jax.checkpoint``
so the scan saves one activation per tick instead of the stage's internal
residuals, and backward recomputes — the rematerialisation trade the
hardware guide prescribes for HBM-bound training.

Constraints (standard for SPMD pipelining):
- stages are homogeneous in *shape*: one ``stage_fn`` whose params are
  stacked with a leading ``num_stages`` dim. Heterogeneous first/last
  layers (embed/unembed) stay OUTSIDE the pipelined region —
  ``PipelinedTransformerLM`` below shows the composition.
- activation shape is identical at every stage boundary.
- per-microbatch side inputs (e.g. attention masks) ride along via
  ``consts`` (leading dim = num_microbatches), gathered per tick.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

PIPE_AXIS = "pipe"


def _device_major_order(n: int, num_devices: int) -> list:
    """The circular layout's stage storage order: position p holds
    global stage ``order[p]``, where device d's contiguous R-block
    carries global stages d, S+d, 2S+d, … (R = n // num_devices). The
    ONE definition both stacking and checkpoint restacking use."""
    if n % num_devices:
        raise ValueError(f"{n} stages not divisible over"
                         f" {num_devices} devices")
    r = n // num_devices
    return [rep * num_devices + d
            for d in range(num_devices) for rep in range(r)]


def stack_stage_params(params_per_stage: Sequence[Any],
                       num_devices: Optional[int] = None) -> Any:
    """Stack per-stage parameter pytrees (identical structure) into one
    pytree with a leading ``num_stages`` dim — the layout
    ``pipeline_apply`` expects (shard dim 0 over the pipe axis).

    With ``num_devices`` given and ``len(params_per_stage) == R *
    num_devices`` for R > 1, stages are re-ordered device-major for the
    circular schedule: device d's contiguous block holds global stages
    ``d, S+d, 2S+d, …`` (its R interleaved stages)."""
    n = len(params_per_stage)
    order = (_device_major_order(n, num_devices)
             if num_devices and n > num_devices else list(range(n)))
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack([leaves[i] for i in order], 0),
        *params_per_stage)


def restack_stages(stacked_params: Any, from_devices: int,
                   to_devices: int) -> Any:
    """Permute the leading stage dim of a stacked-params pytree from one
    circular layout's device-major order to another's — the fix-up when
    a sharded checkpoint saved at pipeline size S1 restores onto S2
    (e.g. a 2-stage×2-repeat layout resharded to 4 straight stages).
    Positions follow ``stack_stage_params``: device d's block holds
    global stages d, S+d, 2S+d, …"""
    leaves = jax.tree_util.tree_leaves(stacked_params)
    n = leaves[0].shape[0]
    src = _device_major_order(n, from_devices)  # src[p] = stage at pos p
    dst = _device_major_order(n, to_devices)
    pos_of = {g: p for p, g in enumerate(src)}
    perm = jnp.asarray([pos_of[g] for g in dst])
    return jax.tree_util.tree_map(lambda a: jnp.take(a, perm, axis=0),
                                  stacked_params)


def _pipeline_local(stacked_params, x_mb, consts_mb, stage_fn,
                    axis_name: str, num_microbatches: int, repeats: int,
                    remat: bool):
    """Per-device body under shard_map.

    stacked_params: this device's R stages, leading dim R.
    x_mb: (M, mb, ...) full microbatch stream (replicated; only ring
          position 0 ingests it).
    consts_mb: pytree with leading dim M of per-microbatch side inputs.
    """
    S = lax.psum(1, axis_name)
    d = lax.axis_index(axis_name)
    M, R = num_microbatches, repeats

    mb_shape = x_mb.shape[1:]
    n_ticks = M * R + S - 1

    # ring: stage i sends to i+1. For R == 1 the wraparound edge carries
    # garbage that position 0 never reads; for the circular schedule it is
    # the real recirculation path (repeat r -> r+1).
    perm = [(i, (i + 1) % S) for i in range(S)]

    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    out0 = jnp.zeros((M,) + mb_shape, x_mb.dtype)
    recv0 = jnp.zeros(mb_shape, x_mb.dtype)

    def tick(carry, t):
        recv, out = carry
        # device d at tick t works on repeat r of microbatch m, where the
        # wavefront gives t = m + r*S + d (garbage outside the window —
        # computed in lockstep anyway, never recorded)
        r = jnp.clip((t - d) // S, 0, R - 1)
        m = jnp.mod(t - d, M)
        my_params = jax.tree_util.tree_map(
            lambda a: lax.dynamic_index_in_dim(a, r, 0, keepdims=False),
            stacked_params)
        inp = lax.dynamic_index_in_dim(x_mb, m, 0, keepdims=False)
        cst = jax.tree_util.tree_map(
            lambda a: lax.dynamic_index_in_dim(a, m, 0, keepdims=False),
            consts_mb)
        # ring position 0 ingests fresh microbatches during the first
        # injection phase; afterwards it reads the recirculated stream
        x_in = jnp.where(jnp.logical_and(d == 0, t < M), inp, recv)
        y = fn(my_params, x_in, cst)
        # last ring position records once the final repeat's wave arrives
        mb_idx = jnp.mod(t - (S - 1), M)
        record = jnp.logical_and(d == S - 1, t >= (R - 1) * M + S - 1)
        cur = lax.dynamic_index_in_dim(out, mb_idx, 0, keepdims=False)
        out = lax.dynamic_update_index_in_dim(
            out, jnp.where(record, y, cur), mb_idx, 0)
        recv = lax.ppermute(y, axis_name, perm)
        return (recv, out), None

    (_, out), _ = lax.scan(tick, (recv0, out0), jnp.arange(n_ticks))
    # Replicate the last position's output buffer to every device (psum of
    # a one-hot-selected buffer == broadcast from the last ring position).
    out = lax.psum(jnp.where(d == S - 1, out, jnp.zeros_like(out)),
                   axis_name)
    return out


def pipeline_apply(stage_fn: Callable,
                   stacked_params: Any,
                   x: jnp.ndarray,
                   mesh: Mesh,
                   *,
                   axis: str = PIPE_AXIS,
                   num_microbatches: Optional[int] = None,
                   consts: Any = None,
                   repeats: int = 1,
                   remat: bool = False) -> jnp.ndarray:
    """Run ``x`` through ``repeats * mesh[axis]`` stage applications
    pipelined over ``mesh[axis]``.

    stage_fn: ``(stage_params, activation(mb, ...)) -> activation`` or,
       when ``consts`` is given, ``(stage_params, activation, consts_mb)
       -> activation``.
    stacked_params: pytree, leaves with leading dim ``repeats *
       mesh.shape[axis]`` in the device-major order produced by
       ``stack_stage_params(..., num_devices=mesh.shape[axis])``.
    x: (batch, ...) global batch; split into ``num_microbatches`` equal
       microbatches along dim 0 (default: one per stage).
    consts: optional pytree of per-example side inputs with leading dim
       ``batch`` (split like ``x``).
    repeats: R > 1 selects the circular/interleaved schedule (requires
       ``num_microbatches == mesh.shape[axis]``).
    remat: checkpoint each stage application (recompute in backward).

    Returns the composed stages applied to x, shape (batch, ...),
    replicated over the pipe axis.
    """
    S = mesh.shape[axis]
    m = num_microbatches or S
    if x.shape[0] % m != 0:
        raise ValueError(f"batch {x.shape[0]} not divisible into {m}"
                         " microbatches")
    if repeats > 1 and m != S:
        raise ValueError(
            f"circular schedule needs num_microbatches == num_stages"
            f" ({S}); got {m} (injection would collide with"
            " recirculation)")
    x_mb = x.reshape((m, x.shape[0] // m) + x.shape[1:])
    takes_consts = consts is not None
    consts_mb = jax.tree_util.tree_map(
        lambda a: a.reshape((m, a.shape[0] // m) + a.shape[1:]),
        consts if takes_consts else ())

    def fn3(p, xm, cst):
        return stage_fn(p, xm, cst) if takes_consts else stage_fn(p, xm)

    pspec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    # Manual ONLY over the pipe axis: any other mesh axes (data, model)
    # stay GSPMD-automatic, so dp batch sharding and Megatron TP inside
    # the stage compose with the pipeline schedule in ONE mesh — the
    # standard 3D dp×tp×pp deployment (partial-auto shard_map).
    manual = (frozenset({axis}) if len(mesh.axis_names) > 1
              else frozenset())
    from deeplearning4j_tpu.parallel.mesh import compat_shard_map
    fn = compat_shard_map(
        lambda p, xm, cm: _pipeline_local(p, xm, cm, fn3, axis, m,
                                          repeats, remat),
        mesh=mesh, in_specs=(pspec, P(), P()), out_specs=P(),
        check_vma=False, axis_names=manual)
    out_mb = fn(stacked_params, x_mb, consts_mb)
    return out_mb.reshape((x.shape[0],) + out_mb.shape[2:])


class PipelinedTransformerLM:
    """Causal transformer LM with heterogeneous embed/unembed OUTSIDE the
    pipelined region and ``n_layers`` TransformerEncoderBlocks as the
    pipelined stages (the upgrade VERDICT asked over the tanh toy).

    Layout: token embedding + learned positions (replicated, every device
    computes them — they are tiny next to the blocks), then
    ``pipeline_apply`` over the block stack (GPipe or circular), then a
    final LayerNorm and a weight-tied-optional unembedding, also outside
    the region. ``loss()`` is pure and jit/grad-able; the golden test
    asserts it matches the sequential (non-pipelined) stack exactly.
    """

    def __init__(self, vocab: int, width: int, n_heads: int, n_layers: int,
                 max_len: int, mesh: Mesh, *, axis: str = PIPE_AXIS,
                 ffn_mult: int = 4, num_microbatches: Optional[int] = None,
                 remat: bool = True):
        from deeplearning4j_tpu.nn.layers.attention import (
            TransformerEncoderBlock)
        S = int(mesh.shape[axis])
        if n_layers % S:
            raise ValueError(f"n_layers={n_layers} not divisible by"
                             f" pipeline size {S}")
        self.vocab, self.width, self.max_len = vocab, width, max_len
        self.mesh, self.axis = mesh, axis
        self.repeats = n_layers // S
        self.num_microbatches = num_microbatches or S
        self.remat = remat
        self.n_layers = n_layers
        self.block = TransformerEncoderBlock(
            n_in=width, n_out=width, n_heads=n_heads, ffn_mult=ffn_mult,
            causal=True)
        from deeplearning4j_tpu.nn.layers.normalization import (
            LayerNormalization)
        self._ln_f = LayerNormalization()

    def init(self, key) -> dict:
        from deeplearning4j_tpu.nn.inputs import RecurrentType
        ke, kp, kh, kb, kl = jax.random.split(key, 5)
        rt = RecurrentType(self.width, None)
        per_stage = [self.block.initialize(jax.random.fold_in(kb, i), rt)
                     for i in range(self.n_layers)]
        S = int(self.mesh.shape[self.axis])
        return {
            "embed": 0.02 * jax.random.normal(ke, (self.vocab, self.width)),
            "pos": 0.02 * jax.random.normal(kp, (self.max_len, self.width)),
            "blocks": stack_stage_params(per_stage, num_devices=S),
            "ln_f": self._ln_f.initialize(kl, rt),
            "head": 0.02 * jax.random.normal(kh, (self.width, self.vocab)),
        }

    def _stage_fn(self):
        from deeplearning4j_tpu.nn.layers.base import LayerContext
        block = self.block

        def fn(p, h):
            y, _ = block.apply(p, {}, h, LayerContext(train=False))
            return y
        return fn

    def param_shardings(self, params, model_axis: str = "model"):
        """NamedShardings composing the pipeline stage dim with Megatron
        tensor parallelism over ``model_axis`` — the 3D dp×tp×pp layout
        (params are replicated over the data axis; the batch shards
        there). Column-parallel: Wqkv (head-major columns = whole
        heads) and FFN W1; row-parallel: Wo and W2 (GSPMD inserts the
        allreduce after the row-parallel contraction). When the mesh
        has no ``model_axis``, this degrades to stage-only sharding."""
        from jax.sharding import NamedSharding
        mesh = self.mesh
        has_tp = model_axis in mesh.axis_names
        ax = self.axis

        def ns(*spec):
            return NamedSharding(mesh, P(*spec))

        col3 = ns(ax, None, model_axis) if has_tp else ns(ax)
        row3 = ns(ax, model_axis, None) if has_tp else ns(ax)
        col2 = ns(ax, model_axis) if has_tp else ns(ax)
        by_name = {"Wqkv": col3, "W1": col3, "bqkv": col2, "b1": col2,
                   "Wo": row3, "W2": row3}

        def block_leaf(path, leaf):
            name = getattr(path[-1], "key", None) or str(path[-1])
            return by_name.get(name, ns(ax))

        return {
            "embed": ns(), "pos": ns(),
            "blocks": jax.tree_util.tree_map_with_path(
                block_leaf, params["blocks"]),
            "ln_f": jax.tree_util.tree_map(lambda _: ns(),
                                           params["ln_f"]),
            "head": ns(None, model_axis) if has_tp else ns(),
        }

    def shard_params(self, params, model_axis: str = "model"):
        """device_put ``params`` onto the composed 3D layout."""
        return jax.device_put(params,
                              self.param_shardings(params, model_axis))

    def _trunk(self, params, tokens, pipelined: bool):
        x = jnp.take(params["embed"], tokens, axis=0)
        x = x + params["pos"][: tokens.shape[1]][None]
        if pipelined:
            h = pipeline_apply(self._stage_fn(), params["blocks"], x,
                               self.mesh, axis=self.axis,
                               num_microbatches=self.num_microbatches,
                               repeats=self.repeats, remat=self.remat)
        else:
            fn = self._stage_fn()
            # device-major stack order: walk repeats-within-device —
            # global stage r*S + d sits at position d*R + r
            S = int(self.mesh.shape[self.axis])
            h = x
            for r in range(self.repeats):
                for d in range(S):
                    p = jax.tree_util.tree_map(
                        lambda a: a[d * self.repeats + r], params["blocks"])
                    h = fn(p, h)
        from deeplearning4j_tpu.nn.layers.base import LayerContext
        h, _ = self._ln_f.apply(params["ln_f"], {}, h,
                                LayerContext(train=False))
        return h

    def logits(self, params, tokens, *, pipelined: bool = True):
        return self._trunk(params, tokens, pipelined) @ params["head"]

    def loss(self, params, tokens, targets, *, pipelined: bool = True):
        """Mean next-token cross-entropy; ``pipelined=False`` runs the
        sequential reference path (golden-test oracle)."""
        lg = self.logits(params, tokens, pipelined=pipelined)
        ll = jax.nn.log_softmax(lg, axis=-1)
        nll = -jnp.take_along_axis(ll, targets[..., None], axis=-1)
        return nll.mean()
