"""Persisted AOT executable cache for the serving engine.

PR 5's warmup sweep means no live request ever pays a compile — but
every fresh process pays the WHOLE sweep before ``assert_warm()``. For
scale-to-zero, fleet rollouts and version swaps that is the cold-start
bill: tracing the model's Python forward once per ladder bucket plus an
XLA compile per (bucket, target). This module persists both halves:

1. **StableHLO blobs** (``jax.export``): one serialized exported module
   per ladder bucket. Loading one skips re-tracing the model's Python
   layer stack — ``export.deserialize(blob).call`` is a thin wrapper
   whose own trace is O(1) in model depth.
2. **XLA executable cache**: the JAX persistent compilation cache is
   pointed at ``<cache_dir>/xla`` so the backend compile of each bucket
   (including the blob-wrapper's signature, which is primed at save
   time) is a disk hit in later processes. Its entries are keyed by the
   computation fingerprint + jaxlib version + backend, so a stale entry
   can never be served — it just misses.

A ``manifest.json`` fingerprints what the blobs were exported from:
model version + weights digest, parameter tree spec, jax/jaxlib
versions, backend platform/device kind, the serving contract
(feature_shape, dtype, ladder, precision, calibration hash).
``try_load`` compares field by field and falls through to live compile
on ANY mismatch (recording which field diverged — for the precision /
calibration fields the reason carries both values, so a rejected quant
cache explains itself) — a cache can make a cold start fast, never
wrong. Mesh-sharded (multi-replica full-bucket) executables are not
exported; they fall through to live compile and still benefit from the
XLA cache half.

Format 2 manifests hold one entry PER PRECISION: f32, bf16 and int8
executables of the same model coexist in one cache dir as first-class
``entries[<precision>]`` rows with per-precision blob filenames, and a
lookup only ever consults its own precision's entry — a quantized blob
can never satisfy an f32 lookup (their fingerprints differ in
``serving.precision``, ``serving.calibration`` AND ``weights_sha256``,
since int8 committed params are different bytes) nor vice versa.

Layout on disk::

    <cache_dir>/manifest.json          per-precision fingerprints + buckets
    <cache_dir>/bucket_<N>.<precision>.stablehlo   exported modules
    <cache_dir>/xla/...                JAX persistent compilation cache
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from pathlib import Path
from typing import Any, Dict, Optional

import numpy as np

from deeplearning4j_tpu.chaos.hook import chaos_site

MANIFEST = "manifest.json"
FORMAT_VERSION = 2          # 2: per-precision entries + calibration hash

_xla_cache_lock = threading.Lock()
_xla_cache_dir: Optional[str] = None


def enable_xla_cache(path: str) -> bool:
    """Point the process-wide JAX persistent compilation cache at
    ``path`` (idempotent; the setting is global — first engine wins and
    later engines reuse it). Returns False when this jax version has no
    persistent cache support; the blob half still works."""
    global _xla_cache_dir
    import jax
    with _xla_cache_lock:
        if _xla_cache_dir is not None:
            return True
        try:
            os.makedirs(path, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", path)
            # serving sweeps are many small compiles: cache all of them,
            # not just the >1s ones the training-oriented default keeps
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
            # a compile that ran before the dir was configured pins the
            # cache "initialized but disabled" — force re-init so the
            # new dir takes effect mid-process (e.g. after model load)
            try:
                from jax.experimental.compilation_cache import (
                    compilation_cache as _cc)
            except ImportError:
                from jax._src import compilation_cache as _cc
            if hasattr(_cc, "reset_cache"):
                _cc.reset_cache()
        except Exception:
            return False
        _xla_cache_dir = path
        return True


def _tree_spec(params) -> list:
    """Stable description of a pytree's structure + leaf shapes/dtypes
    (metadata only — no device reads)."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(params)
    spec = []
    for a in leaves:
        dt = getattr(a, "dtype", None)
        spec.append([list(np.shape(a)),
                     str(dt) if dt is not None else type(a).__name__])
    return [str(treedef), spec]


def weights_digest(params) -> str:
    """sha256 over every leaf's bytes — the model-version key. One-time
    device→host read at engine start (cache setup), not a hot path."""
    import jax
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(params):
        a = np.asarray(leaf)  # host-sync-ok: one-time startup fingerprint fetch, pre-traffic
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def fingerprint(params, mstate, *, feature_shape, dtype, ladder,
                precision: str = "f32",
                calibration: Optional[str] = None,
                bf16: Optional[bool] = None,
                model_version: Optional[str] = None) -> Dict:
    """Everything a loaded executable's validity depends on.

    ``precision`` is the PrecisionPolicy tag (f32/bf16/int8) and
    ``calibration`` the int8 calibration provenance hash
    (QuantizedModel.calibration_hash()) — both are load-bearing: a
    quant entry must never satisfy an f32 lookup, and a re-calibrated
    model must never be served from stale-scale executables. ``bf16=``
    is the pre-PrecisionPolicy spelling, kept for old callers."""
    import jax
    import jaxlib
    if bf16 is not None:
        precision = "bf16" if bf16 else "f32"
    dev = jax.devices()[0]
    return {
        "format_version": FORMAT_VERSION,
        "model_version": model_version,
        "weights_sha256": weights_digest(params),
        "params_spec": _tree_spec(params),
        "model_state_spec": _tree_spec(mstate),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": {"platform": dev.platform,
                    "device_kind": dev.device_kind},
        "serving": {"feature_shape": list(feature_shape),
                    "dtype": str(np.dtype(dtype)),
                    "ladder": list(ladder),
                    "precision": str(precision),
                    "calibration": calibration},
    }


def _first_mismatch(want: Dict, got: Dict, prefix: str = "") -> Optional[str]:
    for k in want:
        w, g = want[k], got.get(k)
        if isinstance(w, dict) and isinstance(g, dict):
            sub = _first_mismatch(w, g, f"{prefix}{k}.")
            if sub:
                return sub
        elif w != g:
            return f"{prefix}{k}"
    return None


def _dig(d: Dict, dotted: str):
    for part in dotted.split("."):
        if not isinstance(d, dict):
            return None
        d = d.get(part)
    return d


def _mismatch_reason(fp: Dict, got_fp: Dict, diff: str) -> str:
    """Human-readable mismatch: always names the diverged field; for
    scalar fields (notably ``serving.precision`` and
    ``serving.calibration``) it also shows both values, so a rejected
    quant cache states exactly WHICH precision/calibration it held."""
    want_v, got_v = _dig(fp, diff), _dig(got_fp, diff)
    if all(isinstance(v, (str, int, float, bool, type(None)))
           for v in (want_v, got_v)):
        def short(v):
            s = repr(v)
            return s[:20] + "..." if len(s) > 23 else s
        return (f"fingerprint field {diff!r} diverged "
                f"(want {short(want_v)}, got {short(got_v)})")
    return f"fingerprint field {diff!r} diverged"


class AOTExecutableCache:
    """One serving engine's view of a persisted executable table.

    ``state`` after construction + ``try_load``:

    - ``"warm"``      manifest matched; blobs deserialized and in use
    - ``"cold"``      no manifest yet (first process; ``save`` fills it)
    - ``"mismatch"``  manifest found but the fingerprint diverged —
      ``reason`` names the first differing field; live compile is used
      (and ``save`` rewrites the cache for the new fingerprint)
    - ``"disabled"``  jax.export unavailable; only the XLA cache half runs
    """

    def __init__(self, cache_dir: str):
        self.dir = Path(cache_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.state = "cold"
        self.reason: Optional[str] = None
        self.hits = 0            # buckets served from a loaded blob
        self.misses = 0          # buckets that fell through to live trace
        self.quarantined = 0     # blobs failing their content checksum
        self._chaos_save = chaos_site("store.save")
        self.xla_cache_enabled = enable_xla_cache(str(self.dir / "xla"))
        try:
            from jax import export  # noqa: F401  (jax >= 0.4.34)
            self._export = export
        except ImportError:
            try:
                from jax.experimental import export  # older spelling
                self._export = export
            except ImportError:
                self._export = None
                self.state = "disabled"
                self.reason = "jax.export unavailable"

    @staticmethod
    def _precision_of(fp: Dict) -> str:
        return str(fp.get("serving", {}).get("precision", "f32"))

    @staticmethod
    def _blob_name(bucket, precision: str) -> str:
        return f"bucket_{bucket}.{precision}.stablehlo"

    # ---- load ------------------------------------------------------------
    def try_load(self, fp: Dict) -> Dict[int, Any]:
        """Deserialized ``Exported`` per bucket when the manifest's
        entry FOR THIS PRECISION matches ``fp``; {} otherwise
        (state/reason record why). Other precisions' entries are
        invisible to the lookup — they can neither satisfy nor
        invalidate it."""
        if self._export is None:
            return {}
        path = self.dir / MANIFEST
        if not path.exists():
            self.state = "cold"
            return {}
        try:
            manifest = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError) as e:
            self.state = "mismatch"
            self.reason = f"unreadable manifest: {e}"
            return {}
        precision = self._precision_of(fp)
        entries = manifest.get("entries")
        if entries is None:
            # format-1 manifest (single fingerprint, pre-precision):
            # diff against its flat fingerprint so the reason names the
            # real divergence (format_version at minimum); save()
            # rewrites it as format 2
            entry = {"fingerprint": manifest.get("fingerprint", {}),
                     "buckets": []}
        else:
            entry = entries.get(precision)
            if entry is None:
                self.state = "cold"
                self.reason = (f"no {precision!r} entry (cache holds "
                               f"{sorted(entries)})")
                return {}
        got_fp = entry.get("fingerprint", {})
        diff = _first_mismatch(fp, got_fp)
        if diff is not None:
            self.state = "mismatch"
            self.reason = _mismatch_reason(fp, got_fp, diff)
            return {}
        loaded: Dict[int, Any] = {}
        checksums = entry.get("checksums") or {}
        for bucket in entry.get("buckets", []):
            blob_path = self.dir / self._blob_name(bucket, precision)
            try:
                raw = blob_path.read_bytes()
                want = checksums.get(str(bucket))
                if want is not None and \
                        hashlib.sha256(raw).hexdigest() != want:
                    # torn or bit-rotted blob: quarantine it and fall
                    # through to live compile — a warming node must
                    # NEVER crash (or serve garbage) on store corruption
                    self._quarantine(blob_path, bucket, "checksum")
                    continue
                loaded[int(bucket)] = self._export.deserialize(
                    bytearray(raw))
            except Exception as e:
                # one bad blob falls through to live compile; the rest
                # of the table still loads
                self.misses += 1
                self.reason = f"bucket {bucket}: {type(e).__name__}"
        self.state = "warm" if loaded else "mismatch"
        return loaded

    def _quarantine(self, blob_path: Path, bucket, why: str) -> None:
        """Move a corrupt blob aside (``.quarantine`` suffix) so later
        loads don't re-pay the checksum failure and a later ``save``
        republishes a clean blob under the original name."""
        self.misses += 1
        self.quarantined += 1
        self.reason = f"bucket {bucket}: quarantined ({why})"
        try:
            os.replace(blob_path,
                       str(blob_path) + ".quarantine")
        except OSError:
            pass
        try:
            from deeplearning4j_tpu.observe.registry import (
                default_registry)
            default_registry().counter(
                "dl4j_aot_quarantined_total",
                "corrupt AOT cache blobs moved aside (content checksum "
                "or deserialize failure); each falls through to live "
                "compile").inc(1.0, bucket=str(bucket), reason=why)
        except Exception:
            pass

    # ---- save ------------------------------------------------------------
    def save(self, jit_fn, committed, fp: Dict, ladder, example) -> int:
        """Export + serialize one module per ladder bucket and prime the
        XLA cache under the blob-wrapper's compile key, then write the
        manifest (atomically, last — a crash mid-save leaves a cache
        that simply misses). Only THIS precision's entry is replaced;
        sibling precisions keep theirs (each entry's fingerprint is
        self-contained, so a stale sibling just misses at its own
        load). Returns the number of buckets saved."""
        if self._export is None:
            return 0
        import jax
        precision = self._precision_of(fp)
        params, mstate = committed
        saved = []
        checksums: Dict[str, str] = {}
        for bucket in ladder:
            x = np.zeros((int(bucket),) + tuple(example.shape[1:]),
                         example.dtype)
            try:
                exp = self._export.export(jit_fn)(params, mstate, x)
                blob = bytes(exp.serialize())
                # checksum of the TRUE bytes: corruption between save
                # and load (torn write, bit rot — or an armed chaos
                # plan mangling the write below) is caught at load
                checksums[str(int(bucket))] = hashlib.sha256(
                    blob).hexdigest()
                if self._chaos_save is not None:
                    blob, _ = self._chaos_save.mangle(blob, arg="blob")
                (self.dir / self._blob_name(bucket,  # graftlint: disable=atomic-write: blob bytes are sha256-checksummed and only become visible through the manifest's atomic os.replace; a torn blob quarantines at load
                                            precision)).write_bytes(blob)
                # prime: the loading process compiles jit(exp.call), a
                # different cache key than jit_fn's — pay it here, once,
                # so the fresh process's compile is a disk hit
                jax.jit(exp.call).lower(params, mstate, x).compile()  # graftlint: disable=recompile-hazard: one-time per-bucket cache-priming compile at save, not a live path
                saved.append(int(bucket))
            except Exception:
                continue        # that bucket warms live on load; rest save
        if saved:
            entries: Dict[str, Any] = {}
            try:
                manifest = json.loads((self.dir / MANIFEST).read_text())
                # format-1 manifests are superseded wholesale
                entries = dict(manifest.get("entries") or {})
            except Exception:
                pass
            entries[precision] = {"fingerprint": fp, "buckets": saved,
                                  "checksums": checksums}
            data = json.dumps(
                {"format_version": FORMAT_VERSION, "entries": entries},
                indent=2).encode("utf-8")
            if self._chaos_save is not None:
                data, _ = self._chaos_save.mangle(data, arg="manifest")
            tmp = self.dir / (MANIFEST + ".tmp")
            tmp.write_bytes(data)
            os.replace(tmp, self.dir / MANIFEST)
        return len(saved)

    def stats(self) -> Dict[str, Any]:
        return {"state": self.state, "reason": self.reason,
                "hits": self.hits, "misses": self.misses,
                "quarantined": self.quarantined,
                "dir": str(self.dir),
                "xla_cache": self.xla_cache_enabled}


class ArtifactStore:
    """Object-store bucket layout over the manifest format: one shared
    root holding one AOT cache dir per model key, so N serving nodes
    warm from ONE saved sweep with zero live compiles.

    Layout (local filesystem today, the key/object split maps 1:1 onto
    a GCS/S3 bucket later)::

        <root>/objects/<key>/manifest.json
        <root>/objects/<key>/bucket_<N>.<precision>.stablehlo
        <root>/objects/<key>/xla/...

    Concurrency relies on the cache's own discipline: the manifest is
    written atomically and LAST (a reader mid-save just misses), every
    entry is self-fingerprinted (a stale or foreign entry can never be
    served), and the sweep is bitwise-deterministic cross-process — so
    the first node to finish its sweep publishes, and every later node
    (or rejoiner) gets a warm start. No locks, no coordinator."""

    def __init__(self, root: str):
        self.root = Path(root)
        (self.root / "objects").mkdir(parents=True, exist_ok=True)

    @staticmethod
    def _safe_key(key: str) -> str:
        import re
        safe = re.sub(r"[^A-Za-z0-9._-]", "_", str(key))
        if not safe or safe in (".", ".."):
            raise ValueError(f"unusable artifact key {key!r}")
        return safe

    def cache_dir(self, key: str) -> str:
        """The AOT cache dir for ``key`` (created if absent) — pass it
        straight to a ServingEngine's ``aot_cache_dir``."""
        d = self.root / "objects" / self._safe_key(key)
        d.mkdir(parents=True, exist_ok=True)
        return str(d)

    def keys(self) -> list:
        base = self.root / "objects"
        return sorted(p.name for p in base.iterdir() if p.is_dir())

    def manifest(self, key: str) -> Optional[Dict[str, Any]]:
        path = (self.root / "objects" / self._safe_key(key) / MANIFEST)
        try:
            return json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            return None

    def stats(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"root": str(self.root), "keys": {}}
        for key in self.keys():
            m = self.manifest(key)
            entries = (m or {}).get("entries") or {}
            out["keys"][key] = {
                "published": m is not None,
                "precisions": {p: len(e.get("buckets", []))
                               for p, e in entries.items()},
            }
        return out
