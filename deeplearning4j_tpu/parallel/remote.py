"""RemoteDispatcher: FleetRouter's least-loaded dispatch, across nodes.

The in-process router picks the engine with the fewest in-flight
requests; this tier does the same across :class:`~deeplearning4j_tpu.
parallel.node.NodeRegistry` worker nodes over HTTP, with the failure
machinery a network hop makes mandatory:

- **per-request timeout** — a dead TCP peer must cost one timeout, not
  a hung client thread;
- **bounded exponential-backoff retry onto a DIFFERENT node** —
  predict is idempotent (same features -> same answer, no state), so a
  failed or timed-out attempt re-dispatches elsewhere; a node that
  answered 503 (shedding / draining) is healthy-but-full, and its
  ``Retry-After`` header is honored instead of the backoff curve;
- **per-node circuit breaker** — consecutive transport failures open
  the breaker (the node stops being picked *before* its heartbeat goes
  stale); after ``reset_after_s`` exactly one half-open probe is
  admitted; success closes, failure re-opens. 503s never open a
  breaker: an overloaded node is alive;
- **hedged requests** — when the primary attempt has not answered
  within ``hedge_after_s``, a second copy goes to a different node and
  the first answer wins (the loser is discarded — idempotence again).
  This is the classic tail-latency trade: a few % duplicate work for a
  p99 bounded by the second-slowest node.

Accounting invariant (tested): a request is counted in a node's local
in-flight exactly once per dispatch to THAT node, and always released
before (or independent of) the retry's increment on the next node — a
retry can never double-count, so least-loaded stays truthful under
failures.

Prometheus series (OBSERVABILITY.md ``dl4j_cluster_*``):
``dl4j_cluster_nodes{state}``, ``dl4j_cluster_breaker_state{node}``,
``dl4j_cluster_dispatch_total{node,outcome}``,
``dl4j_cluster_retries_total``, ``dl4j_cluster_hedges_total{outcome}``.
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Any, Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.chaos.hook import chaos_site
from deeplearning4j_tpu.parallel.deadline import Deadline, DeadlineExceeded
from deeplearning4j_tpu.parallel.node import NodeRegistry


class NoNodesError(RuntimeError):
    """No dispatchable node in the registry (empty fleet, everyone dead
    or draining). The autoscaler's ``note_demand`` hook fires before
    this is raised, so a scale-to-zero fleet restarts on it."""


class RemoteError(RuntimeError):
    """A request failed on every node it was tried on."""

    def __init__(self, detail: str, attempts: List[Tuple[str, str]]):
        super().__init__(detail)
        self.attempts = attempts        # [(node_id, reason), ...]


#: Gauge encoding of breaker states (closed is the healthy 0).
_BREAKER_GAUGE = {"closed": 0.0, "half_open": 0.5, "open": 1.0}


class CircuitBreaker:
    """Per-node breaker: closed -> (N consecutive failures) -> open ->
    (``reset_after_s`` elapsed) -> half-open, which admits EXACTLY one
    probe; probe success closes, probe failure re-opens. Thread-safe;
    ``clock`` is injectable so tests never sleep."""

    def __init__(self, *, failure_threshold: int = 3,
                 reset_after_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = int(failure_threshold)
        self.reset_after_s = float(reset_after_s)  # host-sync-ok: python config scalar
        self.clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive = 0
        self._opened_at: Optional[float] = None
        self._probe_inflight = False
        self.opened_total = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def would_allow(self) -> bool:
        """Peek without consuming the half-open probe slot — the picker
        uses this to skip broken nodes; only a committed send may call
        :meth:`allow`."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                return (self.clock() - self._opened_at
                        >= self.reset_after_s)
            return not self._probe_inflight

    def allow(self) -> bool:
        """Admit one request. In half-open, exactly one caller gets
        True until its verdict lands (``record_success`` /
        ``record_failure`` release the probe slot)."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if self.clock() - self._opened_at < self.reset_after_s:
                    return False
                self._state = "half_open"
                self._probe_inflight = True
                return True
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def record_success(self):
        with self._lock:
            self._state = "closed"
            self._consecutive = 0
            self._probe_inflight = False

    def record_failure(self):
        with self._lock:
            self._probe_inflight = False
            self._consecutive += 1
            trip = self._state == "half_open" \
                or (self._state == "closed"
                    and self._consecutive >= self.failure_threshold)
            if trip:
                self._state = "open"
                self._opened_at = self.clock()
                self.opened_total += 1


class _Attempt:
    """Outcome of one send to one node."""

    __slots__ = ("ok", "value", "retriable", "retry_after", "reason")

    def __init__(self, ok, value, retriable=False, retry_after=None,
                 reason=""):
        self.ok = ok
        self.value = value
        self.retriable = retriable
        self.retry_after = retry_after
        self.reason = reason


def _http_transport(url: str, body: bytes, timeout_s: float
                    ) -> Tuple[int, Dict[str, str], bytes]:
    """Default transport: ``(status, headers, body)``; non-2xx statuses
    are RETURNED (they carry shed/drain semantics), transport-level
    failures raise."""
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:  # graftlint: disable=chaos-hygiene: covered upstream — RemoteDispatcher's remote.send site wraps every transport call
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


class RemoteDispatcher:
    """Cluster front door: least-loaded node pick + timeout / retry /
    breaker / hedge. Thread-safe; one instance serves many client
    threads. ``transport``, ``clock`` and ``sleep`` are injectable so
    the failure machinery is testable without sockets or real time."""

    def __init__(self, registry: NodeRegistry, *,
                 timeout_s: float = 30.0,
                 retries: int = 2,
                 backoff_s: float = 0.05,
                 backoff_max_s: float = 2.0,
                 hedge_after_s: Optional[float] = None,
                 breaker_failures: int = 3,
                 breaker_reset_s: float = 2.0,
                 snapshot_ttl_s: float = 0.1,
                 on_no_nodes: Optional[Callable[[], Any]] = None,
                 wait_for_nodes_s: float = 0.0,
                 metrics=None,
                 transport: Optional[Callable] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 seed: Optional[int] = None):
        from deeplearning4j_tpu.observe.registry import default_registry
        self.registry = registry
        self.timeout_s = float(timeout_s)  # host-sync-ok: python config scalar
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)  # host-sync-ok: python config scalar
        self.backoff_max_s = float(backoff_max_s)  # host-sync-ok: python config scalar
        self.hedge_after_s = hedge_after_s
        self.breaker_failures = int(breaker_failures)
        self.breaker_reset_s = float(breaker_reset_s)  # host-sync-ok: python config scalar
        self.snapshot_ttl_s = float(snapshot_ttl_s)  # host-sync-ok: python config scalar
        self.on_no_nodes = on_no_nodes
        self.wait_for_nodes_s = float(wait_for_nodes_s)  # host-sync-ok: python config scalar
        self.transport = transport if transport is not None \
            else _http_transport
        # chaos sites bind once here; disarmed runs hold None and the
        # send path pays a single is-None test per attempt
        self._chaos_send = chaos_site("remote.send")
        _chaos_clock = chaos_site("remote.clock")
        if _chaos_clock is not None:
            _base_clock = clock
            self._clock_skew_s = 0.0

            def _skewed_clock():
                self._clock_skew_s += _chaos_clock.skew()
                return _base_clock() + self._clock_skew_s
            clock = _skewed_clock
        self.clock = clock
        self.sleep = sleep
        # EWMA of attempt wall time: the budget gate below refuses a
        # retry the remaining deadline can't plausibly cover
        self._attempt_ewma_s = 0.0
        self._rand = random.Random(seed)
        self._lock = threading.Lock()
        self._inflight: Dict[str, int] = {}
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._snap: List[Dict[str, Any]] = []
        self._snap_at: Optional[float] = None
        self._pool = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="dl4j-remote")

        reg = metrics if metrics is not None else default_registry()
        self._g_nodes = reg.gauge(
            "dl4j_cluster_nodes",
            "registry membership by state: up / slow / draining / dead")
        self._g_breaker = reg.gauge(
            "dl4j_cluster_breaker_state",
            "per-node circuit breaker: 0 closed, 0.5 half-open, 1 open")
        self._c_dispatch = reg.counter(
            "dl4j_cluster_dispatch_total",
            "attempts per node; outcome=ok|shed|error")
        self._c_retries = reg.counter(
            "dl4j_cluster_retries_total",
            "re-dispatches onto a different node after a retriable "
            "failure")
        self._c_hedges = reg.counter(
            "dl4j_cluster_hedges_total",
            "hedged duplicate requests; outcome=fired|won")
        self._c_bad_ra = reg.counter(
            "dl4j_remote_bad_retry_after_total",
            "malformed Retry-After headers (non-numeric, non-finite, "
            "negative, or absurd) ignored in favor of the backoff curve")
        self._c_deadline = reg.counter(
            "dl4j_remote_deadline_total",
            "dispatches given up on deadline; stage=ingress|retry")

    # ---- membership view -------------------------------------------------
    def _breaker(self, node_id: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(node_id)
            if br is None:
                br = CircuitBreaker(
                    failure_threshold=self.breaker_failures,
                    reset_after_s=self.breaker_reset_s,
                    clock=self.clock)
                self._breakers[node_id] = br
            return br

    def breaker_state(self, node_id: str) -> str:
        return self._breaker(node_id).state

    def inflight(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._inflight)

    def _nodes(self, force: bool = False) -> List[Dict[str, Any]]:
        now = self.clock()
        with self._lock:
            fresh = (self._snap_at is not None
                     and now - self._snap_at < self.snapshot_ttl_s)
            if fresh and not force:
                return list(self._snap)
        snap = self.registry.snapshot()
        counts = {"up": 0, "slow": 0, "draining": 0, "dead": 0}
        nodes = []
        for rec in snap.values():
            if rec["state"] == "draining":
                counts["draining"] += 1
            elif rec["health"] == "dead":
                counts["dead"] += 1
            else:
                counts["up" if rec["health"] == "alive" else "slow"] += 1
            if rec["state"] == "up" and rec["health"] != "dead":
                nodes.append(rec)
        for state, n in counts.items():
            self._g_nodes.set(float(n), state=state)  # host-sync-ok: python int count to gauge
        with self._lock:
            self._snap = nodes
            self._snap_at = now
        return list(nodes)

    def _pick(self, exclude) -> Optional[Dict[str, Any]]:
        """Least-loaded dispatchable node not in ``exclude`` whose
        breaker would admit a request. Load = local in-flight first
        (ground truth we maintain), gossiped pending as the tie-break
        (staleness-tolerant), alive preferred over slow."""
        candidates = []
        with self._lock:
            local = dict(self._inflight)
        for rec in self._nodes():
            nid = rec["node_id"]
            if nid in exclude:
                continue
            if not self._breaker(nid).would_allow():
                self._g_breaker.set(
                    _BREAKER_GAUGE[self._breaker(nid).state], node=nid)
                continue
            gossip = int(rec["stats"].get("pending") or 0) \
                + int(rec["stats"].get("inflight") or 0)
            health_rank = 0 if rec["health"] == "alive" else 1
            candidates.append(
                (health_rank, local.get(nid, 0), gossip, nid, rec))
        if not candidates:
            return None
        candidates.sort(key=lambda t: t[:4])
        return candidates[0][4]

    # ---- one attempt -----------------------------------------------------
    _RETRY_AFTER_CAP_S = 3600.0

    def _parse_retry_after(self, v) -> Optional[float]:
        """Defensive Retry-After parse: a malformed value (non-numeric,
        NaN/inf, negative, or over an hour) must fall back to the
        backoff curve, never drive the pause — one bad node header
        can't stall the whole client."""
        try:
            ra = float(v)  # host-sync-ok: HTTP header scalar
        except (TypeError, ValueError):
            ra = None
        if ra is None or ra != ra or ra < 0 \
                or ra > self._RETRY_AFTER_CAP_S:
            self._c_bad_ra.inc(1.0)
            return None
        return ra

    def _send(self, rec: Dict[str, Any], body: bytes,
              timeout_s: Optional[float] = None,
              path: str = "/api/predict") -> _Attempt:
        nid = rec["node_id"]
        br = self._breaker(nid)
        if not br.allow():
            return _Attempt(False, None, retriable=True,
                            reason="breaker_open")
        url = rec["url"].rstrip("/") + path
        with self._lock:
            self._inflight[nid] = self._inflight.get(nid, 0) + 1
        try:
            if self._chaos_send is not None:
                # delay sleeps here; error/timeout raise and land in
                # the except arm exactly like an organic transport fault
                self._chaos_send.fail(arg=nid)
            status, headers, payload = self.transport(
                url, body,
                self.timeout_s if timeout_s is None else timeout_s)
        except Exception as e:
            br.record_failure()
            self._g_breaker.set(_BREAKER_GAUGE[br.state], node=nid)
            self._c_dispatch.inc(1.0, node=nid, outcome="error")
            return _Attempt(False, None, retriable=True,
                            reason=f"{type(e).__name__}: {e}")
        finally:
            # released HERE, before any retry touches the next node:
            # the idempotency/accounting invariant in the module doc
            with self._lock:
                n = self._inflight.get(nid, 1) - 1
                if n <= 0:
                    self._inflight.pop(nid, None)
                else:
                    self._inflight[nid] = n
        if status == 200:
            br.record_success()
            self._g_breaker.set(_BREAKER_GAUGE[br.state], node=nid)
            self._c_dispatch.inc(1.0, node=nid, outcome="ok")
            return _Attempt(True, json.loads(payload))
        if status == 503:
            # shedding / draining: the node is alive and answering —
            # never a breaker failure; honor its Retry-After
            br.record_success()
            self._g_breaker.set(_BREAKER_GAUGE[br.state], node=nid)
            self._c_dispatch.inc(1.0, node=nid, outcome="shed")
            ra = None
            for k, v in headers.items():
                if k.lower() == "retry-after":
                    ra = self._parse_retry_after(v)
            return _Attempt(False, None, retriable=True,
                            retry_after=ra, reason="shed(503)")
        if status >= 500:
            br.record_failure()
            self._g_breaker.set(_BREAKER_GAUGE[br.state], node=nid)
            self._c_dispatch.inc(1.0, node=nid, outcome="error")
            return _Attempt(False, None, retriable=True,
                            reason=f"http {status}")
        # 4xx: the REQUEST is bad — retrying elsewhere cannot fix the
        # caller's payload, and the node did nothing wrong
        br.record_success()
        self._c_dispatch.inc(1.0, node=nid, outcome="error")
        return _Attempt(False, None, retriable=False,
                        reason=f"http {status}: "
                        f"{payload[:200].decode('utf-8', 'replace')}")

    def _send_hedged(self, rec: Dict[str, Any], body: bytes,
                     tried: set,
                     deadline: Optional[Deadline] = None) -> _Attempt:
        """Primary send with an optional hedge: when the primary has
        not answered within ``hedge_after_s``, fire a duplicate at a
        different node; first OK wins, the loser's answer is discarded
        (predict is idempotent). A deadline caps the per-attempt
        transport timeout and suppresses the hedge when the remaining
        budget can't cover waiting for it."""
        timeout_s = None if deadline is None \
            else max(deadline.cap_timeout(self.timeout_s), 1e-3)
        if self.hedge_after_s is None or (
                deadline is not None
                and deadline.remaining_s()
                < self.hedge_after_s + max(self._attempt_ewma_s,
                                           self.hedge_after_s)):
            return self._send(rec, body, timeout_s)
        primary = self._pool.submit(self._send, rec, body, timeout_s)
        done, _ = wait([primary], timeout=self.hedge_after_s)
        if done:
            return primary.result()
        hedge_rec = self._pick(exclude=tried | {rec["node_id"]})
        if hedge_rec is None:
            return primary.result()
        tried.add(hedge_rec["node_id"])
        self._c_hedges.inc(1.0, outcome="fired")
        hedge = self._pool.submit(self._send, hedge_rec, body,
                                  timeout_s)
        pending = {primary, hedge}
        first_failure = None
        while pending:
            done, pending = wait(pending, return_when=FIRST_COMPLETED)
            for f in done:
                att = f.result()
                if att.ok:
                    if f is hedge:
                        self._c_hedges.inc(1.0, outcome="won")
                    return att
                first_failure = first_failure or att
        return first_failure

    # ---- public API ------------------------------------------------------
    def predict(self, features, timeout_s: Optional[float] = None,
                deadline: Optional[Deadline] = None):
        """Dispatch one predict; returns the decoded JSON answer dict
        (``{"output": ..., "n": ...}``). Raises :class:`NoNodesError`
        when the registry has nothing dispatchable, :class:`RemoteError`
        when every attempt failed, :class:`DeadlineExceeded` when the
        caller's budget (``deadline``, or ``timeout_s`` from now) ran
        out — expired requests shed synchronously, and the retry/hedge
        loop stops as soon as the remaining budget can't cover a
        typical attempt."""
        if hasattr(features, "tolist"):
            features = features.tolist()  # host-sync-ok: HTTP request body must be host JSON
        body = json.dumps({"features": features}).encode()
        if timeout_s is not None:
            d2 = Deadline.after_ms(float(timeout_s) * 1e3,  # host-sync-ok: config scalar, host time arithmetic
                                   clock=self.clock)
            if deadline is None \
                    or d2.remaining_s() < deadline.remaining_s():
                deadline = d2
        if deadline is not None and deadline.expired:
            self._c_deadline.inc(1.0, stage="ingress")
            raise DeadlineExceeded(
                "remote predict: deadline expired before dispatch")
        tried: set = set()
        attempts: List[Tuple[str, str]] = []
        delay = self.backoff_s
        for attempt_no in range(self.retries + 1):
            rec = self._pick(exclude=tried)
            if rec is None and not tried:
                rec = self._await_first_node()
            if rec is None:
                break
            tried.add(rec["node_id"])
            t_att0 = self.clock()
            att = self._send_hedged(rec, body, tried, deadline)
            dt = max(self.clock() - t_att0, 0.0)
            self._attempt_ewma_s = dt if self._attempt_ewma_s == 0.0 \
                else 0.8 * self._attempt_ewma_s + 0.2 * dt
            if att.ok:
                return att.value
            attempts.append((rec["node_id"], att.reason))
            if not att.retriable:
                raise RemoteError(
                    f"predict rejected by node {rec['node_id']}: "
                    f"{att.reason}", attempts)
            if attempt_no >= self.retries:
                break
            # a 503's Retry-After overrides the backoff curve (the node
            # told us when it wants traffic back); otherwise bounded
            # exponential backoff with jitter
            if att.retry_after is not None:
                pause = att.retry_after
            else:
                pause = delay * (0.5 + self._rand.random())
                delay = min(delay * 2.0, self.backoff_max_s)
            if deadline is not None and pause + max(
                    self._attempt_ewma_s, 0.0) >= deadline.remaining_s():
                # the pause plus a typical attempt would blow the
                # budget: give up NOW and hand the budget back as 504
                self._c_deadline.inc(1.0, stage="retry")
                raise DeadlineExceeded(
                    "remote predict: budget exhausted after "
                    + "; ".join(f"{n}: {r}" for n, r in attempts))
            if pause > 0:
                self.sleep(min(pause, self.backoff_max_s * 4))
            self._c_retries.inc(1.0)
        if not attempts:
            raise NoNodesError(
                "no dispatchable node in the registry at "
                f"{self.registry.dir!r}")
        raise RemoteError(
            "predict failed on every tried node: "
            + "; ".join(f"{n}: {r}" for n, r in attempts), attempts)

    def records(self) -> List[Dict[str, Any]]:
        """The current dispatchable registry records — for callers
        that own placement themselves (the neighbors scatter-gather
        maps shard ownership from the gossiped stats) but still want
        this dispatcher's breakers/inflight accounting on every send."""
        return self._nodes()

    def call(self, rec: Dict[str, Any], payload: Dict[str, Any], *,
             path: str, timeout_s: Optional[float] = None,
             deadline: Optional[Deadline] = None) -> Dict[str, Any]:
        """One TARGETED dispatch: send ``payload`` to exactly the node
        in ``rec`` at ``path`` — no re-pick, no retry-elsewhere (the
        caller owns placement; a sharded corpus query cannot be
        answered by an arbitrary other node). Breaker accounting,
        deadline capping and the chaos seam are the same machinery
        :meth:`predict` uses. Raises :class:`RemoteError` on any
        failure (the caller decides between replica retry and partial
        degradation) and :class:`DeadlineExceeded` on an expired
        budget."""
        if deadline is not None and deadline.expired:
            self._c_deadline.inc(1.0, stage="ingress")
            raise DeadlineExceeded(
                f"remote call {path}: deadline expired before dispatch")
        body = json.dumps(payload).encode()
        t = self.timeout_s if timeout_s is None else float(timeout_s)  # host-sync-ok: config scalar
        if deadline is not None:
            t = max(deadline.cap_timeout(t), 1e-3)
        att = self._send(rec, body, t, path=path)
        if att.ok:
            return att.value
        raise RemoteError(
            f"call {path} failed on node {rec['node_id']}: "
            f"{att.reason}", [(rec["node_id"], att.reason)])

    def _await_first_node(self) -> Optional[Dict[str, Any]]:
        """Scale-from-zero path: signal demand, then (optionally) wait
        for the autoscaler to bring a node up."""
        if self.on_no_nodes is not None:
            try:
                self.on_no_nodes()
            except Exception:
                pass        # a hook bug must not mask the NoNodes
        if self.wait_for_nodes_s <= 0:
            return None
        deadline = self.clock() + self.wait_for_nodes_s
        while self.clock() < deadline:
            self.sleep(min(0.05, self.wait_for_nodes_s))
            rec = self._pick(exclude=set())
            if rec is not None:
                return rec
        return None

    def output(self, features, timeout_s: Optional[float] = None,
               deadline: Optional[Deadline] = None):
        """Like :meth:`predict` but returns just the output list — the
        remote spelling of ``FleetRouter.output``."""
        return self.predict(features, timeout_s=timeout_s,
                            deadline=deadline)["output"]

    def shutdown(self):
        self._pool.shutdown(wait=False)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
