"""Threshold gradient compression (1-bit encoding with residual).

TPU-native redesign of the reference's gradient-sharing codec stack
(SURVEY §2.1 "Gradient sharing / compression"):

- reference: ``optimize/solvers/accumulation/EncodedGradientsAccumulator.java:255-292``
  decodes two native codecs (``ThresholdCompression.FLEXIBLE_ENCODING`` — a
  sparse signed-index list — and ``BITMAP_ENCODING`` — 2 bits/element), and
  ``EncodingHandler.java:26`` threshold-compresses each worker's gradient,
  keeps the residual locally, and fans the message out to all peers.
- here: the *quantization* (clip to {-t, 0, +t}, residual update) is a pure
  jax function that runs on-device and jit-fuses into the train step; the
  *wire packing* is a host-side codec over numpy buffers (optionally
  accelerated by the native C++ codec in ``native/``), used only when
  updates must cross DCN — intra-slice exchange rides ICI allreduce and
  needs no compression (SURVEY §5.8).

The adaptive threshold schedule mirrors the knobs of
``SharedTrainingMaster.java:72-107`` (threshold / minThreshold /
thresholdStep / stepTrigger / stepDelay / shakeFrequency).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

FLEXIBLE_ENCODING = 0
BITMAP_ENCODING = 1

# Reference picks bitmap when density makes the sparse-index list larger
# than 2 bits/element: index list costs 32 bits per nonzero.
_BITMAP_DENSITY_CUTOFF = 2.0 / 32.0


# --------------------------------------------------------------------------
# Device-side quantization (jit-friendly, static shapes)
# --------------------------------------------------------------------------

def quantize(grad: jnp.ndarray, residual: jnp.ndarray,
             threshold: float | jnp.ndarray
             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Threshold-quantize ``grad + residual`` to signs in {-1, 0, +1}.

    Returns ``(signs:int8, new_residual)``. The decoded update is
    ``signs * threshold``; everything not transmitted stays in the
    residual (EncodingHandler keeps the residual locally — the message
    only carries the thresholded part).
    """
    acc = grad + residual
    signs = jnp.where(acc >= threshold, jnp.int8(1),
                      jnp.where(acc <= -threshold, jnp.int8(-1),
                                jnp.int8(0)))
    new_residual = acc - signs.astype(acc.dtype) * threshold
    return signs, new_residual


def dequantize(signs: jnp.ndarray, threshold: float | jnp.ndarray,
               dtype=jnp.float32) -> jnp.ndarray:
    return signs.astype(dtype) * threshold


def quantize_pytree(grads, residuals, threshold):
    """Tree-mapped :func:`quantize`; returns (signs_tree, residual_tree)."""
    flat_g, treedef = jax.tree_util.tree_flatten(grads)
    flat_r = treedef.flatten_up_to(residuals)
    out = [quantize(g, r, threshold) for g, r in zip(flat_g, flat_r)]
    signs = treedef.unflatten([s for s, _ in out])
    res = treedef.unflatten([r for _, r in out])
    return signs, res


# --------------------------------------------------------------------------
# Host-side wire codecs
# --------------------------------------------------------------------------

def encode_flexible(signs: np.ndarray) -> np.ndarray:
    """Sparse signed-index list: int32 header [FLEXIBLE, length, nnz]
    followed by one int32 per nonzero — (index+1) with sign."""
    flat = signs.reshape(-1)
    idx = np.nonzero(flat)[0]
    body = ((idx + 1) * flat[idx]).astype(np.int32)
    header = np.array([FLEXIBLE_ENCODING, flat.size, idx.size],
                      dtype=np.int32)
    return np.concatenate([header, body])


def encode_bitmap(signs: np.ndarray) -> np.ndarray:
    """2-bit/element codec: 00 zero, 01 plus, 10 minus; 16 elements per
    int32 word. Header [BITMAP, length, n_words]."""
    flat = signs.reshape(-1).astype(np.int64)
    codes = np.where(flat > 0, 1, np.where(flat < 0, 2, 0)).astype(np.uint64)
    pad = (-flat.size) % 16
    if pad:
        codes = np.concatenate([codes, np.zeros(pad, dtype=np.uint64)])
    codes = codes.reshape(-1, 16)
    shifts = (2 * np.arange(16, dtype=np.uint64))
    words = np.bitwise_or.reduce(codes << shifts, axis=1).astype(np.uint32)
    header = np.array([BITMAP_ENCODING, flat.size, words.size],
                      dtype=np.int32)
    return np.concatenate([header, words.view(np.int32)])


def encode(signs: np.ndarray) -> np.ndarray:
    """Pick FLEXIBLE vs BITMAP by density, as the reference's native
    ThresholdCompression does (EncodedGradientsAccumulator.java:255-292).
    Uses the C++ codec (native/dl4j_native.cpp) when built."""
    signs = np.asarray(signs)  # host-sync-ok: host-side codec input
    from deeplearning4j_tpu.utils import native
    msg = native.encode(signs)
    if msg is not None:
        return msg
    nnz = int(np.count_nonzero(signs))
    density = nnz / max(signs.size, 1)
    if density > _BITMAP_DENSITY_CUTOFF:
        return encode_bitmap(signs)
    return encode_flexible(signs)


def decode(message: np.ndarray, shape=None) -> np.ndarray:
    """Decode either codec back to an int8 sign array."""
    message = np.asarray(message, dtype=np.int32)  # host-sync-ok: host-side codec input
    from deeplearning4j_tpu.utils import native
    if native.available():
        out = native.decode(message)
        return out.reshape(shape) if shape is not None else out
    kind, length = int(message[0]), int(message[1])
    out = np.zeros(length, dtype=np.int8)
    if kind == FLEXIBLE_ENCODING:
        nnz = int(message[2])
        body = message[3:3 + nnz]
        idx = np.abs(body) - 1
        out[idx] = np.sign(body).astype(np.int8)
    elif kind == BITMAP_ENCODING:
        n_words = int(message[2])
        words = message[3:3 + n_words].view(np.uint32).astype(np.uint64)
        shifts = (2 * np.arange(16, dtype=np.uint64))
        codes = (words[:, None] >> shifts) & np.uint64(3)
        flat = np.where(codes == 1, 1, np.where(codes == 2, -1, 0))
        out = flat.reshape(-1)[:length].astype(np.int8)
    else:
        raise ValueError(f"unknown encoding kind {kind}")
    if shape is not None:
        out = out.reshape(shape)
    return out


def compression_ratio(message: np.ndarray, length: int,
                      dtype_bytes: int = 4) -> float:
    return (length * dtype_bytes) / max(message.nbytes, 1)


# --------------------------------------------------------------------------
# Adaptive threshold schedule
# --------------------------------------------------------------------------

@dataclass
class ThresholdSchedule:
    """Adaptive 1-bit threshold, knob-compatible with
    ``SharedTrainingMaster.java:72-107``.

    If fewer than ``step_trigger`` per-mille of elements pass the threshold
    for ``step_delay`` consecutive iterations, the threshold is decreased by
    ``threshold_step`` (never below ``min_threshold``). Every
    ``shake_frequency`` iterations a "shake" pass additionally transmits at
    ``threshold/2`` to flush stale residual.
    """
    threshold: float = 1e-3
    min_threshold: float = 1e-5
    threshold_step: float = 2.0          # divide by this on trigger
    step_trigger: float = 0.05           # fraction of elements, not permille
    step_delay: int = 50
    shake_frequency: int = 0

    _low_count: int = field(default=0, repr=False)
    _iteration: int = field(default=0, repr=False)

    def current(self) -> float:
        self._iteration += 1
        if self.shake_frequency and self._iteration % self.shake_frequency == 0:
            return self.threshold / 2.0
        return self.threshold

    def observe(self, density: float) -> None:
        """Feed back the fraction of elements that passed the threshold."""
        if density < self.step_trigger:
            self._low_count += 1
            if self._low_count >= self.step_delay:
                self.threshold = max(self.min_threshold,
                                     self.threshold / self.threshold_step)
                self._low_count = 0
        else:
            self._low_count = 0


# --------------------------------------------------------------------------
# Accumulator (API parity with EncodedGradientsAccumulator)
# --------------------------------------------------------------------------

class EncodedGradientsAccumulator:
    """N-worker broadcast accumulator over encoded updates.

    Host-side analog of ``EncodedGradientsAccumulator.java:33`` +
    ``FancyBlockingQueue`` (single-producer multi-consumer broadcast): each
    ``store_update`` quantizes one worker's gradient pytree against its own
    residual and enqueues the encoded message for every *other* worker;
    ``apply_updates`` drains a worker's queue into a dense gradient pytree.

    On TPU this path is only exercised for DCN-bound exchange or for parity
    tests — the ICI path is a plain psum (SURVEY §5.8).
    """

    def __init__(self, n_workers: int,
                 schedule: Optional[ThresholdSchedule] = None,
                 encode_wire: bool = True):
        self.n_workers = n_workers
        # One schedule per worker, as in the reference (each worker owns an
        # EncodingHandler with its own adaptive threshold) — a shared one
        # would advance step_delay/shake_frequency n_workers times per step.
        proto = schedule or ThresholdSchedule()
        self.schedules: List[ThresholdSchedule] = [
            ThresholdSchedule(threshold=proto.threshold,
                              min_threshold=proto.min_threshold,
                              threshold_step=proto.threshold_step,
                              step_trigger=proto.step_trigger,
                              step_delay=proto.step_delay,
                              shake_frequency=proto.shake_frequency)
            for _ in range(n_workers)]
        self.encode_wire = encode_wire
        self._queues: List[List[Tuple[np.ndarray, float]]] = [
            [] for _ in range(n_workers)]
        self._residuals: Dict[int, object] = {}
        self._treedef = None
        self._shapes: Optional[List[Tuple[int, ...]]] = None
        self._lock = threading.Lock()

    @property
    def schedule(self) -> ThresholdSchedule:
        return self.schedules[0]

    def _ensure_residual(self, worker: int, grads):
        if worker not in self._residuals:
            self._residuals[worker] = jax.tree_util.tree_map(
                jnp.zeros_like, grads)

    def store_update(self, worker: int, grads) -> None:
        with self._lock:
            self._ensure_residual(worker, grads)
            threshold = self.schedules[worker].current()
            residual = self._residuals[worker]
        signs, new_res = quantize_pytree(grads, residual, threshold)

        flat, treedef = jax.tree_util.tree_flatten(signs)
        flat_np = [np.asarray(s) for s in flat]  # host-sync-ok: host gather IS the compression boundary
        nnz = sum(int(np.count_nonzero(s)) for s in flat_np)
        total = sum(s.size for s in flat_np)
        concat = np.concatenate([s.reshape(-1) for s in flat_np])
        msg = encode(concat) if self.encode_wire else concat

        with self._lock:
            self._residuals[worker] = new_res
            if self._treedef is None:
                self._treedef = treedef
                self._shapes = [s.shape for s in flat_np]
            self.schedules[worker].observe(nnz / max(total, 1))
            for peer in range(self.n_workers):
                if peer != worker:
                    self._queues[peer].append((msg, threshold))

    def apply_updates(self, worker: int, dtype=np.float32):
        """Drain ``worker``'s queue; returns a dense update pytree or None."""
        with self._lock:
            pending, self._queues[worker] = self._queues[worker], []
        if not pending or self._treedef is None:
            return None
        total = sum(int(np.prod(s)) for s in self._shapes)
        acc = np.zeros(total, dtype=dtype)
        for msg, threshold in pending:
            signs = decode(msg) if self.encode_wire else msg
            acc += signs.astype(dtype) * threshold
        leaves, off = [], 0
        for shape in self._shapes:
            n = int(np.prod(shape))
            leaves.append(acc[off:off + n].reshape(shape))
            off += n
        return jax.tree_util.tree_unflatten(self._treedef, leaves)
