"""Distributed checkpointing: sharded save, cross-mesh restore, elastic
restart.

The reference has no analog — its checkpoints are single-process zips
(ModelSerializer) and Spark fault tolerance recomputes lost partitions
(SURVEY §5.3/§5.4). At pod scale the checkpoint itself is distributed and
the job that restores it may have a different chip count (preemption,
resize), so resharding is first-class (SURVEY §7.2 stage 7 "checkpoint
resharding, elastic restart semantics"):

- :func:`save_sharded` writes one ``.npz``-per-leaf layout with a JSON
  manifest. Arrays are fetched through jax, which gathers across the
  devices of a single-process mesh transparently. (Multi-host jobs need a
  per-host gather — multihost_utils — before saving; process 0 writes.)
- :func:`restore_sharded` loads the state and places it for a NEW mesh —
  any device count/topology — via the same sharding-inference rules used
  at training start. Optimizer state is restored exactly, so an elastic
  restart continues bit-identically modulo the data order.
- :class:`ElasticTrainer` wraps the fit loop with periodic sharded
  checkpoints and a ``resume()`` that reshards onto whatever mesh the
  restarted process has.
"""

from __future__ import annotations

import json
import os
import shutil
import warnings
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.optimize.solver import TrainState
from deeplearning4j_tpu.parallel.sharding import (
    apply_shardings,
    infer_param_shardings,
)


def _key_str(entry) -> str:
    for attr in ("key", "idx", "name"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def _flatten(tree) -> Dict[str, Any]:
    out = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        out["/".join(_key_str(p) for p in path)] = leaf
    return out


def save_sharded(train_state: TrainState, directory: str,
                 step: Optional[int] = None) -> str:
    """Write params/model_state/opt_state + iteration under ``directory``.
    Returns the checkpoint path (one subdir per step)."""
    it = int(train_state.iteration) if step is None else int(step)
    path = os.path.join(directory, f"step_{it:010d}")
    if os.path.exists(os.path.join(path, "COMMITTED")):
        # this step is already durably saved; rewriting would open a
        # crash window that destroys the only committed copy
        return path
    tmp = path + ".tmp"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    manifest = {"iteration": it, "groups": {}, "dtypes": {}}
    for group, tree in (("params", train_state.params),
                        ("model_state", train_state.model_state),
                        ("opt_state", train_state.opt_state)):
        leaves = _flatten(tree)
        arrays = {}
        for k, v in leaves.items():
            if not hasattr(v, "shape"):
                continue
            a = np.asarray(v)
            if a.dtype == jnp.bfloat16:
                # npz has no bf16: carry the raw bits, record the dtype
                manifest["dtypes"][f"{group}/{k}"] = "bfloat16"
                a = a.view(np.uint16)
            arrays[k] = a
        np.savez(os.path.join(tmp, f"{group}.npz"), **arrays)
        manifest["groups"][group] = sorted(arrays)
    with open(os.path.join(tmp, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    # completion marker inside the staged dir; the rename publishes it
    # atomically, so a torn write can never look committed
    with open(os.path.join(tmp, "COMMITTED"), "w") as f:
        f.write("ok")
    if os.path.isdir(path):  # uncommitted partial from a prior crash
        shutil.rmtree(path)
    os.rename(tmp, path)
    return path


def latest_checkpoint(directory: str) -> Optional[str]:
    if not os.path.isdir(directory):
        return None
    steps = [d for d in os.listdir(directory)
             if d.startswith("step_") and not d.endswith(".tmp") and
             os.path.exists(os.path.join(directory, d, "COMMITTED"))]
    if not steps:
        return None
    return os.path.join(directory, sorted(steps)[-1])


def restore_sharded(model, path: str, mesh: Optional[Mesh] = None
                    ) -> TrainState:
    """Restore a sharded checkpoint into ``model`` (already init()ed so
    the pytree structure exists), placing params for ``mesh`` — which may
    have a different device count than the mesh that saved it."""
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    loaded = {g: dict(np.load(os.path.join(path, f"{g}.npz")))
              for g in manifest["groups"]}

    dtypes = manifest.get("dtypes", {})

    def rebuild(group, template, flat: Dict[str, np.ndarray]):
        flat_t, treedef = jax.tree_util.tree_flatten_with_path(template)
        leaves = []
        consumed = set()
        for p, leaf in flat_t:
            key = "/".join(_key_str(q) for q in p)
            if key in flat:
                consumed.add(key)
                arr = flat[key]
                if dtypes.get(f"{group}/{key}") == "bfloat16":
                    import ml_dtypes
                    # stored as raw uint16 bits; reinterpret, don't convert
                    arr = arr.view(ml_dtypes.bfloat16)
                if hasattr(leaf, "shape") and \
                        tuple(leaf.shape) != tuple(np.shape(arr)):
                    raise ValueError(
                        f"checkpoint leaf {key} has shape "
                        f"{np.shape(arr)}, model expects "
                        f"{tuple(leaf.shape)}")
                leaves.append(jnp.asarray(arr))
            elif hasattr(leaf, "shape") and np.size(leaf) > 0:
                # an array the model expects but the checkpoint lacks:
                # resuming would silently mix restored and random weights
                raise KeyError(
                    f"checkpoint is missing {group} leaf {key!r} "
                    "(layer added/renamed since the save?)")
            else:
                leaves.append(leaf)  # non-array leaf (counts, None)
        unconsumed = set(flat) - consumed
        if unconsumed:
            warnings.warn(
                f"checkpoint {group} entries not used by this model: "
                f"{sorted(unconsumed)[:5]}...", stacklevel=2)
        return jax.tree_util.tree_unflatten(treedef, leaves)

    ts = model.train_state
    params = rebuild("params", ts.params, loaded.get("params", {}))
    mstate = rebuild("model_state", ts.model_state,
                     loaded.get("model_state", {}))
    opt = rebuild("opt_state", ts.opt_state, loaded.get("opt_state", {}))
    iteration = jnp.asarray(manifest["iteration"], jnp.int32)

    if mesh is not None:
        # reshard for the new topology: params by inference rules,
        # everything else replicated
        shardings = infer_param_shardings(params, mesh)
        params = apply_shardings(params, shardings)
        repl = NamedSharding(mesh, P())
        mstate = jax.device_put(mstate, repl)
        opt = jax.device_put(opt, repl)
        iteration = jax.device_put(iteration, repl)

    new_ts = TrainState(params, mstate, opt, iteration)
    model.train_state = new_ts
    return new_ts


class ElasticTrainer:
    """Periodic sharded checkpoints + resumable fit: the elastic-restart
    harness (Spark's recompute-on-failure becomes restore-and-reshard)."""

    def __init__(self, model, directory: str,
                 checkpoint_every: int = 100,
                 mesh: Optional[Mesh] = None,
                 keep_last: Optional[int] = 5):
        if keep_last is not None and keep_last < 1:
            raise ValueError(
                "keep_last must be >= 1 (or None to disable pruning)")
        self.model = model
        self.directory = directory
        self.checkpoint_every = checkpoint_every
        self.mesh = mesh
        self.keep_last = keep_last

    def _prune(self):
        """Retention (the CheckpointListener keep-last policy): drop the
        oldest committed checkpoints beyond ``keep_last``."""
        if self.keep_last is None or not os.path.isdir(self.directory):
            return
        steps = sorted(
            d for d in os.listdir(self.directory)
            if d.startswith("step_") and not d.endswith(".tmp")
            and os.path.exists(os.path.join(self.directory, d,
                                            "COMMITTED")))
        for d in steps[:-self.keep_last]:
            shutil.rmtree(os.path.join(self.directory, d))

    def resume(self) -> bool:
        """Restore the newest committed checkpoint (resharding onto this
        process's mesh). Returns True when a checkpoint was found."""
        path = latest_checkpoint(self.directory)
        if path is None:
            return False
        restore_sharded(self.model, path, mesh=self.mesh)
        return True

    def fit(self, iterator, epochs: int = 1):
        """Delegates to the model's own fit loop (listeners and epoch
        accounting intact); periodic saves ride a TrainingListener."""
        from deeplearning4j_tpu.optimize.listeners import TrainingListener

        trainer = self

        class _Saver(TrainingListener):
            def __init__(self):
                self.last_saved = None

            def iteration_done(self, model, iteration, epoch, loss,
                               etl_ms, examples):
                if self.last_saved is None:
                    self.last_saved = int(iteration) - 1
                if iteration - self.last_saved >= trainer.checkpoint_every:
                    save_sharded(model.train_state, trainer.directory)
                    trainer._prune()
                    self.last_saved = int(iteration)

        m = self.model
        saver = _Saver()
        m.add_listeners(saver)
        try:
            m.fit(iterator, epochs=epochs)
        finally:
            m.listeners.remove(saver)
        if saver.last_saved != int(m.train_state.iteration):
            save_sharded(m.train_state, self.directory)
            self._prune()
        return m
