"""Distributed checkpointing: sharded save, cross-mesh restore, elastic
restart.

The reference has no analog — its checkpoints are single-process zips
(ModelSerializer) and Spark fault tolerance recomputes lost partitions
(SURVEY §5.3/§5.4). At pod scale the checkpoint itself is distributed and
the job that restores it may have a different chip count (preemption,
resize), so resharding is first-class (SURVEY §7.2 stage 7 "checkpoint
resharding, elastic restart semantics"):

- :func:`save_sharded` writes per-process shard files (format 2): every
  process stores ONLY its addressable shards — no full-array gather
  anywhere — and process 0 publishes the manifest after a global barrier,
  so pod-scale models that never fit on one host checkpoint to a shared
  filesystem orbax-style.
- :func:`restore_sharded` loads the state and places it for a NEW mesh —
  any device count/topology — via the same sharding-inference rules used
  at training start; each process assembles only the shard regions it
  will hold (``jax.make_array_from_callback``), and optimizer-state
  leaves that mirror a param get that param's sharding. Optimizer state
  is restored exactly, so an elastic restart continues bit-identically
  modulo the data order.
- :class:`ElasticTrainer` wraps the fit loop with periodic sharded
  checkpoints and a ``resume()`` that reshards onto whatever mesh the
  restarted process has.
"""

from __future__ import annotations

import json
import os
import shutil
import warnings
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.optimize.solver import TrainState
from deeplearning4j_tpu.parallel.sharding import infer_param_shardings


def _key_str(entry) -> str:
    for attr in ("key", "idx", "name"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def _flatten(tree) -> Dict[str, Any]:
    out = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        out["/".join(_key_str(p) for p in path)] = leaf
    return out


def _barrier(name: str) -> None:
    if jax.process_count() > 1:
        # Uneven-device-count-safe barrier: the tiny device-sharded
        # reduction forces every process to participate.
        # (multihost_utils.sync_global_devices crashes when processes
        # own unequal numbers of devices.)
        import zlib
        from deeplearning4j_tpu.parallel.mesh import (
            global_device_value_range)
        h = float(zlib.crc32(name.encode()) % (1 << 20))  # host-sync-ok: Python crc32 constant, no device value
        mn, mx = global_device_value_range(h)
        if mn != mx:             # pragma: no cover
            raise RuntimeError(
                f"barrier {name!r} mismatch across processes")


def _shard_starts(index, shape) -> list:
    """Global start offsets of a shard's slice tuple."""
    starts = []
    for sl, dim in zip(index, shape):
        starts.append(0 if sl.start is None else int(sl.start))
    return starts


def save_sharded(train_state: TrainState, directory: str,
                 step: Optional[int] = None,
                 emergency: bool = False) -> str:
    """Write params/model_state/opt_state + iteration under ``directory``.

    Multihost-safe: every process writes ONLY its addressable shards (one
    ``{group}.proc{K}.npz`` + index sidecar per process per group — no
    full-array gather anywhere, so a pod-scale model that never fits on
    one host checkpoints fine on a shared filesystem, orbax-style).
    Process 0 publishes the manifest + COMMITTED marker after a global
    barrier. Returns the checkpoint path (one subdir per step).

    ``emergency=True`` is the peer-loss path: NO barriers (a dead peer
    would hang them forever) — this process alone writes a complete,
    committed checkpoint into ``step_XXXX.em{rank}``. Requires every
    array leaf to be fully addressable from this process (true for
    replicated data-parallel state); partially-sharded state raises
    rather than committing a checkpoint with silent zero-filled holes.
    """
    it = int(train_state.iteration) if step is None else int(step)
    pidx = jax.process_index()
    name = f"step_{it:010d}" + (f".em{pidx}" if emergency else "")
    path = os.path.join(directory, name)
    if os.path.exists(os.path.join(path, "COMMITTED")):
        # this step is already durably saved; rewriting would open a
        # crash window that destroys the only committed copy
        return path
    if emergency:
        for group, tree in (("params", train_state.params),
                            ("model_state", train_state.model_state),
                            ("opt_state", train_state.opt_state)):
            for k, v in _flatten(tree).items():
                if isinstance(v, jax.Array) and \
                        not v.is_fully_addressable:
                    raise ValueError(
                        f"emergency checkpoint: {group} leaf {k!r} is "
                        "not fully addressable from this process — a "
                        "solo save would commit a checkpoint with "
                        "zero-filled holes")
    tmp = path + ".tmp"
    if pidx == 0 or emergency:
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
    if not emergency:
        _barrier(f"ckpt_mkdir_{it}")
    manifest = {"format": 2, "iteration": it,
                "process_count": jax.process_count(),
                "groups": {}, "dtypes": {}, "shapes": {}}
    for group, tree in (("params", train_state.params),
                        ("model_state", train_state.model_state),
                        ("opt_state", train_state.opt_state)):
        leaves = _flatten(tree)
        arrays: Dict[str, np.ndarray] = {}
        index: Dict[str, Dict[str, Any]] = {}
        names = []
        for k, v in leaves.items():
            if not hasattr(v, "shape"):
                continue
            names.append(k)
            is_bf16 = v.dtype == jnp.bfloat16
            if is_bf16:
                manifest["dtypes"][f"{group}/{k}"] = "bfloat16"
            manifest["shapes"][f"{group}/{k}"] = list(np.shape(v))
            if isinstance(v, jax.Array) and hasattr(v, "addressable_shards"):
                # replica_id==0 dedups replicated copies (exactly one
                # process/device owns each piece of the global array).
                # Emergency saves can't rely on replica 0 being local
                # (the dead peer may have owned it): dedup by shard
                # index instead — full addressability was checked above.
                seen = set()
                for i, s in enumerate(v.addressable_shards):
                    if emergency:
                        sig = str(s.index)
                        if sig in seen:
                            continue
                        seen.add(sig)
                    elif s.replica_id != 0:
                        continue
                    a = np.asarray(s.data)  # host-sync-ok: checkpoint save writes host shards by design
                    if is_bf16:
                        a = a.view(np.uint16)
                    ent = f"{k}::{i}"
                    arrays[ent] = a
                    index[ent] = {"leaf": k, "dtype": str(a.dtype),
                                  "start": _shard_starts(s.index, v.shape)}
            elif pidx == 0 or emergency:  # plain numpy leaf: identical everywhere
                a = np.asarray(v)  # host-sync-ok: checkpoint save writes host shards by design
                if is_bf16:
                    a = a.view(np.uint16)
                arrays[f"{k}::0"] = a
                index[f"{k}::0"] = {"leaf": k, "dtype": str(a.dtype),
                                    "start": [0] * np.ndim(v)}
        np.savez(os.path.join(tmp, f"{group}.proc{pidx:04d}.npz"), **arrays)
        with open(os.path.join(tmp, f"{group}.proc{pidx:04d}.idx.json"),
                  "w") as f:
            json.dump(index, f)
        manifest["groups"][group] = sorted(set(names))
    if not emergency:
        _barrier(f"ckpt_written_{it}")
    if pidx == 0 or emergency:
        if emergency:
            manifest["process_count"] = 1
            manifest["emergency"] = {"process_index": pidx}
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump(manifest, f)
        # completion marker inside the staged dir; the rename publishes it
        # atomically, so a torn write can never look committed
        with open(os.path.join(tmp, "COMMITTED"), "w") as f:
            f.write("ok")
        if os.path.isdir(path):  # uncommitted partial from a prior crash
            shutil.rmtree(path)
        os.rename(tmp, path)
    if not emergency:
        _barrier(f"ckpt_commit_{it}")
    return path


def latest_checkpoint(directory: str) -> Optional[str]:
    if not os.path.isdir(directory):
        return None
    steps = [d for d in os.listdir(directory)
             if d.startswith("step_") and not d.endswith(".tmp") and
             os.path.exists(os.path.join(directory, d, "COMMITTED"))]
    if not steps:
        return None
    return os.path.join(directory, sorted(steps)[-1])


class _GroupReader:
    """Lazy region reads over a checkpoint group: a leaf is assembled
    piece-by-piece and only for the requested global region, so a process
    restoring onto a sharded mesh never materializes the full array
    (format 2; the legacy single-npz format 1 reads whole leaves)."""

    def __init__(self, path: str, group: str, manifest: dict):
        self.group = group
        self.shapes = {k.split("/", 1)[1]: tuple(v)
                       for k, v in manifest.get("shapes", {}).items()
                       if k.startswith(group + "/")}
        self._pieces: Dict[str, list] = {}
        self._dtypes: Dict[str, np.dtype] = {}
        self._legacy = None
        if manifest.get("format", 1) < 2:
            self._legacy = np.load(os.path.join(path, f"{group}.npz"))
            for k in self._legacy.files:
                self._pieces[k] = []
                self.shapes.setdefault(k, tuple(self._legacy[k].shape))
            return
        for pf in sorted(f for f in os.listdir(path)
                         if f.startswith(f"{group}.proc")
                         and f.endswith(".npz")):
            with open(os.path.join(
                    path, pf[:-len(".npz")] + ".idx.json")) as fh:
                index = json.load(fh)
            npz = np.load(os.path.join(path, pf))  # lazy per-entry zip
            for ent, meta in index.items():
                self._pieces.setdefault(meta["leaf"], []).append(
                    (tuple(meta["start"]), npz, ent))
                if "dtype" in meta:
                    self._dtypes[meta["leaf"]] = np.dtype(meta["dtype"])

    def keys(self):
        return set(self._pieces)

    def read(self, key: str, region=None) -> np.ndarray:
        """Assemble the leaf (or just ``region``, a tuple of slices into
        the global shape) from the pieces that overlap it."""
        if self._legacy is not None:
            a = self._legacy[key]
            return a if region is None else np.ascontiguousarray(a[region])
        shape = self.shapes[key]
        pieces = self._pieces[key]
        if region is None:
            region = tuple(slice(0, d) for d in shape)
        lo = [0 if r.start is None else int(r.start) for r in region]
        hi = [shape[i] if r.stop is None else int(r.stop)
              for i, r in enumerate(region)]
        dtype = self._dtypes.get(key)
        if dtype is None:  # pre-sidecar-dtype save: probe the first piece
            dtype = pieces[0][1][pieces[0][2]].dtype if pieces \
                else np.float32
        out = np.zeros([b - a for a, b in zip(lo, hi)], dtype)
        for pstart, npz, ent in pieces:
            piece = npz[ent]
            src, dst, skip = [], [], False
            for d in range(len(shape)):
                a = max(lo[d], pstart[d])
                b = min(hi[d], pstart[d] + piece.shape[d])
                if a >= b:
                    skip = True
                    break
                src.append(slice(a - pstart[d], b - pstart[d]))
                dst.append(slice(a - lo[d], b - lo[d]))
            if not skip:
                out[tuple(dst)] = piece[tuple(src)]
        return out


def mirror_opt_shardings(opt_state, params, param_shardings, replicated):
    """Sharding tree for an optimizer state: each leaf whose pytree path
    ends with a param's path (optax states embed the param tree, e.g.
    ScaleByAdamState.mu/nu) and matches its shape gets that param's
    sharding; everything else (step counts, scalars) is replicated."""
    pflat, _ = jax.tree_util.tree_flatten_with_path(params)
    sflat, _ = jax.tree_util.tree_flatten_with_path(param_shardings)
    by_path = {}
    for (pp, leaf), (_, sh) in zip(pflat, sflat):
        key = tuple(_key_str(q) for q in pp)
        by_path[key] = (tuple(getattr(leaf, "shape", ())), sh)
    oflat, otree = jax.tree_util.tree_flatten_with_path(opt_state)
    out = []
    for op, leaf in oflat:
        okey = tuple(_key_str(q) for q in op)
        sh = replicated
        shape = tuple(getattr(leaf, "shape", ()))
        if shape:
            for pkey, (pshape, psh) in by_path.items():
                if (pshape == shape and len(okey) >= len(pkey)
                        and okey[-len(pkey):] == pkey):
                    sh = psh
                    break
        out.append(sh)
    return jax.tree_util.tree_unflatten(otree, out)


def _unconsumed_msg(group: str, unconsumed) -> str:
    """Warning text for checkpoint entries the model has no leaf for:
    list up to 5, and say how many more there are ONLY when there are
    more (the old text appended "..." even for a complete listing)."""
    shown = sorted(unconsumed)[:5]
    more = len(unconsumed) - len(shown)
    msg = (f"checkpoint {group} entries not used by this model: "
           f"{shown}")
    if more > 0:
        msg += f" (+{more} more)"
    return msg


def restore_sharded(model, path: str, mesh: Optional[Mesh] = None,
                    param_shardings=None) -> TrainState:
    """Restore a sharded checkpoint into ``model`` (already init()ed so
    the pytree structure exists), placing params for ``mesh`` — which may
    have a different device count OR a different layout (e.g. a 3D
    dp×tp×pp mesh resharded to a different dp/tp/pp split) than the mesh
    that saved it. ``param_shardings`` overrides the inferred target
    shardings with an explicit tree (matching ``params``' structure) —
    how the 3D pipelined-TP layouts restore (the DP-default inference
    knows nothing about Megatron column/row splits)."""
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    dtypes = manifest.get("dtypes", {})
    ts = model.train_state

    # Target shardings come from the TEMPLATE trees (shapes known before
    # any data is read) so each leaf can be constructed directly with its
    # final placement — a process on a sharded mesh reads only the shard
    # regions it will hold, never the whole array.
    if param_shardings is not None:
        t_sh = jax.tree_util.tree_structure(param_shardings)
        t_p = jax.tree_util.tree_structure(ts.params)
        if t_sh != t_p:
            raise ValueError(
                "param_shardings tree structure does not match the "
                f"model's params: {t_sh} vs {t_p} — a silent zip "
                "misalignment would restore arrays with the wrong "
                "layouts")
        if mesh is None:
            some = jax.tree_util.tree_leaves(param_shardings)[0]
            mesh = some.mesh
        param_sh = param_shardings
        repl = NamedSharding(mesh, P())
        opt_sh = mirror_opt_shardings(ts.opt_state, ts.params, param_sh,
                                      repl)
        mstate_sh = jax.tree_util.tree_map(lambda _: repl, ts.model_state)
    elif mesh is not None:
        param_sh = infer_param_shardings(ts.params, mesh)
        repl = NamedSharding(mesh, P())
        opt_sh = mirror_opt_shardings(ts.opt_state, ts.params, param_sh,
                                      repl)
        mstate_sh = jax.tree_util.tree_map(lambda _: repl, ts.model_state)
    else:
        param_sh = opt_sh = mstate_sh = repl = None

    def rebuild(group, template, shardings):
        reader = _GroupReader(path, group, manifest)
        flat_t, treedef = jax.tree_util.tree_flatten_with_path(template)
        sh_list = ([None] * len(flat_t) if shardings is None else
                   jax.tree_util.tree_leaves(
                       shardings, is_leaf=lambda x: x is None))
        leaves = []
        consumed = set()
        stored_keys = reader.keys()
        for (p, leaf), sh in zip(flat_t, sh_list):
            key = "/".join(_key_str(q) for q in p)
            if key in stored_keys:
                consumed.add(key)
                is_bf16 = dtypes.get(f"{group}/{key}") == "bfloat16"

                def fetch(region=None, _k=key, _b=is_bf16):
                    arr = reader.read(_k, region)
                    if _b:
                        import ml_dtypes
                        # raw uint16 bits; reinterpret, don't convert
                        arr = arr.view(ml_dtypes.bfloat16)
                    return arr

                stored_shape = reader.shapes.get(key)
                if hasattr(leaf, "shape") and stored_shape is not None and \
                        tuple(leaf.shape) != tuple(stored_shape):
                    raise ValueError(
                        f"checkpoint leaf {key} has shape "
                        f"{tuple(stored_shape)}, model expects "
                        f"{tuple(leaf.shape)}")
                if sh is not None and hasattr(leaf, "shape"):
                    leaves.append(jax.make_array_from_callback(
                        tuple(leaf.shape), sh, fetch))
                else:
                    # copy=True, never asarray: CPU asarray zero-copy
                    # aliases aligned host arrays, and the resumed fit's
                    # donated step would hand XLA a buffer the reader's
                    # numpy still owns (intermittent heap corruption)
                    leaves.append(jnp.array(fetch(), copy=True))
            elif hasattr(leaf, "shape") and np.size(leaf) > 0:
                # an array the model expects but the checkpoint lacks:
                # resuming would silently mix restored and random weights
                raise KeyError(
                    f"checkpoint is missing {group} leaf {key!r} "
                    "(layer added/renamed since the save?)")
            else:
                leaves.append(leaf)  # non-array leaf (counts, None)
        unconsumed = stored_keys - consumed
        if unconsumed:
            warnings.warn(_unconsumed_msg(group, unconsumed),
                          stacklevel=2)
        return jax.tree_util.tree_unflatten(treedef, leaves)

    params = rebuild("params", ts.params, param_sh)
    mstate = rebuild("model_state", ts.model_state, mstate_sh)
    opt = rebuild("opt_state", ts.opt_state, opt_sh)
    iteration = jnp.asarray(manifest["iteration"], jnp.int32)
    if mesh is not None:
        iteration = jax.device_put(iteration, repl)

    new_ts = TrainState(params, mstate, opt, iteration)
    model.train_state = new_ts
    try:
        from deeplearning4j_tpu.observe.registry import default_registry
        r = default_registry()
        r.counter("dl4j_elastic_restore_total",
                  "sharded-checkpoint restore events (elastic "
                  "resume/reshape)").inc()
        r.gauge("dl4j_elastic_restored_step",
                "iteration of the most recent restored checkpoint"
                ).set(manifest["iteration"])
    except Exception:                          # pragma: no cover
        pass  # observability must never fail a restore
    return new_ts


class ElasticTrainer:
    """Periodic sharded checkpoints + resumable fit: the elastic-restart
    harness (Spark's recompute-on-failure becomes restore-and-reshard)."""

    def __init__(self, model, directory: str,
                 checkpoint_every: int = 100,
                 mesh: Optional[Mesh] = None,
                 keep_last: Optional[int] = 5):
        if keep_last is not None and keep_last < 1:
            raise ValueError(
                "keep_last must be >= 1 (or None to disable pruning)")
        self.model = model
        self.directory = directory
        self.checkpoint_every = checkpoint_every
        self.mesh = mesh
        self.keep_last = keep_last

    def _prune(self):
        """Retention (the CheckpointListener keep-last policy): drop the
        oldest committed checkpoints beyond ``keep_last``.

        Multi-process: ONLY process 0 prunes, and only after the commit
        barrier in ``save_sharded`` has completed (the caller's save
        returned). Every process racing the same ``shutil.rmtree`` was a
        crash window: a process could delete a victim another process
        was still listing, and — worse — a slow process could observe a
        half-deleted checkpoint as the 'latest' on resume."""
        if jax.process_index() != 0:
            return
        if self.keep_last is None or not os.path.isdir(self.directory):
            return
        steps = sorted(
            d for d in os.listdir(self.directory)
            if d.startswith("step_") and not d.endswith(".tmp")
            and os.path.exists(os.path.join(self.directory, d,
                                            "COMMITTED")))
        for d in steps[:-self.keep_last]:
            shutil.rmtree(os.path.join(self.directory, d))

    def resume(self) -> bool:
        """Restore the newest committed checkpoint (resharding onto this
        process's mesh). Returns True when a checkpoint was found."""
        from deeplearning4j_tpu.observe.tracer import get_tracer
        path = latest_checkpoint(self.directory)
        if path is None:
            return False
        with get_tracer(self.model).span("checkpoint", cat="io",
                                         op="restore"):
            restore_sharded(self.model, path, mesh=self.mesh)
        return True

    def fit(self, iterator, epochs: int = 1):
        """Delegates to the model's own fit loop (listeners and epoch
        accounting intact); periodic saves ride a TrainingListener."""
        from deeplearning4j_tpu.observe.tracer import get_tracer
        from deeplearning4j_tpu.optimize.listeners import TrainingListener

        trainer = self

        class _Saver(TrainingListener):
            def __init__(self):
                self.last_saved = None

            def iteration_done(self, model, iteration, epoch, loss,
                               etl_ms, examples):
                if self.last_saved is None:
                    self.last_saved = int(iteration) - 1
                if iteration - self.last_saved >= trainer.checkpoint_every:
                    with get_tracer(model).span("checkpoint", cat="io",
                                                op="save"):
                        save_sharded(model.train_state, trainer.directory)
                        trainer._prune()
                    self.last_saved = int(iteration)

        m = self.model
        saver = _Saver()
        m.add_listeners(saver)
        try:
            m.fit(iterator, epochs=epochs)
        except BaseException:
            # Best-effort emergency save: chaos resume then loses at
            # most ``checkpoint_every`` steps, not the whole tail since
            # the last periodic save. Never mask the original failure —
            # the state may be garbage (donated buffers, poisoned
            # arrays), in which case the save itself raises and is
            # swallowed. Multi-process uses the barrier-free emergency
            # path: a dead peer would hang the commit barrier forever.
            try:
                if m.train_state is not None:
                    save_sharded(m.train_state, self.directory,
                                 emergency=jax.process_count() > 1)
                    self._prune()
            except BaseException as save_err:
                warnings.warn(
                    "elastic trainer: emergency checkpoint failed "
                    f"({type(save_err).__name__}: {save_err}); "
                    "original exception propagates", stacklevel=2)
            raise
        finally:
            m.listeners.remove(saver)
        if saver.last_saved != int(m.train_state.iteration):
            with get_tracer(m).span("checkpoint", cat="io",
                                    op="save"):
                save_sharded(m.train_state, self.directory)
                self._prune()
        return m
