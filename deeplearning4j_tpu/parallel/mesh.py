"""Device mesh construction.

The TPU-native replacement for the reference's device-affinity machinery
(JITA ``AffinityManager`` thread↔GPU pinning used by ParallelWrapper at
deeplearning4j-scaleout-parallelwrapper/.../ParallelWrapper.java:195 and the
Aeron ``VoidParameterServer`` mesh discovery — SURVEY §2.14): one
``jax.sharding.Mesh`` over all addressable devices, with named axes for
each parallelism strategy:

- ``data``  — data parallelism (ParallelWrapper / Spark masters analog)
- ``model`` — tensor parallelism (no reference analog; SURVEY §2.11 row 7)
- ``seq``   — sequence/context parallelism (ring attention)
- ``pipe``  — pipeline stages

Multi-host: ``jax.distributed.initialize`` + the same Mesh spanning all
processes; XLA routes collectives over ICI within a slice and DCN across
slices. No parameter server, no gradient compression — the interconnect is
the parameter server.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"


def create_mesh(axes: Optional[Dict[str, int]] = None,
                devices: Optional[Sequence] = None) -> Mesh:
    """Build a mesh. Default: all devices on the data axis.

    ``axes`` values may include one -1 entry meaning "everything left",
    e.g. {"data": -1, "model": 4}.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not axes:
        axes = {DATA_AXIS: n}
    names = list(axes.keys())
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis may be -1")
    fixed = math.prod(s for s in sizes if s != -1)
    if -1 in sizes:
        if n % fixed != 0:
            raise ValueError(f"{n} devices not divisible by fixed axes {fixed}")
        sizes[sizes.index(-1)] = n // fixed
    total = math.prod(sizes)
    if total != n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total}"
                         f" devices, have {n}")
    dev_array = np.asarray(devices).reshape(sizes)  # host-sync-ok: device objects, not device data
    return Mesh(dev_array, tuple(names))


def create_3d_mesh(dp: int, tp: int, pp: int,
                   devices: Optional[Sequence] = None) -> Mesh:
    """dp×tp×pp mesh with the canonical axis order
    ``(data, model, pipe)`` — the composed-parallelism layout the
    PipelinedTransformerLM's ``param_shardings`` expects. Device order
    is whatever ``devices`` (default: ``jax.devices()``) yields, so the
    pipe axis varies fastest — stage-major placement, matching the
    device-major stage stacking in ``restack_stages``."""
    return create_mesh({DATA_AXIS: dp, MODEL_AXIS: tp, PIPE_AXIS: pp},
                       devices)


def local_device_count() -> int:
    return jax.local_device_count()


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None):
    """Multi-host bring-up (replaces VoidParameterServer.init + Aeron mesh
    discovery, SharedTrainingWrapper.java:206-244). On TPU pods with the
    standard runtime, argumentless initialize() autodetects everything.

    On the CPU backend, multiprocess computations need an explicit
    collectives transport — without one every cross-process jit fails
    with "Multiprocess computations aren't implemented on the CPU
    backend". Select gloo before the backend client is created; the
    knob is CPU-only so it is harmless on TPU/GPU, and absent on jax
    versions where CPU collectives were on by default."""
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass
    if coordinator_address is None:
        jax.distributed.initialize()
    else:
        jax.distributed.initialize(coordinator_address, num_processes,
                                   process_id)


import functools


@functools.lru_cache(maxsize=4)
def _device_range_fn(devs):
    """Cached (jitted reduction, mesh) over one flat device tuple — a
    fresh jit per call would re-trace/compile on every barrier."""
    mesh = Mesh(np.array(devs), ("d",))
    repl = NamedSharding(mesh, P())
    fn = jax.jit(lambda a: (a.min(), a.max()),
                 out_shardings=(repl, repl))
    return fn, mesh


def global_device_value_range(value: float) -> tuple:
    """(min, max) of a per-process scalar across ALL devices of ALL
    processes, via a tiny device-sharded reduction. Safe when processes
    own UNEVEN device counts (multihost_utils.process_allgather stacks
    per-process then tiles per-device and crashes on uneven layouts).
    Every process must call this — it doubles as a barrier."""
    devs = tuple(jax.devices())
    fn, mesh = _device_range_fn(devs)
    sh = NamedSharding(mesh, P("d"))
    loc = jax.local_device_count()
    arr = jax.make_array_from_process_local_data(
        sh, np.full((loc,), value, np.float64), (len(devs),))
    mn, mx = fn(arr)
    return float(mn), float(mx)  # host-sync-ok: barrier helper: the sync is the point


def compat_shard_map(f, mesh: Mesh, in_specs, out_specs,
                     check_vma: bool = False, axis_names=None):
    """``jax.shard_map`` across JAX versions. New JAX exposes
    ``jax.shard_map(..., check_vma=..., axis_names=...)``; 0.4.x has
    ``jax.experimental.shard_map.shard_map(..., check_rep=..., auto=...)``
    where ``auto`` is the complement of the manual ``axis_names`` set.
    An empty/None ``axis_names`` means fully manual in both."""
    if hasattr(jax, "shard_map"):
        kw = dict(check_vma=check_vma)
        if axis_names:
            kw["axis_names"] = axis_names
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _sm
    auto = (frozenset(mesh.axis_names) - frozenset(axis_names)
            if axis_names else frozenset())
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma, auto=auto)


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Leading-dim (batch) sharding for input batches."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
