"""Multi-node serving: gossiped node registry + node lifecycle.

The fleet story so far stops at one process: FleetRouter
(parallel/fleet.py) fronts in-process ModelPools. This module grows it
into a cluster tier (ROADMAP item 1, the DL4J L7 front door at fleet
scale):

- :class:`NodeRegistry` — a file-gossiped membership view, the serving
  analog of CollectiveWatchdog's heartbeat files (parallel/cluster.py).
  Every worker node writes ``node_<id>.json`` (atomic tmp+rename) with
  its URL, state and a stats snapshot; any reader classifies each
  record's age through the SAME
  :func:`~deeplearning4j_tpu.parallel.cluster.classify_heartbeat_age`
  boundary the training watchdog uses (exactly at a threshold -> the
  less severe class), so "slow vs dead" can never disagree between the
  two tiers. A shared filesystem is the transport (NFS/GCS-fuse in
  production, tmpfs in tests); nothing here assumes a coordinator.
- :class:`ServingNode` — one worker: a FleetRouter-fronted ServingEngine
  behind the UI HTTP surface, heartbeating into a registry. Joining
  nodes warm from a shared :class:`~deeplearning4j_tpu.parallel.
  aot_cache.ArtifactStore` (N nodes, one saved sweep, zero live
  compiles). ``drain()`` is the graceful-exit path: mark draining in
  the gossip (dispatchers stop routing here), refuse NEW predicts with
  503 + ``Retry-After``, finish every accepted in-flight request,
  deregister, then stop — SIGTERM is wired to it via
  :func:`install_sigterm_drain` so a rolling restart never drops an
  accepted request.
- :class:`AutoScaler` — replica-count control loop with the AIMD shed
  controller's sensors: the gossiped windowed p99 vs the SLO plus total
  queue depth decide scale-up; sustained idleness decides scale-down,
  all the way to **zero** nodes when ``min_nodes=0`` (cold start is
  bounded by the artifact-store warm-up, PERF r9, plus the dispatcher's
  ``on_no_nodes`` demand signal re-spawning the first node).

The HTTP dispatch half (circuit breakers, retries, hedging) lives in
parallel/remote.py; telemetry lands in the ``dl4j_cluster_*`` series
(OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from deeplearning4j_tpu.chaos.hook import chaos_site
from deeplearning4j_tpu.parallel.cluster import classify_heartbeat_age

#: Node gossip states. ``draining`` nodes are alive (they still answer
#: in-flight work and their heartbeat stays fresh) but must receive no
#: new dispatches.
NODE_UP = "up"
NODE_DRAINING = "draining"


class NodeRegistry:
    """File-gossiped membership: one ``node_<id>.json`` per worker.

    Heartbeat classification (``health`` in :meth:`snapshot`) reuses
    the CollectiveWatchdog boundary: age exactly at ``stale_after_s``
    is **slow** (still dispatchable, deprioritized), strictly past
    ``dead_after_s`` is **dead** (invisible to dispatch). Records are
    written atomically, so a rejoining node with a crashed
    predecessor's stale file simply overwrites it — same contract as a
    rejoining watchdog rank.
    """

    def __init__(self, registry_dir: str, *,
                 stale_after_s: float = 2.0,
                 dead_after_s: float = 6.0):
        if dead_after_s < stale_after_s:
            raise ValueError(
                f"dead_after_s {dead_after_s} < stale_after_s "
                f"{stale_after_s}: a node cannot be dead before slow")
        self.dir = str(registry_dir)
        self.stale_after_s = float(stale_after_s)  # host-sync-ok: python config scalar
        self.dead_after_s = float(dead_after_s)  # host-sync-ok: python config scalar
        os.makedirs(self.dir, exist_ok=True)
        self._chaos_write = chaos_site("registry.write")

    def _path(self, node_id: str) -> str:
        return os.path.join(self.dir, f"node_{node_id}.json")

    # ---- write side (one node gossiping itself) -------------------------
    def write(self, node_id: str, url: str, *, state: str = NODE_UP,
              stats: Optional[Dict[str, Any]] = None,
              now: Optional[float] = None):
        """Atomically publish one node's record (tmp + rename, like the
        watchdog's ``_beat`` — readers never see a torn file)."""
        payload = json.dumps({
            "node_id": node_id, "url": url, "pid": os.getpid(),
            "state": state, "time": time.time() if now is None else now,
            "stats": stats or {}}).encode("utf-8")
        if self._chaos_write is not None:
            try:
                # torn_write truncates the record (readers classify it
                # dead), delay stalls the beat, error loses it entirely
                payload, _ = self._chaos_write.mangle(payload,
                                                      arg=node_id)
            except Exception:
                return      # injected write failure: this beat is lost
        try:
            fd, tmp = tempfile.mkstemp(dir=self.dir,
                                       prefix=f".node_{node_id}_")
            with os.fdopen(fd, "wb") as f:
                f.write(payload)
            os.replace(tmp, self._path(node_id))
        except OSError:
            pass            # a full/slow disk must not kill the beat

    def deregister(self, node_id: str):
        try:
            os.remove(self._path(node_id))
        except OSError:
            pass

    # ---- read side (dispatchers, autoscaler, benchmarks) ----------------
    def read_all(self) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in sorted(names):
            if not name.startswith("node_") or not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.dir, name)) as f:
                    rec = json.load(f)
                out[str(rec["node_id"])] = rec
            except (OSError, ValueError, KeyError):
                # torn/garbage record (interrupted writer, bit rot):
                # surface it as a DEAD placeholder keyed by filename —
                # never raise, never silently hide a node whose record
                # exists. ``time: None`` makes snapshot() classify it
                # dead; the next healthy beat overwrites it whole.
                nid = name[len("node_"):-len(".json")]
                if nid:
                    out.setdefault(nid, {
                        "node_id": nid, "url": "", "pid": None,
                        "state": NODE_UP, "time": None, "stats": {},
                        "corrupt": True})
        return out

    def snapshot(self, now: Optional[float] = None
                 ) -> Dict[str, Dict[str, Any]]:
        """Every record + its heartbeat ``age`` and ``health``
        (``alive``/``slow``/``dead`` via the shared boundary)."""
        now = time.time() if now is None else now
        snap = {}
        for node_id, rec in self.read_all().items():
            try:
                age = now - float(rec.get("time", 0.0))  # host-sync-ok: heartbeat file timestamp
            except (TypeError, ValueError):
                age = None
            rec = dict(rec)
            rec["age_s"] = age
            rec["health"] = classify_heartbeat_age(
                age, self.dead_after_s, self.stale_after_s)
            snap[node_id] = rec
        return snap

    def dispatchable(self, now: Optional[float] = None
                     ) -> List[Dict[str, Any]]:
        """Nodes a dispatcher may route to: state ``up`` (draining nodes
        answer in-flight only) and not dead — alive first, slow after
        (a slow node is a last resort, not an equal peer)."""
        rank = {"alive": 0, "slow": 1}
        nodes = [r for r in self.snapshot(now).values()
                 if r["state"] == NODE_UP and r["health"] in rank]
        nodes.sort(key=lambda r: (rank[r["health"]], r["node_id"]))
        return nodes


class ServingNode:
    """One worker node: FleetRouter + ServingEngine behind the UI HTTP
    surface, heartbeating into a :class:`NodeRegistry`.

    ``artifact_store``/``model_key`` point the engine's AOT cache at
    the shared bucket layout (parallel/aot_cache.ArtifactStore): the
    first node of a model key pays the warmup sweep and saves; every
    later joiner deserializes the saved executables and reaches
    ``assert_warm()`` with zero live compiles.
    """

    def __init__(self, model, *, node_id: str, registry: NodeRegistry,
                 model_name: str = "default", version: str = "v1",
                 slo_ms: Optional[float] = None,
                 artifact_store=None, model_key: Optional[str] = None,
                 pool_size: int = 1, ui_port: int = 0,
                 heartbeat_interval_s: float = 0.5,
                 metrics_registry=None, window_s: Optional[float] = None,
                 **engine_kwargs):
        from deeplearning4j_tpu.observe.registry import default_registry
        from deeplearning4j_tpu.parallel.fleet import FleetRouter
        from deeplearning4j_tpu.ui.server import UIServer
        from deeplearning4j_tpu.ui.serving_module import (
            FleetModule, ServingModule)
        from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

        self.node_id = str(node_id)
        self.registry = registry
        self.model_name = model_name
        self.metrics = metrics_registry if metrics_registry is not None \
            else default_registry()
        self.heartbeat_interval_s = float(heartbeat_interval_s)  # host-sync-ok: python config scalar
        if artifact_store is not None:
            key = model_key or model_name
            engine_kwargs["aot_cache_dir"] = artifact_store.cache_dir(key)
        self.router = FleetRouter(
            slo_ms=slo_ms, registry=self.metrics, window_s=window_s,
            session_id=f"node-{self.node_id}")
        self.router.add_pool(model_name, model, version=version,
                             pool_size=pool_size, **engine_kwargs)
        self.server = UIServer(port=ui_port, registry=self.metrics)
        self.server.attach(InMemoryStatsStorage())
        # FleetModule first: its admission-controlled /api/predict wins
        self.server.register_module(FleetModule(self.router))
        self.server.register_module(
            ServingModule(self.router.pool(model_name).engines[0]))
        self.server.start()

        self._g_drain = self.metrics.gauge(
            "dl4j_cluster_drain_seconds",
            "wall seconds the last graceful drain took on this node")
        self._lock = threading.Lock()
        self._state = NODE_UP
        self._stopped = False
        self._stop_beat = threading.Event()
        self._beat_now()            # visible before the thread spins up
        self._beat_thread = threading.Thread(
            target=self._beat_loop, name=f"dl4j-node-{self.node_id}",
            daemon=True)
        self._beat_thread.start()

    # ---- gossip ---------------------------------------------------------
    @property
    def url(self) -> str:
        return self.server.url

    def node_stats(self) -> Dict[str, Any]:
        """The gossiped load snapshot (the dispatcher's least-loaded
        tie-break and the autoscaler's sensor)."""
        pool = self.router.pool(self.model_name)
        with pool.lock:
            pending = pool.pending
            p99 = pool.windowed_p99_ms
            engines = list(pool.engines)
        inflight = sum(e.inflight for e in engines)
        queue_depth = sum(e.stats().get("queue_depth", 0)
                          for e in engines)
        return {"pending": pending, "inflight": inflight,
                "queue_depth": queue_depth, "windowed_p99_ms": p99,
                "requests": pool.ring.count}

    def _beat_now(self):
        with self._lock:
            state = self._state
        try:
            stats = self.node_stats()
        except Exception:
            stats = {}
        self.registry.write(self.node_id, self.url, state=state,
                            stats=stats)

    def _beat_loop(self):
        while not self._stop_beat.wait(self.heartbeat_interval_s):
            self._beat_now()

    # ---- convenience ----------------------------------------------------
    def output(self, features):
        return self.router.output(features, model=self.model_name)

    def assert_warm(self):
        self.router.assert_warm()

    def stats(self) -> Dict[str, Any]:
        return {"node_id": self.node_id, "url": self.url,
                "state": self._state, **self.router.stats()}

    # ---- lifecycle ------------------------------------------------------
    def _inflight_total(self) -> int:
        pool = self.router.pool(self.model_name)
        with pool.lock:
            pending = pool.pending
        # HTTP handler threads may still be serializing a finished
        # answer after the pool drains — count them too, so "drained"
        # means the response bytes are on the wire
        return pending + self.server.active_requests

    def drain(self, timeout_s: float = 30.0) -> Dict[str, Any]:
        """Graceful exit: gossip ``draining`` (dispatchers stop routing
        here), refuse NEW predicts with 503 + ``Retry-After``, wait for
        every accepted request to finish (admitted work is never shed),
        deregister, then stop the server and engines. Returns
        ``{"drained": bool, "seconds": float, "inflight_left": int}``.
        """
        t0 = time.monotonic()
        with self._lock:
            already = self._stopped
            self._state = NODE_DRAINING
        if already:
            return {"drained": True, "seconds": 0.0, "inflight_left": 0}
        self._beat_now()                    # gossip "draining" at once
        self.server.drain()                 # 503 + Retry-After on new work
        deadline = t0 + float(timeout_s)  # host-sync-ok: python config scalar
        left = self._inflight_total()
        while left > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
            left = self._inflight_total()
        seconds = time.monotonic() - t0
        self._g_drain.set(seconds, node=self.node_id)
        # deregister BEFORE the server dies: peers must see an orderly
        # departure, never a record that just goes stale
        self._stop_beat.set()
        self._beat_thread.join(timeout=5 * self.heartbeat_interval_s + 1)
        self.registry.deregister(self.node_id)
        with self._lock:
            self._stopped = True
        self.server.stop()
        self.router.shutdown()
        return {"drained": left == 0, "seconds": seconds,
                "inflight_left": left}

    def shutdown(self):
        """Fast stop (no waiting): deregister + tear down. ``drain()``
        is the graceful path; this is for tests and error exits."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
        self._stop_beat.set()
        self._beat_thread.join(timeout=5 * self.heartbeat_interval_s + 1)
        self.registry.deregister(self.node_id)
        self.server.stop()
        self.router.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()


def install_sigterm_drain(node: ServingNode,
                          timeout_s: float = 30.0) -> None:
    """SIGTERM -> graceful drain -> exit 0. The handler runs the full
    drain (finish in-flight, deregister) then ``os._exit(0)`` — the
    orchestrator's TERM..KILL grace window is exactly what
    ``timeout_s`` should be set to."""
    def _handler(signum, frame):
        result = node.drain(timeout_s)
        print(f"[node {node.node_id}] SIGTERM drain: "
              f"{result['seconds']:.2f}s, "
              f"inflight_left={result['inflight_left']}", flush=True)
        sys.stdout.flush()
        os._exit(0 if result["drained"] else 1)
    signal.signal(signal.SIGTERM, _handler)


class AutoScaler:
    """Replica-count control loop over a :class:`NodeRegistry`.

    The sensor is the AIMD shed controller's own signals, gossiped:
    any node's windowed p99 over the SLO, or total queued work past
    ``queue_high`` per live node, means the fleet is tight; sustained
    for ``hold_s`` it spawns one node (additive increase — one at a
    time, like the shed step). No traffic at all for ``idle_after_s``
    retires one node, down to ``min_nodes`` — with ``min_nodes=0`` the
    fleet scales to zero and the dispatcher's ``on_no_nodes`` demand
    signal (:meth:`note_demand`) restarts the first node, cold start
    bounded by the shared-artifact warm-up.

    ``spawn()`` / ``stop(node_id)`` are injected (subprocess launcher
    in production, fakes in tests); ``clock`` is injectable so tests
    never sleep.
    """

    def __init__(self, registry: NodeRegistry, *,
                 spawn: Callable[[], Any],
                 stop: Callable[[str], Any],
                 slo_ms: Optional[float] = None,
                 min_nodes: int = 0, max_nodes: int = 4,
                 queue_high: int = 8, hold_s: float = 1.0,
                 idle_after_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        self.registry = registry
        self.spawn = spawn
        self.stop = stop
        self.slo_ms = slo_ms
        self.min_nodes = int(min_nodes)
        self.max_nodes = int(max_nodes)
        self.queue_high = int(queue_high)
        self.hold_s = float(hold_s)  # host-sync-ok: python config scalar
        self.idle_after_s = float(idle_after_s)  # host-sync-ok: python config scalar
        self.clock = clock
        self._lock = threading.Lock()
        self._over_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_requests: Optional[int] = None
        self._demand = False
        self.scale_ups = 0
        self.scale_downs = 0

    def note_demand(self):
        """Demand signal from the dispatch tier (``on_no_nodes``): a
        request arrived with nothing to route to — the scale-from-zero
        trigger."""
        with self._lock:
            self._demand = True

    def tick(self) -> Optional[str]:
        """One control step; returns ``"up"``/``"down"``/None for what
        it did. Call it on a timer (or from tests with a fake clock)."""
        now = self.clock()
        snap = self.registry.snapshot()
        live = [r for r in snap.values()
                if r["state"] == NODE_UP and r["health"] != "dead"]
        with self._lock:
            demand, self._demand = self._demand, False

        # ---- pressure sensor (the AIMD controller's own signals) -----
        p99s = [r["stats"].get("windowed_p99_ms") for r in live]
        p99s = [p for p in p99s if p is not None]
        queued = sum(int(r["stats"].get("pending") or 0)
                     + int(r["stats"].get("queue_depth") or 0)
                     for r in live)
        over = (demand and not live) \
            or (self.slo_ms is not None and p99s
                and max(p99s) > self.slo_ms) \
            or (live and queued > self.queue_high * len(live))
        if over:
            if self._over_since is None:
                self._over_since = now
            held = now - self._over_since
            # scale-from-zero is immediate: there is nothing to measure
            # a hold against, and every waiting request is an error
            if (not live or held >= self.hold_s) \
                    and len(live) < self.max_nodes:
                self._over_since = None
                self.scale_ups += 1
                self.spawn()
                return "up"
            return None
        self._over_since = None

        # ---- idleness sensor -----------------------------------------
        total_requests = sum(int(r["stats"].get("requests") or 0)
                             for r in live)
        if self._last_requests is None \
                or total_requests != self._last_requests:
            self._last_requests = total_requests
            self._idle_since = now
            return None
        if self._idle_since is not None \
                and now - self._idle_since >= self.idle_after_s \
                and len(live) > self.min_nodes:
            self._idle_since = now
            victim = max(live, key=lambda r: r["node_id"])
            self.scale_downs += 1
            self.stop(victim["node_id"])
            return "down"
        return None
