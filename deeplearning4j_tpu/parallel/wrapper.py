"""ParallelWrapper — single-process multi-chip data-parallel training.

Analog of the reference's ``ParallelWrapper``
(deeplearning4j-scaleout-parallelwrapper/.../ParallelWrapper.java:58 —
TrainingMode AVERAGING / SHARED_GRADIENTS at :59, fit loop :217-310,
averaging via native ``Nd4j.averageAndPropagate`` :326) redesigned as SPMD:

- **SHARED_GRADIENTS** (default, the reference's EncodedGradientsAccumulator
  path): synchronous data parallelism. The global batch is sharded over the
  ``data`` mesh axis, parameters are replicated, and XLA inserts the
  gradient all-reduce over ICI during the backward pass. No threads, no
  queues, no 1-bit compression — the ICI allreduce IS the accumulator.
- **AVERAGING** (the reference's parameter-averaging mode): local-SGD.
  Each device runs ``averaging_frequency`` optimizer steps on its own batch
  shard with locally-diverged parameters inside a ``shard_map`` +
  ``lax.scan``, then parameters AND updater state are averaged with
  ``lax.pmean`` — exactly the reference's averaging semantics including
  updater-state averaging (ParallelWrapper.averageUpdatersState:338).

Both modes wrap an existing MultiLayerNetwork/ComputationGraph without
changing it: the wrapper builds its own jitted/shard_mapped step around the
model's pure loss function.
"""

from __future__ import annotations

import enum
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.datasets.dataset import DataSet, DataSetIterator
from deeplearning4j_tpu.observe.telemetry import has_buffer
from deeplearning4j_tpu.optimize.solver import TrainState
from deeplearning4j_tpu.parallel.mesh import (DATA_AXIS, compat_shard_map,
                                              create_mesh)


class TrainingMode(enum.Enum):
    SHARED_GRADIENTS = "shared_gradients"   # sync allreduce DP
    AVERAGING = "averaging"                 # local SGD + periodic averaging
    ASYNC_ELASTIC = "async_elastic"         # bounded-staleness PS rounds
    CUSTOM = "custom"


def _default_divergence_threshold() -> float:
    # mirrors observe/health.py: past this relative spread of per-replica
    # grad norms the replicas are considered diverging
    try:
        return float(os.environ.get("DL4J_DIVERGENCE_THRESHOLD", "2.0"))  # host-sync-ok: env knob read once at options construction
    except ValueError:
        return 2.0


@dataclass
class ElasticOptions:
    """Knobs for :attr:`TrainingMode.ASYNC_ELASTIC` — the
    parameter-server analog of the reference's Aeron-backed
    SharedTrainingMaster, recast as bounded-staleness rounds.

    Each round every worker runs ``averaging_frequency`` local steps
    from its last adopted server snapshot. Workers that report within
    ``round_deadline_ms`` are *members* of the round: their parameter
    deltas are merged into the server params, staleness-weighted by
    ``staleness_decay ** (age - 1)`` where ``age`` counts the rounds
    since the worker last adopted the server state. A contribution
    older than ``staleness_bound`` rounds is discarded outright (merged
    with weight 0 — the delta is against a hopelessly old base).
    Members adopt the merged server state and reset their age; dropped
    stragglers keep training on their divergent local params and age by
    one.

    The ``dl4j_replica_divergence`` gauge (relative spread of
    per-worker grad norms) guards the whole scheme: past
    ``divergence_threshold`` the next round is forced into a **hard
    sync** — every worker contributes with weight 1 and every worker
    adopts, collapsing the round to plain AVERAGING semantics.

    ``straggler_policy`` exists for tests/benchmarks: a deterministic
    ``(round_index, n_workers) -> per-worker delay in ms`` function
    simulating slow workers. It MUST be deterministic in its arguments
    — in multi-process runs every host evaluates it independently and
    they must agree on the round's membership. None means nobody lags.
    """
    round_deadline_ms: float = 250.0
    staleness_bound: int = 3
    staleness_decay: float = 0.5
    divergence_threshold: float = field(
        default_factory=_default_divergence_threshold)
    straggler_policy: Optional[
        Callable[[int, int], Sequence[float]]] = None


class ParallelWrapper:
    """Builder-style API mirroring the reference:

        wrapper = (ParallelWrapper.builder(model)
                   .training_mode(TrainingMode.SHARED_GRADIENTS)
                   .workers(8)
                   .averaging_frequency(5)
                   .build())
        wrapper.fit(iterator, epochs)
    """

    def __init__(self, model, mesh: Optional[Mesh] = None,
                 mode: TrainingMode = TrainingMode.SHARED_GRADIENTS,
                 averaging_frequency: int = 5,
                 average_updaters: bool = True,
                 tensor_parallel: bool = False,
                 elastic_options: Optional[ElasticOptions] = None,
                 watchdog=None):
        self.model = model
        self.mesh = mesh if mesh is not None else create_mesh()
        self.mode = mode
        self.averaging_frequency = averaging_frequency
        self.average_updaters = average_updaters
        self.tensor_parallel = tensor_parallel
        self.elastic_options = (elastic_options if elastic_options
                                is not None else ElasticOptions())
        self._watchdog = watchdog
        if tensor_parallel and mode is not TrainingMode.SHARED_GRADIENTS:
            # AVERAGING runs per-device replicas inside shard_map — params
            # cannot simultaneously be model-axis sharded; silently
            # ignoring the flag would fake TP at the user
            raise ValueError(
                f"tensor_parallel requires SHARED_GRADIENTS mode, not"
                f" {mode.name}")
        self._step = None
        self._elastic = None        # ASYNC_ELASTIC per-worker state
        if model.train_state is None:
            model.init()

    # ---- builder --------------------------------------------------------
    class Builder:
        def __init__(self, model):
            self._model = model
            self._mesh = None
            self._mode = TrainingMode.SHARED_GRADIENTS
            self._avg_freq = 5
            self._avg_updaters = True
            self._tp = False
            self._elastic_opts = None
            self._wd = None

        def workers(self, n: int):
            devs = jax.devices()
            if n > len(devs):
                raise ValueError(f"requested {n} workers but only"
                                 f" {len(devs)} devices present")
            self._mesh = create_mesh({DATA_AXIS: n}, devs[:n])
            return self

        def mesh(self, mesh: Mesh):
            self._mesh = mesh
            return self

        def training_mode(self, mode: TrainingMode):
            self._mode = mode
            return self

        def averaging_frequency(self, k: int):
            self._avg_freq = k
            return self

        def average_updaters(self, flag: bool):
            self._avg_updaters = flag
            return self

        def tensor_parallel(self, flag: bool = True):
            """Shard parameters over the mesh's ``model`` axis with the
            Megatron row/column pairing (parallel/tensor_parallel.py).
            Requires a mesh with a ``model`` axis (e.g.
            ``create_mesh({"data": 2, "model": 4})``)."""
            self._tp = flag
            return self

        def elastic_options(self, opts: "ElasticOptions"):
            """Bounded-staleness knobs for ASYNC_ELASTIC mode."""
            self._elastic_opts = opts
            return self

        def watchdog(self, wd):
            """Attach a CollectiveWatchdog (parallel/cluster.py): the
            wrapper marks every blocking collective wait in-flight via
            ``wd.guard()`` and routes collective exceptions through
            ``wd.on_collective_error`` so a dead peer produces an
            emergency checkpoint + ``peer_loss`` forensics instead of a
            hang or an unclassified crash."""
            self._wd = wd
            return self

        def build(self) -> "ParallelWrapper":
            return ParallelWrapper(self._model, self._mesh, self._mode,
                                   self._avg_freq, self._avg_updaters,
                                   tensor_parallel=self._tp,
                                   elastic_options=self._elastic_opts,
                                   watchdog=self._wd)

    @staticmethod
    def builder(model) -> "ParallelWrapper.Builder":
        return ParallelWrapper.Builder(model)

    # ---- internals ------------------------------------------------------
    @property
    def num_workers(self) -> int:
        return int(self.mesh.shape[DATA_AXIS])

    def _loss_adapter(self):
        """model-specific pure loss closure (masks threaded through)."""
        from deeplearning4j_tpu.models.multi_layer_network import (
            MultiLayerNetwork)
        m = self.model
        if isinstance(m, MultiLayerNetwork):
            def loss_fn(params, mstate, feats, labels, fmask, lmask, rng, it):
                return m._loss(params, mstate, feats, labels, fmask, lmask,
                               rng, it)
        else:
            def loss_fn(params, mstate, feats, labels, fmask, lmask, rng, it):
                return m._loss(params, mstate, (feats,), (labels,),
                               None if fmask is None else (fmask,),
                               None if lmask is None else (lmask,), rng, it)
        return loss_fn

    def _build_sync_step(self):
        """SHARED_GRADIENTS: jit with sharded batch + replicated (or, with
        ``tensor_parallel``, Megatron row/column-sharded) params. XLA emits
        the gradient psum over ICI in backward — the TPU-native
        EncodingHandler.broadcastUpdates."""
        loss_fn = self._loss_adapter()
        tx = self.model._tx
        mesh = self.mesh
        batch_sh = NamedSharding(mesh, P(DATA_AXIS))
        spec = self.model._telemetry_spec()
        self._built_spec = spec
        # grads here are globally reduced before any code sees them, so
        # the per-device observable is whether the REPLICAS still agree:
        # an L2 param fingerprint per device, gathered over the data axis
        # (desync / silent-data-corruption detector). TP params are
        # model-sharded — per-device norms would differ by construction.
        probe_replicas = (spec is not None and spec.replicas > 1
                          and not self.tensor_parallel)

        def _param_fingerprint(params):
            def l2(p):
                leaves = jax.tree_util.tree_leaves(p)
                sumsq = sum((jnp.sum(jnp.square(l.astype(jnp.float32)))
                             for l in leaves),
                            jnp.zeros((), jnp.float32))
                return jnp.sqrt(sumsq).reshape(1, 1)
            return compat_shard_map(
                l2, mesh=mesh, in_specs=(P(),),
                out_specs=P(DATA_AXIS), check_vma=False)(params)

        ts_sh = None
        if self.tensor_parallel:
            from deeplearning4j_tpu.parallel.mesh import MODEL_AXIS
            from deeplearning4j_tpu.parallel.tensor_parallel import (
                plan_tp, shard_train_state)
            if MODEL_AXIS not in mesh.shape:
                raise ValueError(
                    "tensor_parallel needs a mesh with a 'model' axis; got "
                    f"{dict(mesh.shape)}")
            plan = plan_tp(self.model, mesh)
            _, ts_sh = shard_train_state(self.model, plan)
            self.model._tp_plan = plan

        def step(ts: TrainState, feats, labels, fmask, lmask, rng):
            def lf(params):
                return loss_fn(params, ts.model_state, feats, labels, fmask,
                               lmask, rng, ts.iteration)
            (loss, new_ms), grads = jax.value_and_grad(lf, has_aux=True)(
                ts.params)
            updates, new_opt = tx.update(grads, ts.opt_state, ts.params)
            new_params = optax.apply_updates(ts.params, updates)
            buf = ts.telemetry
            if spec is not None and has_buffer(buf):
                # loss/grads are global here — the base row records the
                # same quantities as the single-device step
                buf = spec.record(buf, loss=loss, grads=grads,
                                  params=new_params,
                                  prev_params=ts.params,
                                  iteration=ts.iteration)
                if probe_replicas:
                    buf = spec.record_replica(
                        buf, values=_param_fingerprint(new_params),
                        iteration=ts.iteration)
            return TrainState(new_params, new_ms, new_opt,
                              ts.iteration + 1, buf), loss

        return jax.jit(
            step,
            in_shardings=(ts_sh, batch_sh, batch_sh, batch_sh, batch_sh,
                          None),
            out_shardings=(ts_sh, None),
            donate_argnums=(0,),
        ), batch_sh

    def _build_averaging_step(self):
        """AVERAGING: shard_map over the data axis; each worker runs
        ``averaging_frequency`` local steps (lax.scan over per-step batch
        slices), then params (+ updater state) are pmean'd — the
        Nd4j.averageAndPropagate analog (ParallelWrapper.java:326,338)."""
        loss_fn = self._loss_adapter()
        tx = self.model._tx
        mesh = self.mesh
        k = self.averaging_frequency
        avg_upd = self.average_updaters
        spec = self.model._telemetry_spec()
        self._built_spec = spec
        record_replicas = spec is not None and spec.replicas > 1

        def worker_steps(ts: TrainState, feats, labels, fmask, lmask, rng):
            # feats: (k, local_batch, ...) — k local steps for this worker
            widx = jax.lax.axis_index(DATA_AXIS)
            rng = jax.random.fold_in(rng, widx)

            def one(carry, xs):
                ts = carry
                f, l, fm, lm, i = xs
                key = jax.random.fold_in(rng, i)

                def lf(params):
                    return loss_fn(params, ts.model_state, f, l, fm, lm, key,
                                   ts.iteration)
                (loss, new_ms), grads = jax.value_and_grad(
                    lf, has_aux=True)(ts.params)
                updates, new_opt = tx.update(grads, ts.opt_state, ts.params)
                new_params = optax.apply_updates(ts.params, updates)
                # local grad-norm rides the scan ys: this worker's
                # gradients never leave the device otherwise, so this is
                # the ONLY place a genuine per-replica norm exists
                gnorm = jnp.sqrt(sum(
                    (jnp.sum(jnp.square(g.astype(jnp.float32)))
                     for g in jax.tree_util.tree_leaves(grads)),
                    jnp.zeros((), jnp.float32)))
                return (TrainState(new_params, new_ms, new_opt,
                                   ts.iteration + 1, ts.telemetry),
                        (loss, gnorm))

            ts, (losses, gnorms) = jax.lax.scan(
                one, ts, (feats, labels, fmask, lmask, jnp.arange(k)))
            buf = ts.telemetry
            if record_replicas and has_buffer(buf):
                # per-worker means over the k local steps, gathered so
                # every device writes the identical [n_workers, 2] row —
                # the replicated layout the buffer lives in
                wl = jax.lax.all_gather(
                    jnp.mean(losses.astype(jnp.float32)), DATA_AXIS)
                wg = jax.lax.all_gather(jnp.mean(gnorms), DATA_AXIS)
                buf = spec.record_replica(
                    buf, values=jnp.stack([wl, wg], axis=-1),
                    iteration=ts.iteration - 1)
            # --- parameter averaging across the data axis (ICI psum) ---
            # integer leaves (Adam/updater step counts) are identical on
            # every replica and pmean would promote them to float,
            # corrupting the next round's tx.update — keep them verbatim
            avg = lambda t: (t if jnp.issubdtype(t.dtype, jnp.integer)
                             else jax.lax.pmean(t, DATA_AXIS))
            new_params = jax.tree_util.tree_map(avg, ts.params)
            new_ms = jax.tree_util.tree_map(avg, ts.model_state)
            new_opt = (jax.tree_util.tree_map(avg, ts.opt_state)
                       if avg_upd else ts.opt_state)
            return (TrainState(new_params, new_ms, new_opt, ts.iteration,
                               buf),
                    jax.lax.pmean(jnp.mean(losses), DATA_AXIS))

        # Everything replicated except the batch: (k, B, ...) sharded on B.
        pspec_batch = P(None, DATA_AXIS)
        wrapped = compat_shard_map(
            worker_steps, mesh=mesh,
            in_specs=(P(), pspec_batch, pspec_batch, pspec_batch,
                      pspec_batch, P()),
            out_specs=(P(), P()),
            check_vma=False)
        return jax.jit(wrapped, donate_argnums=(0,)), None

    def _build_async_step(self):
        """ASYNC_ELASTIC: bounded-staleness parameter-server rounds.

        Server params live replicated in ``model.train_state``; each
        worker additionally carries LOCAL params/updater-state plus the
        server snapshot it last adopted (``base``), all stacked with a
        leading worker dim sharded over the data axis. One round =
        ``averaging_frequency`` local steps per worker (same scan as
        AVERAGING), then a presence/staleness-weighted delta merge:

            theta' = theta + sum_i(w_i * (local_i - base_i)) / sum_i(w_i)
            w_i    = present_i * decay^(age_i)      (0 past the bound)

        Members (present_i=1) adopt theta' and reset base; dropped
        stragglers keep drifting on their local params. A hard-sync
        round (``hard=1``) ignores staleness entirely: every worker
        contributes with weight 1 and adopts — exactly an AVERAGING
        round. With no stragglers every round IS a hard round
        semantically (all ages 0, all weights 1), which is what makes
        straggler-free ASYNC_ELASTIC converge like AVERAGING.

        Presence/ages/hard are computed on the host (deterministic
        straggler policy — see ElasticOptions) and fed as tiny arrays;
        everything heavy stays on device.
        """
        loss_fn = self._loss_adapter()
        tx = self.model._tx
        mesh = self.mesh
        k = self.averaging_frequency
        avg_upd = self.average_updaters
        opts = self.elastic_options
        bound = float(opts.staleness_bound)  # host-sync-ok: trace-time config
        decay = float(opts.staleness_decay)  # host-sync-ok: trace-time config
        spec = self.model._telemetry_spec()
        self._built_spec = spec
        record_replicas = spec is not None and spec.replicas > 1

        def unstack(t):
            # inside shard_map each worker owns leading-dim slice [1, ...]
            return jax.tree_util.tree_map(lambda a: a[0], t)

        def restack(t):
            return jax.tree_util.tree_map(lambda a: a[None], t)

        def round_fn(ts: TrainState, local_p, local_o, base_p,
                     feats, labels, fmask, lmask, rng,
                     present, ages, hard):
            widx = jax.lax.axis_index(DATA_AXIS)
            lp, lo, bp = unstack(local_p), unstack(local_o), unstack(base_p)
            rng_w = jax.random.fold_in(rng, widx)

            def one(carry, xs):
                lp, lo, ms = carry
                f, l, fm, lm, i = xs
                key = jax.random.fold_in(rng_w, i)

                def lf(params):
                    return loss_fn(params, ms, f, l, fm, lm, key,
                                   ts.iteration + i)
                (loss, new_ms), grads = jax.value_and_grad(
                    lf, has_aux=True)(lp)
                updates, new_lo = tx.update(grads, lo, lp)
                new_lp = optax.apply_updates(lp, updates)
                gnorm = jnp.sqrt(sum(
                    (jnp.sum(jnp.square(g.astype(jnp.float32)))
                     for g in jax.tree_util.tree_leaves(grads)),
                    jnp.zeros((), jnp.float32)))
                return (new_lp, new_lo, new_ms), (loss, gnorm)

            (lp, lo, ms), (losses, gnorms) = jax.lax.scan(
                one, (lp, lo, ts.model_state),
                (feats, labels, fmask, lmask, jnp.arange(k)))

            # ---- per-worker stats, gathered replicated ----------------
            wl = jax.lax.all_gather(
                jnp.mean(losses.astype(jnp.float32)), DATA_AXIS)
            wg = jax.lax.all_gather(jnp.mean(gnorms), DATA_AXIS)
            stats = jnp.stack([wl, wg], axis=-1)        # (n, 2)
            buf = ts.telemetry
            if record_replicas and has_buffer(buf):
                buf = spec.record_replica(buf, values=stats,
                                          iteration=ts.iteration + k - 1)

            # ---- staleness-weighted delta merge -----------------------
            pres = present[widx]
            age1 = ages[widx] + 1.0     # rounds of drift incl. this one
            w_soft = pres * jnp.where(age1 <= bound,
                                      decay ** (age1 - 1.0), 0.0)
            w = jnp.where(hard > 0, 1.0, w_soft)
            den = jax.lax.psum(w, DATA_AXIS)
            safe_den = jnp.maximum(den, 1e-12)

            def merge_params(srv, l, b):
                num = jax.lax.psum(w * (l - b), DATA_AXIS)
                return jnp.where(den > 0, srv + num / safe_den, srv)
            new_theta = jax.tree_util.tree_map(
                merge_params, ts.params, lp, bp)

            # model/opt state: adoption-weighted mean over members
            # (integer leaves — updater step counts — keep the server's
            # copy verbatim: a pmean would float-promote them)
            a = jnp.where(hard > 0, 1.0, pres)
            da = jax.lax.psum(a, DATA_AXIS)
            safe_da = jnp.maximum(da, 1e-12)

            def merge_state(srv, l):
                if jnp.issubdtype(srv.dtype, jnp.integer):
                    return srv
                num = jax.lax.psum(a * l, DATA_AXIS)
                return jnp.where(da > 0, num / safe_da, srv)
            new_ms = jax.tree_util.tree_map(merge_state, ts.model_state,
                                            ms)
            new_opt = (jax.tree_util.tree_map(merge_state, ts.opt_state,
                                              lo)
                       if avg_upd else ts.opt_state)

            # ---- worker adoption --------------------------------------
            adopt = jnp.where(hard > 0, 1.0, pres)

            def take(new, old):
                if jnp.issubdtype(old.dtype, jnp.integer):
                    return old          # counts advance locally
                return jnp.where(adopt > 0, new, old)
            lp2 = jax.tree_util.tree_map(take, new_theta, lp)
            bp2 = jax.tree_util.tree_map(take, new_theta, bp)
            lo2 = (jax.tree_util.tree_map(take, new_opt, lo)
                   if avg_upd else lo)

            new_ts = TrainState(new_theta, new_ms, new_opt,
                                ts.iteration + k, buf)
            loss_out = jax.lax.pmean(jnp.mean(losses), DATA_AXIS)
            return (new_ts, restack(lp2), restack(lo2), restack(bp2),
                    stats, loss_out)

        pspec_batch = P(None, DATA_AXIS)
        stacked = P(DATA_AXIS)          # leading worker dim
        wrapped = compat_shard_map(
            round_fn, mesh=mesh,
            in_specs=(P(), stacked, stacked, stacked,
                      pspec_batch, pspec_batch, pspec_batch, pspec_batch,
                      P(), P(), P(), P()),
            out_specs=(P(), stacked, stacked, stacked, P(), P()),
            check_vma=False)
        return jax.jit(wrapped, donate_argnums=(0, 1, 2, 3)), None

    # ---- fit ------------------------------------------------------------
    def fit(self, iterator: DataSetIterator, epochs: int = 1):
        """Train over the iterator.

        Multi-process contract: EVERY batch each host yields (not just
        the first) must be proportional to that host's share of the mesh
        devices — same rows-per-device everywhere. Hosts pad their tail
        batches independently (``_pad_batch`` pads to the local worker
        multiple), so an uneven final split that violates this builds
        inconsistent global shapes and hangs the first collective rather
        than raising; the cross-host equality check runs only once (see
        ``_global_batch_size`` for why repeating it would itself
        deadlock). A collective-free local monitor warns when a
        *non-final* batch's per-device count drifts from the checked
        value — the final batch legitimately may."""
        self._pending_uneven_per = None     # fresh fit: prior tail is fine
        if self.mode not in (TrainingMode.SHARED_GRADIENTS,
                             TrainingMode.AVERAGING,
                             TrainingMode.ASYNC_ELASTIC):
            raise ValueError(f"unsupported mode: {self.mode}")
        m = self.model
        # re-adopt the device iteration once per fit (BaseModel.fit does
        # the same); listener dispatch then advances a host mirror
        m._host_iteration = None
        self._arm_telemetry()
        try:
            if self.mode is TrainingMode.SHARED_GRADIENTS:
                return self._fit_sync(iterator, epochs)
            if self.mode is TrainingMode.ASYNC_ELASTIC:
                return self._fit_async(iterator, epochs)
            return self._fit_averaging(iterator, epochs)
        except Exception as e:
            # a collective that RAISES on peer death (fail-fast
            # transports like gloo) goes through the watchdog's
            # classifier first: peer loss gets the emergency checkpoint
            # + peer_loss dump + resumable marker instead of a generic
            # crash dump
            wd = self._watchdog
            if wd is not None and wd.on_collective_error(e):
                raise
            # same crash-forensics contract as BaseModel.fit: dump, then
            # let the exception surface
            rec = m._recorder()
            if rec is not None:
                rec.record_crash(m, exc=e)
            raise

    def _arm_telemetry(self):
        """Extend an attached TelemetryCollector with the per-device row
        ring: AVERAGING workers report genuine per-worker loss/grad-norm
        (local gradients exist per device there); synchronous DP reports
        an L2 param fingerprint per device, since its gradients are
        globally reduced before any code sees them. Enabling changes the
        buffer pytree, so the step is rebuilt and the buffer rebound —
        once, before the next dispatch. Also rebuilds the step when a
        collector was attached/detached after the step was compiled."""
        m = self.model
        tel = m.telemetry
        spec = m._telemetry_spec()
        if (self._step is not None
                and getattr(self, "_built_spec", None) is not spec):
            self._step = None
        if tel is None or self.num_workers <= 1 or self.tensor_parallel:
            return
        metrics = (("loss", "grad_norm")
                   if self.mode in (TrainingMode.AVERAGING,
                                    TrainingMode.ASYNC_ELASTIC)
                   else ("param_norm",))
        if tel.enable_replicas(self.num_workers, metrics):
            self._step = None
            if m.train_state is not None:
                m.train_state = tel.rebind_buffer(m.train_state)

    def _pad_batch(self, batch: DataSet, target: int | None = None) -> DataSet:
        """Pad to a multiple of num_workers (and optionally to ``target``
        examples) with zero-weight rows: padded examples carry
        labels_mask == 0, so the masked loss mean ignores them. Loss and
        gradients then match the unpadded single-device step; the one
        exception is BatchNormalization batch statistics, which see the
        duplicated rows (mask-free batch moments) — a bounded, usually
        negligible perturbation. (The reference rebalances queues across
        trainer threads instead — ParallelWrapper.java:225; static shapes
        make padding the XLA way.) Row duplication + mask synthesis live
        in datasets/feeder.pad_rows — one implementation for the fit loop
        and the wrapper."""
        from deeplearning4j_tpu.datasets.feeder import pad_rows
        n = batch.num_examples()
        w = self.num_workers
        pad = ((target - n) if target else 0) + ((-(target or n)) % w)
        return pad_rows(batch, pad)

    def _put_batch(self, a, sharding=None, batch_dim: int = 0):
        """Stage one batch tensor onto the data-sharded layout.

        Single process: device_put of the full array. Multi-process
        (real multi-host): ``a`` is THIS process's shard of the global
        batch (the standard jax data-loading contract — each host's
        iterator yields its share), assembled into the global array via
        make_array_from_process_local_data; XLA moves nothing between
        hosts. Processes may own UNEVEN device counts (round 3): each
        local batch must be proportional to this process's share of the
        mesh devices (checked once per shape — a wrong split would
        silently build inconsistent global shapes and hang the first
        collective)."""
        if a is None:
            return None
        sh = self._batch_sh if sharding is None else sharding
        if jax.process_count() == 1:
            return jax.device_put(jnp.asarray(a), sh)
        a = np.asarray(a)  # host-sync-ok: host-side batch split/pad before transfer
        total = self._global_batch_size(a.shape[batch_dim])
        gshape = list(a.shape)
        gshape[batch_dim] = total
        return jax.make_array_from_process_local_data(sh, a,
                                                      tuple(gshape))

    def _global_batch_size(self, n: int) -> int:
        """Global batch rows for a local shard of ``n`` rows: every
        device carries the same per-device batch, so the global size is
        (n / local_devices) · global_devices — valid when processes own
        UNEVEN device counts.

        The cross-process consistency check (a tiny device-sharded
        reduction) runs exactly ONCE, on the very first staged array —
        a point every process reaches together. It must NOT be repeated
        per shard size: processes can see different size sequences, and
        a check collective entered by only some of them would deadlock
        against the train-step collective of the rest."""
        loc = jax.local_device_count()
        if n % loc:
            raise ValueError(
                f"multi-host fit: this process's batch shard ({n} rows) "
                f"must divide evenly over its {loc} local devices — "
                "split each host's data by its device share.")
        per = n // loc
        if not getattr(self, "_batch_check_done", False):
            self._batch_check_done = True
            self._checked_per = per
            from deeplearning4j_tpu.parallel.mesh import (
                global_device_value_range)
            mn, mx = global_device_value_range(float(per))  # host-sync-ok: one-time per-device batch barrier
            if mn != mx:
                raise ValueError(
                    "multi-host fit needs the SAME per-device batch on "
                    f"every process; this process feeds {per} rows/"
                    f"device but the mesh sees between {int(mn)} and "
                    f"{int(mx)}. Split each host's data shard by its "
                    "device share.")
        return per * jax.device_count()

    def _monitor_uneven_batch(self, n: int):
        """Collective-free drift monitor (advisor r3), batch-level: a
        batch whose per-device count differs from the checked value is
        legal only as the FINAL batch of a fit. When ANOTHER batch
        follows an uneven one, the uneven one was mid-stream and the
        global shapes it built were inconsistent across hosts — warn
        loudly, once (we cannot raise retroactively, and a fresh
        collective check would deadlock; see ``_global_batch_size``)."""
        loc = jax.local_device_count()
        per = n // loc if n % loc == 0 else n / loc
        if (getattr(self, "_pending_uneven_per", None) is not None
                and not getattr(self, "_uneven_warned", False)):
            self._uneven_warned = True
            import warnings
            warnings.warn(
                "multi-host fit: a NON-final batch fed "
                f"{self._pending_uneven_per} rows/device where the "
                f"checked value is {getattr(self, '_checked_per', '?')} "
                "— each host must split every mid-stream batch "
                "proportionally to its device share; the preceding "
                "collective may have mixed inconsistent global shapes.",
                stacklevel=3)
        checked = getattr(self, "_checked_per", None)
        self._pending_uneven_per = per if (checked is not None
                                           and per != checked) else None

    def _sync_prepare(self, batch: DataSet) -> DataSet:
        """Host-side prep for one sync-mode batch: pad to the worker
        multiple, then run the multi-host drift monitor. Shared by the
        legacy per-batch staging and the DeviceFeeder ``prepare`` hook."""
        batch = self._pad_batch(batch)
        if jax.process_count() > 1:
            self._monitor_uneven_batch(batch.num_examples())
        return batch

    def _stage_batch(self, batch: DataSet):
        """Pad to the worker multiple and stage the four batch arrays on
        the mesh — the single home for sync-step argument staging."""
        batch = self._sync_prepare(batch)
        return (self._put_batch(batch.features),
                self._put_batch(batch.labels),
                self._put_batch(batch.features_mask),
                self._put_batch(batch.labels_mask))

    def _make_feeder(self, iterator):
        """Build the DeviceFeeder for this mode: per-replica shards are
        placed on the mesh (``_put_batch``) while the current round
        computes, and plain iterators get the AsyncDataSetIterator wrap —
        the same overlap fit() has, honoring AsyncShield. Returns
        (feeder, source); feeder is None when the iterator opted out."""
        from deeplearning4j_tpu.datasets.feeder import DeviceFeeder
        from deeplearning4j_tpu.datasets.iterators import (
            AsyncDataSetIterator)
        from deeplearning4j_tpu.observe.tracer import get_tracer
        if not getattr(iterator, "async_supported", True):
            return None, iterator
        source = iterator
        if (isinstance(iterator, DataSetIterator)
                and not isinstance(iterator, AsyncDataSetIterator)):
            source = AsyncDataSetIterator(iterator)
        tracer = get_tracer(self.model)
        if self.mode in (TrainingMode.AVERAGING,
                         TrainingMode.ASYNC_ELASTIC):
            feeder = DeviceFeeder(
                source, k_steps=self.averaging_frequency,
                pad_ragged=False,
                group_prepare=self._avg_group_prepare,
                group_remainder="pad",
                put=lambda a: self._put_batch(
                    a, sharding=self._avg_batch_sh, batch_dim=1),
                tracer=tracer, session_id="parallel")
        else:
            feeder = DeviceFeeder(source, prepare=self._sync_prepare,
                                  pad_ragged=False, put=self._put_batch,
                                  tracer=tracer, session_id="parallel")
        return feeder, source

    def collective_census(self, batch: DataSet):
        """Compile the sync step for this batch's shapes and count its
        collective HLOs (the TP communication audit — e.g. the ResNet50
        conv pairing should show ~1 all-gather + 1 all-reduce per
        bottleneck plus the gradient all-reduce over the data axis).

        Note: this AOT-compiles a separate audit executable — jax's jit
        dispatch cache is not populated by ``lower().compile()``, so a
        following ``fit`` still compiles its own step."""
        from deeplearning4j_tpu.parallel.tensor_parallel import (
            count_collectives)
        if self.mode is not TrainingMode.SHARED_GRADIENTS:
            raise ValueError("collective_census audits the sync step")
        if self._step is None:
            self._step, self._batch_sh = self._build_sync_step()
        feats, labels, fmask, lmask = self._stage_batch(batch)
        compiled = self._step.lower(self.model.train_state, feats, labels,
                                    fmask, lmask,
                                    jax.random.PRNGKey(0)).compile()
        return count_collectives(compiled)

    def _fit_sync(self, iterator, epochs):
        if self._step is None:
            self._step, self._batch_sh = self._build_sync_step()
        m = self.model
        feeder, source = self._make_feeder(iterator)
        for epoch in range(epochs):
            for lst in m.listeners:
                lst.on_epoch_start(m, m.epoch_count)
            if feeder is not None:
                for item in feeder:
                    if item.k == 0:
                        # foreign object the feeder passed through:
                        # legacy staging (raises where it always did)
                        self._fit_sync_one(item.raw, item.queue_wait_ms)
                    else:
                        self._dispatch_sync(item)
            else:
                t0 = time.perf_counter()
                for batch in iterator:
                    etl_ms = (time.perf_counter() - t0) * 1000
                    self._fit_sync_one(batch, etl_ms)
                    t0 = time.perf_counter()
            source.reset()
            # an epoch's final batch is "final" — a legal uneven tail
            # must not trip the drift monitor on the next epoch
            self._pending_uneven_per = None
            for lst in m.listeners:
                lst.on_epoch_end(m, m.epoch_count)
            m.epoch_count += 1
        self._tail_flush()
        return m

    def _fit_sync_one(self, batch, etl_ms: float):
        """Legacy (unfed) sync-mode body: stage this batch now, then
        dispatch — used when the feeder is shielded off, and for foreign
        passthrough objects."""
        m = self.model
        n_real = batch.num_examples()
        m._rng, key = jax.random.split(m._rng)
        feats, labels, fmask, lmask = self._stage_batch(batch)
        if m._telemetry is not None:
            m.train_state = m._telemetry.ensure_buffer(m.train_state)
        m.train_state, loss = self._step(m.train_state, feats, labels,
                                         fmask, lmask, key)
        self._guarded_wait(loss)
        # _post_step: host iteration mirror + telemetry flush
        # opportunity + flight-recorder poll — no per-batch
        # device sync (the old int(iteration) read was one)
        it = m._post_step()
        for lst in m.listeners:
            lst.iteration_done(m, it, m.epoch_count, loss, etl_ms, n_real)
        m._last_loss = loss

    def _dispatch_sync(self, item):
        """Fed sync-mode body: the feeder already padded and placed the
        per-replica shards; only the dispatch remains on this thread."""
        m = self.model
        m._rng, key = jax.random.split(m._rng)
        if m._telemetry is not None:
            m.train_state = m._telemetry.ensure_buffer(m.train_state)
        m.train_state, loss = self._step(
            m.train_state, item.features, item.labels, item.features_mask,
            item.labels_mask, key)
        self._guarded_wait(loss)
        it = m._post_step()
        for lst in m.listeners:
            lst.iteration_done(m, it, m.epoch_count, loss,
                               item.queue_wait_ms, item.n_examples)
        m._last_loss = loss

    def _tail_flush(self):
        """Drain rows still on device when the fit ends (mirrors
        BaseModel's tail flush), then give the recorder a final look."""
        m = self.model
        if m._telemetry is not None:
            m._telemetry.flush(m.train_state)
            rec = m._recorder()
            if rec is not None:
                rec.poll(m)

    def _fit_averaging(self, iterator, epochs):
        if self._step is None:
            self._step, _ = self._build_averaging_step()
        return self._fit_rounds(iterator, epochs,
                                self._dispatch_averaging,
                                self._run_averaging_round)

    def _fit_async(self, iterator, epochs):
        if self._step is None:
            self._step, _ = self._build_async_step()
        if self._elastic is None:
            self._init_elastic_state()
        return self._fit_rounds(iterator, epochs,
                                self._dispatch_async,
                                self._run_async_round)

    def _fit_rounds(self, iterator, epochs, dispatch, run_round):
        """Shared round loop for the k-local-steps modes (AVERAGING and
        ASYNC_ELASTIC): group k batches per round, fed or legacy."""
        # (k, B, ...) rounds shard the batch dim over data; multi-host
        # staging assembles each process's slice (see _put_batch)
        self._avg_batch_sh = NamedSharding(self.mesh,
                                           P(None, DATA_AXIS))
        m = self.model
        k = self.averaging_frequency
        feeder, source = self._make_feeder(iterator)
        for epoch in range(epochs):
            for lst in m.listeners:
                lst.on_epoch_start(m, m.epoch_count)
            if feeder is not None:
                # the feeder groups k batches per round (short tails
                # repeat the last batch — the old pending loop's
                # contract), runs _avg_group_prepare on the host thread,
                # and places the stacked (k, B, ...) round shards before
                # the previous round finishes
                for item in feeder:
                    if item.k == 0:
                        raise TypeError(
                            f"ParallelWrapper {self.mode.name} consumes "
                            "DataSet batches, got "
                            f"{type(item.raw).__name__}")
                    dispatch(item)
            else:
                pending = []
                for batch in iterator:
                    pending.append(batch)
                    if len(pending) == k:
                        run_round(pending)
                        pending = []
                if pending:
                    # pad the round reusing batches (keeps shapes static)
                    while len(pending) < k:
                        pending.append(pending[-1])
                    run_round(pending)
            source.reset()
            self._pending_uneven_per = None     # legal uneven tail round
            for lst in m.listeners:
                lst.on_epoch_end(m, m.epoch_count)
            m.epoch_count += 1
        self._tail_flush()
        return m

    def _avg_group_prepare(self, batches):
        """Host-side staging of one averaging round: equalize example
        counts with masked padding, harmonize labels masks, stack to
        (k, B, ...) host arrays. Shared by the legacy round path and the
        DeviceFeeder ``group_prepare`` hook."""
        from deeplearning4j_tpu.datasets.feeder import ones_labels_mask
        # equalize batch sizes (stacking needs it), padding w/ masked rows
        target = max(b.num_examples() for b in batches)
        batches = [self._pad_batch(b, target=target) for b in batches]
        if jax.process_count() > 1:
            # same drift contract as _stage_batch: every mid-stream
            # round's per-host rows must match the checked value
            self._monitor_uneven_batch(batches[0].num_examples())
        # padding gave short batches a labels_mask; full-size batches must
        # then get an all-ones mask, or stack() would drop every mask and
        # train on the padded rows as real examples
        if any(b.labels_mask is not None for b in batches):
            batches = [b if b.labels_mask is not None else DataSet(
                b.features, b.labels, b.features_mask, ones_labels_mask(b))
                for b in batches]

        def stack(get):
            vals = [get(b) for b in batches]
            if any(v is None for v in vals):
                return None
            return np.stack([np.asarray(v) for v in vals])  # host-sync-ok: host-side batch staging for averaging round

        return (stack(lambda b: b.features), stack(lambda b: b.labels),
                stack(lambda b: b.features_mask),
                stack(lambda b: b.labels_mask))

    def _dispatch_averaging(self, item):
        """Fed averaging-round body: arrays arrive stacked and placed;
        dispatch, then advance the host mirrors by the k local steps the
        round ran."""
        m = self.model
        m._rng, key = jax.random.split(m._rng)
        if m._telemetry is not None:
            m.train_state = m._telemetry.ensure_buffer(m.train_state)
        m.train_state, loss = self._step(
            m.train_state, item.features, item.labels, item.features_mask,
            item.labels_mask, key)
        self._guarded_wait(loss)
        it = m._post_step(item.k)
        for lst in m.listeners:
            lst.iteration_done(m, it, m.epoch_count, loss,
                               item.queue_wait_ms, item.n_examples)
        m._last_loss = loss

    def _run_averaging_round(self, batches):
        m = self.model
        m._rng, key = jax.random.split(m._rng)
        n_real = sum(b.num_examples() for b in batches)
        arrays = self._avg_group_prepare(batches)
        # multi-host: each process holds its slice of the (k, B) global
        # batch along the batch dim (dim 1)
        feats, labels, fmask, lmask = (
            None if a is None else self._put_batch(
                a, sharding=self._avg_batch_sh, batch_dim=1)
            for a in arrays)
        if m._telemetry is not None:
            m.train_state = m._telemetry.ensure_buffer(m.train_state)
        m.train_state, loss = self._step(m.train_state, feats, labels,
                                         fmask, lmask, key)
        self._guarded_wait(loss)
        # the round advanced the device iteration by k local steps
        it = m._post_step(len(batches))
        for lst in m.listeners:
            lst.iteration_done(m, it, m.epoch_count, loss, 0.0, n_real)
        m._last_loss = loss

    # ---- ASYNC_ELASTIC --------------------------------------------------
    def _init_elastic_state(self):
        """Stack n copies of the server params/updater-state with a
        leading worker dim sharded over the data axis — each worker's
        local replica plus the base snapshot it diverges from."""
        m = self.model
        n = self.num_workers
        stacked_sh = NamedSharding(self.mesh, P(DATA_AXIS))

        stack_n = jax.jit(
            lambda tree: jax.tree_util.tree_map(
                lambda a: jnp.broadcast_to(a[None], (n,) + a.shape),
                tree),
            out_shardings=stacked_sh)
        ts = m.train_state
        self._elastic = {
            "local_params": stack_n(ts.params),
            "local_opt": stack_n(ts.opt_state),
            "base_params": stack_n(ts.params),
            "ages": np.zeros(n, dtype=np.float32),
            "round": 0,
            "hard_next": False,
        }

    def _dispatch_async(self, item):
        self._async_round_core(item.features, item.labels,
                               item.features_mask, item.labels_mask,
                               item.k, item.queue_wait_ms,
                               item.n_examples)

    def _run_async_round(self, batches):
        n_real = sum(b.num_examples() for b in batches)
        arrays = self._avg_group_prepare(batches)
        feats, labels, fmask, lmask = (
            None if a is None else self._put_batch(
                a, sharding=self._avg_batch_sh, batch_dim=1)
            for a in arrays)
        self._async_round_core(feats, labels, fmask, lmask,
                               len(batches), 0.0, n_real)

    def _async_round_core(self, feats, labels, fmask, lmask,
                          k_real, wait_ms, n_real):
        """One bounded-staleness round: host computes this round's
        membership (deterministic straggler policy) and staleness ages,
        the device step does the weighted merge, then the divergence
        guard decides whether the NEXT round is a hard sync."""
        m = self.model
        el = self._elastic
        opts = self.elastic_options
        n = self.num_workers
        round_idx = el["round"]
        hard = bool(el["hard_next"])
        if opts.straggler_policy is not None and not hard:
            delays = np.asarray(  # host-sync-ok: host-side policy output, not device data
                opts.straggler_policy(round_idx, n), dtype=np.float64)
            if delays.shape != (n,):
                raise ValueError(
                    "straggler_policy must return one delay per worker "
                    f"({n}), got shape {delays.shape}")
            present = (delays <= opts.round_deadline_ms
                       ).astype(np.float32)
        else:
            present = np.ones(n, dtype=np.float32)
        ages = el["ages"]

        m._rng, key = jax.random.split(m._rng)
        if m._telemetry is not None:
            m.train_state = m._telemetry.ensure_buffer(m.train_state)
        (m.train_state, el["local_params"], el["local_opt"],
         el["base_params"], stats, loss) = self._step(
            m.train_state, el["local_params"], el["local_opt"],
            el["base_params"], feats, labels, fmask, lmask, key,
            jnp.asarray(present), jnp.asarray(ages),
            jnp.float32(1.0 if hard else 0.0))
        self._guarded_wait(loss)

        # ---- host bookkeeping: ages, counters, divergence guard -------
        age1 = ages + 1.0
        adopted = np.ones(n, dtype=bool) if hard else present > 0
        merged_stale = int(np.sum(adopted & (age1 > 1)
                                  & (age1 <= opts.staleness_bound)))
        discarded_stale = 0 if hard else int(
            np.sum(adopted & (age1 > opts.staleness_bound)))
        dropped = int(np.sum(~adopted))
        el["ages"] = np.where(adopted, 0.0, age1).astype(np.float32)
        el["round"] = round_idx + 1

        # ONE small fetch per round (k steps amortize it) — the
        # divergence guard needs the per-worker grad norms on host
        arr = np.asarray(stats)  # host-sync-ok: per-round (k steps) fetch of the (n,2) stats row for the divergence guard
        gnorms = arr[:, 1]
        finite = gnorms[np.isfinite(gnorms)]
        if finite.size < gnorms.size:
            div = float("inf")      # host-sync-ok: a non-finite worker IS divergence
        elif finite.size >= 2:
            scale = float(np.mean(np.abs(finite)))  # host-sync-ok: np math on the already-fetched stats row
            div = float((finite.max() - finite.min()) / (scale + 1e-12))  # host-sync-ok: np math on the already-fetched stats row
        else:
            div = 0.0
        el["hard_next"] = div > opts.divergence_threshold
        self._publish_elastic(n - dropped, dropped, merged_stale,
                              discarded_stale, float(el["ages"].max()),  # host-sync-ok: host np bookkeeping
                              div, hard)

        it = m._post_step(k_real)
        for lst in m.listeners:
            lst.iteration_done(m, it, m.epoch_count, loss, wait_ms,
                               n_real)
        m._last_loss = loss

    def _publish_elastic(self, members, dropped, merged_stale,
                         discarded_stale, max_age, div, was_hard):
        try:
            from deeplearning4j_tpu.observe.registry import (
                default_registry)
            r = default_registry()
        except Exception:
            return
        s = "elastic"
        r.gauge("dl4j_elastic_round_members", "workers whose delta was "
                "merged in the latest ASYNC_ELASTIC round").set(
            members, session=s)
        r.gauge("dl4j_elastic_staleness", "max rounds any worker has "
                "drifted without adopting the server params").set(
            max_age, session=s)
        if dropped:
            r.counter("dl4j_elastic_stragglers_dropped_total", "workers "
                      "dropped from a round for missing the deadline"
                      ).inc(dropped, session=s)
        if merged_stale:
            r.counter("dl4j_elastic_stale_merged_total", "late worker "
                      "contributions merged staleness-weighted").inc(
                merged_stale, session=s)
        if discarded_stale:
            r.counter("dl4j_elastic_stale_discarded_total", "late "
                      "contributions discarded past the staleness bound"
                      ).inc(discarded_stale, session=s)
        if was_hard:
            r.counter("dl4j_elastic_hard_syncs_total", "rounds forced "
                      "into full synchronous averaging by the "
                      "divergence guard").inc(session=s)
        r.gauge("dl4j_replica_divergence", "relative max pairwise "
                "spread of per-replica grad norms (0 = replicas in "
                "sync)").set(div, session=s)

    # ---- watchdog plumbing ----------------------------------------------
    def _guarded_wait(self, x):
        """Block on a dispatched step's output under the collective
        watchdog's in-flight window, so a peer that died mid-collective
        turns into a peer_loss exit instead of an infinite hang. No-op
        without a watchdog — the usual async dispatch pipelining is then
        preserved."""
        wd = self._watchdog
        if wd is None:
            return
        it = getattr(self.model, "_host_iteration", None)
        with wd.guard(iteration=it if it is not None else 0):
            jax.block_until_ready(x)
