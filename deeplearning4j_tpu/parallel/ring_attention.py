"""Ring attention: exact attention over sequence-sharded inputs.

The reference's longest-sequence story is truncated BPTT (SURVEY §5.7);
sequence/context parallelism is ABSENT there and is designed fresh here
(SURVEY §7.2 stage 7, §7.3 item 4): each device in a mesh axis holds a
T/P slice of the sequence; K/V blocks rotate around the ring via
``lax.ppermute`` (ICI neighbor exchange) while each device accumulates
its queries' attention with a numerically-stable online softmax
(flash-attention style running max/denominator). After P steps every
query has seen every key — EXACT attention, O(T/P) memory per chip,
compute/communication overlapped by XLA.

``ring_self_attention`` matches nn/layers/attention.py's
``scaled_dot_product_attention`` bit-for-all-practical-purposes (f32
softmax accumulation) — asserted by tests/test_attention.py.

Masking uses large-FINITE score floors (not -inf): -inf produces NaN in
the softmax/exp VJPs for fully-masked rows, which would poison batch
gradients (same rationale as scaled_dot_product_attention).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

SEQ_AXIS = "sp"

_NEG = float(jnp.finfo(jnp.float32).min) / 2  # host-sync-ok: trace-time Python constant


def _ring_attention_local(q, k, v, mask, axis_name: str, causal: bool):
    """Per-device body (runs under shard_map).

    q, k, v: (N, Tl, H, Dh) local sequence shards.
    mask:    (N, Tl) local key-validity shard, or None (statically known:
             the mask carry/permute/where work is skipped entirely).
    """
    n_dev = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    tl = q.shape[1]
    dh = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(dh, jnp.float32))
    qf = q.astype(jnp.float32)
    has_mask = mask is not None

    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    m0 = jnp.full(q.shape[:1] + (q.shape[2], tl), _NEG, jnp.float32)
    l0 = jnp.zeros_like(m0)                       # (N, H, Tq)
    acc0 = jnp.zeros(q.shape, jnp.float32)        # (N, Tq, H, Dh)

    def loop_body(i, carry):
        if has_mask:
            m, l, acc, k_c, v_c, mask_c = carry
        else:
            m, l, acc, k_c, v_c = carry
        src = (my - i) % n_dev                    # owner of this K/V block
        s = jnp.einsum("nqhd,nkhd->nhqk", qf,
                       k_c.astype(jnp.float32)) * scale
        if causal:
            qpos = my * tl + jnp.arange(tl)
            kpos = src * tl + jnp.arange(tl)
            s = jnp.where(kpos[None, None, None, :]
                          <= qpos[None, None, :, None], s, _NEG)
        if has_mask:
            s = jnp.where(mask_c[:, None, None, :].astype(bool), s, _NEG)
        m_new = jnp.maximum(m, s.max(-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        # masked entries: s == _NEG underflows exp to exact 0 for any
        # m_new ≥ O(1); for all-masked rows (m_new == _NEG) zero explicitly
        p = jnp.where(s <= _NEG, 0.0, p)
        l_new = l * corr + p.sum(-1)
        acc_new = (acc * corr.transpose(0, 2, 1)[..., None]
                   + jnp.einsum("nhqk,nkhd->nqhd", p,
                                v_c.astype(jnp.float32)))
        k_c = lax.ppermute(k_c, axis_name, perm)
        v_c = lax.ppermute(v_c, axis_name, perm)
        if has_mask:
            mask_c = lax.ppermute(mask_c, axis_name, perm)
            return m_new, l_new, acc_new, k_c, v_c, mask_c
        return m_new, l_new, acc_new, k_c, v_c

    init = ((m0, l0, acc0, k, v, mask) if has_mask
            else (m0, l0, acc0, k, v))
    out_carry = lax.fori_loop(0, n_dev, loop_body, init)
    l, acc = out_carry[1], out_carry[2]
    # (N, H, Tq) → (N, Tq, H); fully-masked rows (l == 0) emit zeros
    denom = l.transpose(0, 2, 1)[..., None]
    out = jnp.where(denom > 0, acc / jnp.maximum(denom, 1e-30), 0.0)
    return out.astype(q.dtype)


def _shard_attention(local_fn, q, k, v, mask, mesh: Mesh, axis: str,
                     batch_axis: Optional[str]):
    """Shared shard_map dispatch for sequence-parallel attention bodies:
    q/k/v sharded (batch, time) over the mesh, mask optional (statically
    absent → the body skips all mask work)."""
    bspec = batch_axis if batch_axis else None
    spec_qkv = P(bspec, axis, None, None)
    spec_mask = P(bspec, axis)
    from deeplearning4j_tpu.parallel.mesh import compat_shard_map
    if mask is None:
        shard_fn = compat_shard_map(
            lambda q_, k_, v_: local_fn(q_, k_, v_, None),
            mesh=mesh, in_specs=(spec_qkv,) * 3, out_specs=spec_qkv,
            check_vma=False)
        return shard_fn(q, k, v)
    shard_fn = compat_shard_map(
        local_fn, mesh=mesh,
        in_specs=(spec_qkv, spec_qkv, spec_qkv, spec_mask),
        out_specs=spec_qkv, check_vma=False)
    return shard_fn(q, k, v, mask)


def ring_self_attention(q, k, v, mesh: Mesh, *, axis: str = SEQ_AXIS,
                        mask: Optional[jax.Array] = None,
                        causal: bool = False,
                        batch_axis: Optional[str] = None):
    """Exact attention with q/k/v sharded along time over ``mesh[axis]``.

    q, k, v: (N, T, H, Dh) GLOBAL shapes; T must divide by the axis size.
    mask:    (N, T) key-validity mask (or None).
    Returns the (N, T, H, Dh) attention output, same sharding as q.
    """
    fn = functools.partial(_ring_attention_local, axis_name=axis,
                           causal=causal)
    return _shard_attention(fn, q, k, v, mask, mesh, axis, batch_axis)


def _ulysses_local(q, k, v, mask, axis_name: str, causal: bool):
    """Per-device body: all-to-all head-scatter/sequence-gather, full-
    sequence attention on the local head shard, all-to-all back."""
    a2a = functools.partial(lax.all_to_all, axis_name=axis_name,
                            tiled=True)
    qg = a2a(q, split_axis=2, concat_axis=1)   # (N, T, H/P, Dh)
    kg = a2a(k, split_axis=2, concat_axis=1)
    vg = a2a(v, split_axis=2, concat_axis=1)
    mg = (None if mask is None
          else lax.all_gather(mask, axis_name, axis=1, tiled=True))
    from deeplearning4j_tpu.ops.pallas_kernels import attention
    o = attention(qg, kg, vg, mask=mg, causal=causal)
    return a2a(o, split_axis=1, concat_axis=2)  # (N, T/P, H, Dh)


def ulysses_self_attention(q, k, v, mesh: Mesh, *, axis: str = SEQ_AXIS,
                           mask: Optional[jax.Array] = None,
                           causal: bool = False,
                           batch_axis: Optional[str] = None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style): the
    alternative SP strategy to the ring. Two all-to-alls swap the
    sequence sharding for a HEAD sharding, each device runs full-
    sequence attention (through the flash-kernel dispatch) on H/P heads,
    and a third all-to-all restores the sequence sharding.

    Trade-off vs the ring: Ulysses moves O(T·H·Dh/P) per device through
    three all-to-alls and needs ``H % P == 0``, but runs the unmodified
    single-device kernel (no online-softmax carry) and has no P-step
    serial dependency; the ring streams K/V in P hops with compute
    overlap and supports any H. Same math either way — both are asserted
    equal to ``scaled_dot_product_attention`` in tests/test_attention.py.

    q, k, v: (N, T, H, Dh) GLOBAL shapes; T and H must divide by the
    axis size. mask: (N, T) key-validity mask (or None).
    """
    p = int(mesh.shape[axis])
    if q.shape[2] % p:
        raise ValueError(f"ulysses needs heads ({q.shape[2]}) divisible"
                         f" by the {axis!r} axis ({p})")
    fn = functools.partial(_ulysses_local, axis_name=axis, causal=causal)
    return _shard_attention(fn, q, k, v, mask, mesh, axis, batch_axis)
