"""End-to-end request deadlines.

A :class:`Deadline` is the caller's remaining time budget, carried
from ui ingress (``X-Deadline-Ms`` header / ``deadline_ms`` body
field) through FleetRouter admission, batch forming, remote
dispatch, and the device tier. Every tier's contract is the same:
an expired request is shed *synchronously* — :class:`DeadlineExceeded`
(or ``ShedError(reason="deadline")`` at admission) maps to HTTP 504
upstream and the work never reaches the device.

This module sits below both ``parallel/serving.py`` and
``parallel/fleet.py`` (which must not import each other), so every
tier shares one exception type and one clock discipline: deadlines
are absolute points on a monotonic clock, converted from wall-budget
milliseconds exactly once at ingress.
"""

from __future__ import annotations

import math
import time
from typing import Optional


class DeadlineExceeded(RuntimeError):
    """A request's time budget was spent before (or while) serving it.
    Maps to HTTP 504 at the ui tier; reason string rides in
    ``detail``."""

    def __init__(self, detail: str = "deadline exceeded"):
        super().__init__(detail)
        self.detail = detail


class Deadline:
    """An absolute give-up point on a monotonic clock.

    The clock is injectable (the remote dispatcher's chaos-skewed
    clock, test doubles); ``time.monotonic`` otherwise.
    """

    __slots__ = ("t_end", "clock")

    def __init__(self, t_end: float, clock=time.monotonic):
        self.t_end = float(t_end)  # host-sync-ok: clock scalar, host time arithmetic
        self.clock = clock

    @classmethod
    def after_ms(cls, ms: float, clock=time.monotonic) -> "Deadline":
        return cls(clock() + float(ms) / 1e3, clock=clock)  # host-sync-ok: clock scalar, host time arithmetic

    @classmethod
    def from_ingress(cls, headers=None, body=None,
                     clock=time.monotonic) -> Optional["Deadline"]:
        """Parse a deadline out of a request: an explicit
        ``deadline_ms`` body field wins over the ``X-Deadline-Ms``
        header. Defensive: garbage, non-finite, or non-positive
        budgets yield None (no deadline) rather than a 500 — a broken
        client should degrade to the undeadlined behavior it had
        before this header existed."""
        raw = None
        if isinstance(body, dict):
            raw = body.get("deadline_ms")
        if raw is None and headers is not None:
            getter = getattr(headers, "get", None)
            if getter is not None:
                raw = getter("X-Deadline-Ms")
        if raw is None:
            return None
        try:
            ms = float(raw)  # host-sync-ok: parsing a request header/body scalar
        except (TypeError, ValueError):
            return None
        if not math.isfinite(ms) or ms <= 0:
            return None
        return cls.after_ms(ms, clock=clock)

    def remaining_s(self) -> float:
        return self.t_end - self.clock()

    @property
    def expired(self) -> bool:
        return self.remaining_s() <= 0.0

    def check(self, detail: str = "deadline exceeded") -> None:
        """Raise :class:`DeadlineExceeded` when the budget is spent —
        the synchronous shed every tier performs before doing work."""
        if self.expired:
            raise DeadlineExceeded(detail)

    def cap_timeout(self, configured: Optional[float]) -> float:
        """Per-attempt timeout = min(configured, remaining budget),
        floored at 0 — what the remote dispatcher hands its
        transport."""
        rem = max(self.remaining_s(), 0.0)
        if configured is None:
            return rem
        return min(float(configured), rem)  # host-sync-ok: config scalar, host time arithmetic

    def __repr__(self):
        return f"Deadline(remaining={self.remaining_s() * 1e3:.1f}ms)"
