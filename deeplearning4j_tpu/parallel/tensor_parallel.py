"""Megatron-style paired tensor parallelism for layer stacks.

No reference analog (SURVEY §2.11 row 7: TP is ABSENT in DL4J — "the TPU
build must design these fresh"). This module upgrades the round-1
column-only rules in ``parallel/sharding.py`` to *paired* row/column
sharding with activation partition specs:

- Consecutive dense layers alternate **column-parallel** (``W: P(None,
  model)``, bias sharded) and **row-parallel** (``W: P(model, None)``,
  bias replicated). Between the pair the activation stays sharded on the
  feature dim (elementwise activations commute with the tiling); after the
  row layer a single psum (inserted by GSPMD from the sharding mismatch)
  restores the replicated activation. Two matmuls, one collective — the
  Megatron MLP recipe.
- ``SelfAttentionLayer`` / ``TransformerEncoderBlock``: QKV projection
  column-parallel over *heads* (the packed Wqkv column order is head-major
  precisely so a contiguous tile is a set of whole heads), attention math
  runs with the head dim sharded, output projection ``Wo`` row-parallel;
  the FFN inside the block is the column→row dense pair. One psum after
  attention, one after the FFN — per block, same as Megatron.
- A final unpaired output layer still goes column-parallel when divisible
  (vocab/class-sharded logits, the Megatron LM-head layout).
- Activation partition specs are applied by the models via
  ``jax.lax.with_sharding_constraint`` at layer boundaries
  (``MultiLayerNetwork._forward``), so XLA never has to *infer* the
  intermediate layout.

Correctness is GSPMD's: shardings never change the math, so the TP train
step is bit-compatible (up to reduction order) with the replicated one —
asserted by the golden test ``tests/test_tensor_parallel.py`` (the analog
of the reference's "Spark vs single machine identical" golden test,
TestCompareParameterAveragingSparkVsSingleMachine.java:1).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

# activation-layout states at layer boundaries
_REPL = "replicated"      # features replicated over the model axis
_SHARDED = "sharded"      # last (feature) dim sharded over the model axis


@dataclasses.dataclass
class TPPlan:
    """Param shardings + per-layer-boundary activation layouts."""
    param_shardings: Any                 # pytree of NamedSharding
    act_kinds: Dict[str, str]            # layer name -> _REPL | _SHARDED
    mesh: Mesh
    model_axis: str = MODEL_AXIS
    data_axis: str = DATA_AXIS

    @property
    def model_parallelism(self) -> int:
        return int(self.mesh.shape.get(self.model_axis, 1))

    def constrain(self, name: str, x):
        """Apply this layer's boundary activation spec (inside jit)."""
        kind = self.act_kinds.get(name)
        if kind is None or not hasattr(x, "ndim") or x.ndim < 2:
            return x
        m = self.model_parallelism
        data = self.data_axis if self.data_axis in self.mesh.shape else None
        last = (self.model_axis
                if kind == _SHARDED and x.shape[-1] % m == 0 else None)
        spec = P(data, *([None] * (x.ndim - 2)), last)
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, spec))


def _named(mesh, spec_tree, params):
    """PartitionSpec pytree -> NamedSharding pytree matching ``params``."""
    return jax.tree_util.tree_map(
        lambda _, s: NamedSharding(mesh, s), params, spec_tree)


def _repl_specs(params):
    return jax.tree_util.tree_map(lambda _: P(), params)


def _attention_specs(p, m, ax):
    """Column(heads)/row pair for SelfAttentionLayer params. Requires the
    packed QKV dim (3*n_out) and the Wo input dim to tile m-ways."""
    if p["Wqkv"].shape[1] % m or p["Wo"].shape[0] % m:
        return _repl_specs(p)
    spec = {"Wqkv": P(None, ax), "Wo": P(ax, None)}
    if "bqkv" in p:
        spec["bqkv"] = P(ax)
    if "bo" in p:
        spec["bo"] = P()
    return spec


def _transformer_specs(p, m, ax, n_heads):
    """Megatron block: head-parallel attention + column→row FFN."""
    spec = {}
    attn = p["attn"]
    if n_heads % m == 0:
        spec["attn"] = _attention_specs(attn, m, ax)
    else:
        spec["attn"] = _repl_specs(attn)
    for ln in ("ln1", "ln2"):
        if ln in p:
            spec[ln] = _repl_specs(p[ln])
    if p["W1"].shape[1] % m == 0:
        spec["W1"] = P(None, ax)
        spec["W2"] = P(ax, None)
        if "b1" in p:
            spec["b1"] = P(ax)
        if "b2" in p:
            spec["b2"] = P()
    else:
        for k in ("W1", "W2", "b1", "b2"):
            if k in p:
                spec[k] = P()
    return spec


def _fallback_specs(p, m, ax):
    """Round-1 column-only rules for layer types without a pairing rule
    (conv output channels, recurrent gate matrices, embeddings)."""
    def rule(path, leaf):
        key = getattr(path[-1], "key", "")
        shape = getattr(leaf, "shape", ())
        if key == "dW" and len(shape) == 4 and shape[-1] % m == 0:
            return P(None, None, None, ax)
        if key in ("Wx", "Wh", "pW") and len(shape) == 2 and shape[-1] % m == 0:
            return P(None, ax)
        return P()
    flat, tree = jax.tree_util.tree_flatten_with_path(p)
    return jax.tree_util.tree_unflatten(tree, [rule(pa, l) for pa, l in flat])


def plan_tp(model, mesh: Mesh, *, model_axis: str = MODEL_AXIS,
            data_axis: str = DATA_AXIS) -> TPPlan:
    """Build the paired TP plan for a MultiLayerNetwork (full pairing
    across the layer stack) or a ComputationGraph (per-node rules: block-
    internal attention/FFN pairing still applies — a transformer block is
    a self-contained column→row pair regardless of DAG shape — but dense
    pairing ACROSS nodes is skipped, since a DAG edge may fan out).

    ``model`` must be initialized (param shapes are read from the live
    pytree). Layers the planner does not understand fall back to the
    round-1 column rules; anything non-divisible stays replicated.
    """
    from deeplearning4j_tpu.nn.layers.attention import (
        SelfAttentionLayer, TransformerEncoderBlock)
    from deeplearning4j_tpu.nn.layers.feedforward import (
        ActivationLayer, AutoEncoder, DenseLayer, DropoutLayer)

    params = model.train_state.params
    if hasattr(model, "layers"):
        layers = list(model.layers)
    else:
        return _plan_tp_graph(model, mesh, model_axis=model_axis,
                              data_axis=data_axis)
    m = int(mesh.shape.get(model_axis, 1))
    ax = model_axis
    spec_tree: Dict[str, Any] = {}
    act_kinds: Dict[str, str] = {}

    if m <= 1:
        for layer in layers:
            spec_tree[layer.name] = _repl_specs(params.get(layer.name, {}))
            act_kinds[layer.name] = _REPL
        return TPPlan(_named(mesh, spec_tree, params), act_kinds, mesh,
                      model_axis, data_axis)

    def dense_w(layer):
        p = params.get(layer.name, {})
        w = p.get("W")
        return w if (w is not None and w.ndim == 2) else None

    def pairable_ahead(i, width):
        """Is there a row-parallel partner after layer i (skipping
        shape-preserving no-param layers)?"""
        for j in range(i + 1, len(layers)):
            lj = layers[j]
            if isinstance(lj, (ActivationLayer, DropoutLayer)):
                continue
            if isinstance(lj, (DenseLayer, AutoEncoder)):
                w = dense_w(lj)
                return (w is not None and w.shape[0] == width
                        and w.shape[0] % m == 0)
            return False
        return False

    state = _REPL
    for i, layer in enumerate(layers):
        p = params.get(layer.name, {})
        name = layer.name
        if isinstance(layer, TransformerEncoderBlock):
            spec_tree[name] = _transformer_specs(p, m, ax, layer.n_heads)
            act_kinds[name] = _REPL
            state = _REPL
        elif isinstance(layer, SelfAttentionLayer):
            if layer.n_heads % m == 0:
                spec_tree[name] = _attention_specs(p, m, ax)
            else:
                spec_tree[name] = _repl_specs(p)
            act_kinds[name] = _REPL
            state = _REPL
        elif isinstance(layer, (DenseLayer, AutoEncoder)) and \
                dense_w(layer) is not None:
            w = dense_w(layer)
            n_in, n_out = w.shape
            spec = _repl_specs(p)
            if state == _SHARDED and n_in % m == 0:
                # row-parallel partner: closes the pair with one psum
                spec["W"] = P(ax, None)
                if "b" in p:
                    spec["b"] = P()
                act_kinds[name] = _REPL
                state = _REPL
            elif state == _REPL and n_out % m == 0 and (
                    pairable_ahead(i, n_out) or i == len(layers) - 1):
                # column-parallel: open a pair, or the final class/vocab-
                # sharded logits layer (Megatron LM-head)
                spec["W"] = P(None, ax)
                if "b" in p:
                    spec["b"] = P(ax)
                act_kinds[name] = _SHARDED
                state = _SHARDED
            else:
                act_kinds[name] = _REPL
                state = _REPL
            spec_tree[name] = spec
        elif isinstance(layer, (ActivationLayer, DropoutLayer)):
            spec_tree[name] = _repl_specs(p)
            act_kinds[name] = state
        else:
            spec_tree[name] = _fallback_specs(p, m, ax)
            act_kinds[name] = _REPL
            state = _REPL

    return TPPlan(_named(mesh, spec_tree, params), act_kinds, mesh,
                  model_axis, data_axis)


def _plan_tp_graph(model, mesh: Mesh, *, model_axis: str = MODEL_AXIS,
                   data_axis: str = DATA_AXIS) -> TPPlan:
    """Per-node TP plan for a ComputationGraph: transformer blocks and
    attention layers keep their internal Megatron pairing (input and
    output replicated, so DAG fan-out is safe); everything else uses the
    fallback column rules."""
    from deeplearning4j_tpu.nn.layers.attention import (
        SelfAttentionLayer, TransformerEncoderBlock)

    params = model.train_state.params
    m = int(mesh.shape.get(model_axis, 1))
    ax = model_axis
    spec_tree: Dict[str, Any] = {}
    act_kinds: Dict[str, str] = {}
    for node in model._layer_nodes:
        name, layer = node.name, node.layer
        p = params.get(name, {})
        if m <= 1:
            spec_tree[name] = _repl_specs(p)
        elif isinstance(layer, TransformerEncoderBlock):
            spec_tree[name] = _transformer_specs(p, m, ax, layer.n_heads)
        elif isinstance(layer, SelfAttentionLayer) and "Wqkv" in p \
                and layer.n_heads % m == 0:
            spec_tree[name] = _attention_specs(p, m, ax)
        else:
            spec_tree[name] = _fallback_specs(p, m, ax)
        act_kinds[name] = _REPL
    return TPPlan(_named(mesh, spec_tree, params), act_kinds, mesh,
                  model_axis, data_axis)


def shard_train_state(model, plan: TPPlan):
    """device_put the model's TrainState onto the plan: params per the
    plan, optimizer-state leaves that mirror a param with that param's
    sharding, everything else replicated. Returns the new TrainState."""
    from deeplearning4j_tpu.optimize.solver import TrainState
    from deeplearning4j_tpu.parallel.checkpoint import mirror_opt_shardings

    ts = model.train_state
    repl = NamedSharding(plan.mesh, P())
    opt_sh = mirror_opt_shardings(ts.opt_state, ts.params,
                                  plan.param_shardings, repl)
    put = jax.tree_util.tree_map
    new = TrainState(
        put(jax.device_put, ts.params, plan.param_shardings),
        jax.device_put(ts.model_state, repl),
        put(jax.device_put, ts.opt_state, opt_sh),
        jax.device_put(ts.iteration, repl))
    model.train_state = new
    return new, TrainState(plan.param_shardings, repl, opt_sh, repl)
