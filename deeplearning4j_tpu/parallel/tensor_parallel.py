"""Megatron-style paired tensor parallelism for layer stacks.

No reference analog (SURVEY §2.11 row 7: TP is ABSENT in DL4J — "the TPU
build must design these fresh"). This module upgrades the round-1
column-only rules in ``parallel/sharding.py`` to *paired* row/column
sharding with activation partition specs:

- Consecutive dense layers alternate **column-parallel** (``W: P(None,
  model)``, bias sharded) and **row-parallel** (``W: P(model, None)``,
  bias replicated). Between the pair the activation stays sharded on the
  feature dim (elementwise activations commute with the tiling); after the
  row layer a single psum (inserted by GSPMD from the sharding mismatch)
  restores the replicated activation. Two matmuls, one collective — the
  Megatron MLP recipe.
- ``SelfAttentionLayer`` / ``TransformerEncoderBlock``: QKV projection
  column-parallel over *heads* (the packed Wqkv column order is head-major
  precisely so a contiguous tile is a set of whole heads), attention math
  runs with the head dim sharded, output projection ``Wo`` row-parallel;
  the FFN inside the block is the column→row dense pair. One psum after
  attention, one after the FFN — per block, same as Megatron.
- A final unpaired output layer still goes column-parallel when divisible
  (vocab/class-sharded logits, the Megatron LM-head layout).
- Activation partition specs are applied by the models via
  ``jax.lax.with_sharding_constraint`` at layer boundaries
  (``MultiLayerNetwork._forward``), so XLA never has to *infer* the
  intermediate layout.

Correctness is GSPMD's: shardings never change the math, so the TP train
step is bit-compatible (up to reduction order) with the replicated one —
asserted by the golden test ``tests/test_tensor_parallel.py`` (the analog
of the reference's "Spark vs single machine identical" golden test,
TestCompareParameterAveragingSparkVsSingleMachine.java:1).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

# activation-layout states at layer boundaries
_REPL = "replicated"      # features replicated over the model axis
_SHARDED = "sharded"      # last (feature) dim sharded over the model axis


@dataclasses.dataclass
class TPPlan:
    """Param shardings + per-layer-boundary activation layouts."""
    param_shardings: Any                 # pytree of NamedSharding
    act_kinds: Dict[str, str]            # layer name -> _REPL | _SHARDED
    mesh: Mesh
    model_axis: str = MODEL_AXIS
    data_axis: str = DATA_AXIS
    # per-layer model_state shardings (BatchNorm running stats of a
    # channel-sharded conv pair live sharded); None = all replicated
    state_shardings: Optional[Dict[str, Any]] = None

    @property
    def model_parallelism(self) -> int:
        return int(self.mesh.shape.get(self.model_axis, 1))

    def constrain(self, name: str, x):
        """Apply this layer's boundary activation spec (inside jit)."""
        kind = self.act_kinds.get(name)
        if kind is None or not hasattr(x, "ndim") or x.ndim < 2:
            return x
        m = self.model_parallelism
        data = self.data_axis if self.data_axis in self.mesh.shape else None
        last = (self.model_axis
                if kind == _SHARDED and x.shape[-1] % m == 0 else None)
        spec = P(data, *([None] * (x.ndim - 2)), last)
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, spec))


def _named(mesh, spec_tree, params):
    """PartitionSpec pytree -> NamedSharding pytree matching ``params``."""
    return jax.tree_util.tree_map(
        lambda _, s: NamedSharding(mesh, s), params, spec_tree)


def _repl_specs(params):
    return jax.tree_util.tree_map(lambda _: P(), params)


def _attention_specs(p, m, ax):
    """Column(heads)/row pair for SelfAttentionLayer params. Requires the
    packed QKV dim (3*n_out) and the Wo input dim to tile m-ways."""
    if p["Wqkv"].shape[1] % m or p["Wo"].shape[0] % m:
        return _repl_specs(p)
    spec = {"Wqkv": P(None, ax), "Wo": P(ax, None)}
    if "bqkv" in p:
        spec["bqkv"] = P(ax)
    if "bo" in p:
        spec["bo"] = P()
    return spec


def _transformer_specs(p, m, ax, n_heads):
    """Megatron block: head-parallel attention + column→row FFN."""
    spec = {}
    attn = p["attn"]
    if n_heads % m == 0:
        spec["attn"] = _attention_specs(attn, m, ax)
    else:
        spec["attn"] = _repl_specs(attn)
    for ln in ("ln1", "ln2"):
        if ln in p:
            spec[ln] = _repl_specs(p[ln])
    if p["W1"].shape[1] % m == 0:
        spec["W1"] = P(None, ax)
        spec["W2"] = P(ax, None)
        if "b1" in p:
            spec["b1"] = P(ax)
        if "b2" in p:
            spec["b2"] = P()
    else:
        for k in ("W1", "W2", "b1", "b2"):
            if k in p:
                spec[k] = P()
    return spec


def _lstm_specs(layer, p, m, ax):
    """Hidden-unit-sharded LSTM: requires the opt-in "hidden_major" gate
    packing (a contiguous 4H-column tile then holds all four gates of a
    hidden slice, so the recurrence c/h math stays local per shard).
    Wx/Wh column-parallel, bias sharded; GSPMD all-gathers h_prev into
    each step's Wh contraction — the inherent LSTM-TP collective."""
    from deeplearning4j_tpu.nn.layers.recurrent import LSTM
    if (type(layer) is not LSTM
            or getattr(layer, "gate_layout", "") != "hidden_major"
            or layer.n_out % m):
        return None
    spec = _repl_specs(p)
    spec["Wx"] = P(None, ax)
    spec["Wh"] = P(None, ax)
    if "b" in p:
        spec["b"] = P(ax)
    return spec


def _fallback_specs(p, m, ax):
    """Round-1 column-only rules for layer types without a pairing rule
    (conv output channels, recurrent gate matrices, embeddings)."""
    def rule(path, leaf):
        key = getattr(path[-1], "key", "")
        shape = getattr(leaf, "shape", ())
        # HWIO conv kernels: shard output channels (key is "W" on
        # ConvolutionLayer; "dW" kept for depthwise kernels)
        if key in ("W", "dW") and len(shape) == 4 and shape[-1] % m == 0:
            return P(None, None, None, ax)
        if key in ("Wx", "Wh", "pW") and len(shape) == 2 and shape[-1] % m == 0:
            return P(None, ax)
        return P()
    flat, tree = jax.tree_util.tree_flatten_with_path(p)
    return jax.tree_util.tree_unflatten(tree, [rule(pa, l) for pa, l in flat])


def plan_tp(model, mesh: Mesh, *, model_axis: str = MODEL_AXIS,
            data_axis: str = DATA_AXIS) -> TPPlan:
    """Build the paired TP plan for a MultiLayerNetwork (full pairing
    across the layer stack) or a ComputationGraph (per-node rules: block-
    internal attention/FFN pairing still applies — a transformer block is
    a self-contained column→row pair regardless of DAG shape — but dense
    pairing ACROSS nodes is skipped, since a DAG edge may fan out).

    ``model`` must be initialized (param shapes are read from the live
    pytree). Layers the planner does not understand fall back to the
    round-1 column rules; anything non-divisible stays replicated.
    """
    from deeplearning4j_tpu.nn.layers.attention import (
        SelfAttentionLayer, TransformerEncoderBlock)
    from deeplearning4j_tpu.nn.layers.feedforward import (
        ActivationLayer, AutoEncoder, DenseLayer, DropoutLayer)

    params = model.train_state.params
    if hasattr(model, "layers"):
        layers = list(model.layers)
    else:
        return _plan_tp_graph(model, mesh, model_axis=model_axis,
                              data_axis=data_axis)
    m = int(mesh.shape.get(model_axis, 1))
    ax = model_axis
    spec_tree: Dict[str, Any] = {}
    act_kinds: Dict[str, str] = {}

    if m <= 1:
        for layer in layers:
            spec_tree[layer.name] = _repl_specs(params.get(layer.name, {}))
            act_kinds[layer.name] = _REPL
        return TPPlan(_named(mesh, spec_tree, params), act_kinds, mesh,
                      model_axis, data_axis)

    def dense_w(layer):
        p = params.get(layer.name, {})
        w = p.get("W")
        return w if (w is not None and w.ndim == 2) else None

    def pairable_ahead(i, width):
        """Is there a row-parallel partner after layer i (skipping
        shape-preserving no-param layers)?"""
        for j in range(i + 1, len(layers)):
            lj = layers[j]
            if isinstance(lj, (ActivationLayer, DropoutLayer)):
                continue
            if isinstance(lj, (DenseLayer, AutoEncoder)):
                w = dense_w(lj)
                return (w is not None and w.shape[0] == width
                        and w.shape[0] % m == 0)
            return False
        return False

    state = _REPL
    for i, layer in enumerate(layers):
        p = params.get(layer.name, {})
        name = layer.name
        if isinstance(layer, TransformerEncoderBlock):
            spec_tree[name] = _transformer_specs(p, m, ax, layer.n_heads)
            act_kinds[name] = _REPL
            state = _REPL
        elif isinstance(layer, SelfAttentionLayer):
            if layer.n_heads % m == 0:
                spec_tree[name] = _attention_specs(p, m, ax)
            else:
                spec_tree[name] = _repl_specs(p)
            act_kinds[name] = _REPL
            state = _REPL
        elif isinstance(layer, (DenseLayer, AutoEncoder)) and \
                dense_w(layer) is not None:
            w = dense_w(layer)
            n_in, n_out = w.shape
            spec = _repl_specs(p)
            if state == _SHARDED and n_in % m == 0:
                # row-parallel partner: closes the pair with one psum
                spec["W"] = P(ax, None)
                if "b" in p:
                    spec["b"] = P()
                act_kinds[name] = _REPL
                state = _REPL
            elif state == _REPL and n_out % m == 0 and (
                    pairable_ahead(i, n_out) or i == len(layers) - 1):
                # column-parallel: open a pair, or the final class/vocab-
                # sharded logits layer (Megatron LM-head)
                spec["W"] = P(None, ax)
                if "b" in p:
                    spec["b"] = P(ax)
                act_kinds[name] = _SHARDED
                state = _SHARDED
            else:
                act_kinds[name] = _REPL
                state = _REPL
            spec_tree[name] = spec
        elif isinstance(layer, (ActivationLayer, DropoutLayer)):
            spec_tree[name] = _repl_specs(p)
            act_kinds[name] = state
        else:
            lstm = _lstm_specs(layer, p, m, ax)
            spec_tree[name] = lstm if lstm is not None \
                else _fallback_specs(p, m, ax)
            act_kinds[name] = _REPL
            state = _REPL

    return TPPlan(_named(mesh, spec_tree, params), act_kinds, mesh,
                  model_axis, data_axis)


def _find_conv_chains(model, m: int):
    """Bottleneck conv chains in a ComputationGraph, by structure (not
    by name): a 1×1 conv whose single-consumer chain through
    BatchNorm/Activation reaches a 3×3 conv, then another such chain to
    a closing 1×1 conv + its BatchNorm. Returns a list of dicts naming
    the chain's members. Only chains whose mid-width divides the model
    axis are returned."""
    from deeplearning4j_tpu.nn.layers.convolution import ConvolutionLayer
    from deeplearning4j_tpu.nn.layers.feedforward import ActivationLayer
    from deeplearning4j_tpu.nn.layers.normalization import (
        BatchNormalization)

    nodes = {n.name: n for n in model.conf.nodes}
    consumers: Dict[str, list] = {}
    for n in model.conf.nodes:
        for src in n.inputs:
            consumers.setdefault(src, []).append(n.name)

    def kernel(layer):
        k = layer.kernel_size
        return (k, k) if isinstance(k, int) else tuple(k)

    def is_conv(name, ksize):
        n = nodes.get(name)
        return (n is not None and isinstance(n.layer, ConvolutionLayer)
                and type(n.layer) is ConvolutionLayer
                and kernel(n.layer) == ksize)

    def follow_bn_act(name):
        """From a conv node, walk its single-consumer BN (+optional
        Activation); returns (bn_name, act_name|None, next_name)."""
        cons = consumers.get(name, [])
        if len(cons) != 1:
            return None
        bn = nodes.get(cons[0])
        if bn is None or not isinstance(bn.layer, BatchNormalization):
            return None
        cons2 = consumers.get(bn.name, [])
        if len(cons2) != 1:
            return None
        nxt = nodes.get(cons2[0])
        if nxt is not None and isinstance(nxt.layer, ActivationLayer):
            cons3 = consumers.get(nxt.name, [])
            if len(cons3) != 1:
                return None
            return bn.name, nxt.name, cons3[0]
        return bn.name, None, cons2[0]

    chains = []
    for n in model.conf.nodes:
        if n.layer is None or not is_conv(n.name, (1, 1)):
            continue
        if n.layer.n_out % m:
            continue
        step_a = follow_bn_act(n.name)
        if step_a is None or not is_conv(step_a[2], (3, 3)):
            continue
        b_name = step_a[2]
        if nodes[b_name].layer.n_out % m:
            continue
        step_b = follow_bn_act(b_name)
        if step_b is None or not is_conv(step_b[2], (1, 1)):
            continue
        # the closing conv's own BatchNorm stays replicated by design
        # (it normalizes the post-psum replicated activation)
        chains.append({
            "a": n.name, "a_bn": step_a[0], "a_act": step_a[1],
            "b": b_name, "b_bn": step_b[0], "b_act": step_b[1],
            "c": step_b[2],
        })
    return chains


def _plan_tp_graph(model, mesh: Mesh, *, model_axis: str = MODEL_AXIS,
                   data_axis: str = DATA_AXIS) -> TPPlan:
    """Per-node TP plan for a ComputationGraph.

    Transformer blocks and attention layers keep their internal Megatron
    pairing (input and output replicated, so DAG fan-out is safe).
    Bottleneck conv chains (1×1 → BN → ReLU → 3×3 → BN → ReLU → 1×1 →
    BN) get the paired conv tiling: the opening 1×1 and the 3×3 are
    column-parallel over output channels (GSPMD all-gathers the sharded
    activation into the 3×3's full-channel contraction), the closing
    1×1 is row-parallel (one psum restores the replicated residual), and
    the BatchNorms between them run fully sharded — per-channel stats
    need no communication at all. Per block: 1 all-gather + 1 psum.
    Everything else uses the fallback column rules."""
    from deeplearning4j_tpu.nn.layers.attention import (
        SelfAttentionLayer, TransformerEncoderBlock)

    params = model.train_state.params
    m = int(mesh.shape.get(model_axis, 1))
    ax = model_axis
    spec_tree: Dict[str, Any] = {}
    act_kinds: Dict[str, str] = {}
    state_specs: Dict[str, Any] = {}

    chain_rules: Dict[str, Any] = {}
    if m > 1:
        for ch in _find_conv_chains(model, m):
            # column convs: HWIO output channels sharded
            chain_rules[ch["a"]] = ("conv", P(None, None, None, ax),
                                    _SHARDED)
            chain_rules[ch["b"]] = ("conv", P(None, None, None, ax),
                                    _SHARDED)
            # row conv: input channels sharded → psum; output replicated
            chain_rules[ch["c"]] = ("conv", P(None, None, ax, None),
                                    _REPL)
            for bn in (ch["a_bn"], ch["b_bn"]):
                chain_rules[bn] = ("bn", P(ax), _SHARDED)
            for act in (ch["a_act"], ch["b_act"]):
                if act is not None:
                    chain_rules[act] = ("pass", None, _SHARDED)

    for node in model._layer_nodes:
        name, layer = node.name, node.layer
        p = params.get(name, {})
        rule = chain_rules.get(name)
        if m <= 1:
            spec_tree[name] = _repl_specs(p)
            act_kinds[name] = _REPL
        elif rule is not None:
            kind, spec, act = rule
            if kind == "conv":
                s = _repl_specs(p)
                s["W"] = spec
                if "b" in p:
                    s["b"] = P(ax) if act == _SHARDED else P()
                spec_tree[name] = s
            elif kind == "bn":
                spec_tree[name] = {k: spec for k in p}
                state_specs[name] = {"mean": spec, "var": spec}
            else:
                spec_tree[name] = _repl_specs(p)
            act_kinds[name] = act
        elif isinstance(layer, TransformerEncoderBlock):
            spec_tree[name] = _transformer_specs(p, m, ax, layer.n_heads)
            act_kinds[name] = _REPL
        elif isinstance(layer, SelfAttentionLayer) and "Wqkv" in p \
                and layer.n_heads % m == 0:
            spec_tree[name] = _attention_specs(p, m, ax)
            act_kinds[name] = _REPL
        else:
            lstm = _lstm_specs(layer, p, m, ax)
            spec_tree[name] = lstm if lstm is not None \
                else _fallback_specs(p, m, ax)
            act_kinds[name] = _REPL
    state_sh = None
    if state_specs:
        mstate = model.train_state.model_state
        state_sh = {
            lname: {k: NamedSharding(mesh, s)
                    for k, s in specs.items() if k in mstate.get(lname, {})}
            for lname, specs in state_specs.items()}
    return TPPlan(_named(mesh, spec_tree, params), act_kinds, mesh,
                  model_axis, data_axis, state_shardings=state_sh)


def count_collectives(compiled) -> Dict[str, int]:
    """Collective-op census of a compiled executable (the per-block
    communication count VERDICT r3 #4 asks the planner to report):
    occurrences of each collective HLO in the optimized module."""
    import re
    txt = compiled.as_text()
    out: Dict[str, int] = {}
    for op in ("all-reduce", "all-gather", "reduce-scatter",
               "collective-permute", "all-to-all"):
        n = len(re.findall(rf" {op}(?:-start)?\(", txt))
        if n:
            out[op] = n
    return out


def shard_train_state(model, plan: TPPlan):
    """device_put the model's TrainState onto the plan: params per the
    plan, optimizer-state leaves that mirror a param with that param's
    sharding, everything else replicated. Returns the new TrainState."""
    from deeplearning4j_tpu.optimize.solver import TrainState
    from deeplearning4j_tpu.parallel.checkpoint import mirror_opt_shardings

    ts = model.train_state
    repl = NamedSharding(plan.mesh, P())
    opt_sh = mirror_opt_shardings(ts.opt_state, ts.params,
                                  plan.param_shardings, repl)
    # model_state: replicated except where the plan shards it (BN running
    # stats of channel-sharded conv pairs). Per-LAYER prefix shardings,
    # not per-leaf: layers may add state keys on the first step (LSTM's
    # last_h/last_c), and a bare sharding prefix covers whatever appears.
    plan_state = plan.state_shardings or {}
    state_sh = {lname: plan_state.get(lname, repl)
                for lname in ts.model_state}
    # device_put needs per-leaf shardings for the CURRENT keys; the
    # prefix form above stays in the returned sharding struct
    state_sh_exact = {
        lname: (sub_sh if isinstance(sub_sh, dict)
                else jax.tree_util.tree_map(lambda _: sub_sh,
                                            ts.model_state[lname]))
        for lname, sub_sh in state_sh.items()}
    put = jax.tree_util.tree_map
    new = TrainState(
        put(jax.device_put, ts.params, plan.param_shardings),
        put(jax.device_put, ts.model_state, state_sh_exact),
        put(jax.device_put, ts.opt_state, opt_sh),
        jax.device_put(ts.iteration, repl))
    model.train_state = new
    return new, TrainState(plan.param_shardings, state_sh, opt_sh, repl)
