"""Streaming latency quantiles for the serving path.

A serving engine needs p50/p95/p99 over recent requests without keeping
an unbounded history or adding per-request allocation. ``LatencyRing``
is a fixed-capacity ring of the last N observations (seconds) with a
lock cheap enough to take per request; ``quantiles()`` sorts a snapshot
on demand (the scrape path, not the hot path). Nearest-rank quantiles —
the convention Prometheus summaries use — so p99 of 100 samples is the
99th ordered sample, not an interpolation.
"""

from __future__ import annotations

import threading
from typing import Dict, Sequence, Tuple

DEFAULT_QUANTILES: Tuple[float, ...] = (0.5, 0.95, 0.99)


class LatencyRing:
    """Last-``capacity`` latency observations, in seconds."""

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._buf = [0.0] * self.capacity
        self._n = 0            # total ever recorded
        self._lock = threading.Lock()

    def record(self, seconds: float):
        with self._lock:
            self._buf[self._n % self.capacity] = float(seconds)
            self._n += 1

    @property
    def count(self) -> int:
        return self._n

    def snapshot(self) -> list:
        """The live window (unordered), at most ``capacity`` samples."""
        with self._lock:
            if self._n >= self.capacity:
                return list(self._buf)
            return self._buf[:self._n]

    def quantiles(self, qs: Sequence[float] = DEFAULT_QUANTILES
                  ) -> Dict[float, float]:
        """Nearest-rank quantiles of the window; empty ring -> {}."""
        window = self.snapshot()
        if not window:
            return {}
        window.sort()
        n = len(window)
        out = {}
        for q in qs:
            if not 0.0 <= q <= 1.0:
                raise ValueError(f"quantile out of range: {q}")
            rank = min(n - 1, max(0, int(q * n + 0.5) - 1))
            out[q] = window[rank]
        return out
