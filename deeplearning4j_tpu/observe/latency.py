"""Streaming latency quantiles for the serving path.

A serving engine needs p50/p95/p99 over recent requests without keeping
an unbounded history or adding per-request allocation. ``LatencyRing``
is a fixed-capacity ring of the last N observations (seconds) with a
lock cheap enough to take per request; ``quantiles()`` sorts a snapshot
on demand (the scrape path, not the hot path). Nearest-rank quantiles —
the convention Prometheus summaries use — so p99 of 100 samples is the
99th ordered sample, not an interpolation.

Two read modes (PR 6):

- ``quantiles()`` — the full live window (up to ``capacity`` samples),
  the dashboard/scrape view.
- ``delta_quantiles()`` — only observations recorded since the previous
  ``delta_quantiles()`` call (or ``mark()``). This is what a feedback
  controller wants: the fleet router's SLO shedder reacts to the last
  tick's traffic, not to a 4096-sample history that takes minutes to
  forget a spike.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Sequence, Tuple

DEFAULT_QUANTILES: Tuple[float, ...] = (0.5, 0.95, 0.99)


def _validate_quantiles(qs: Sequence[float]):
    """Range-check BEFORE any sorting work: a bad q must raise even on
    an empty window, and must not waste the sort on a doomed call."""
    for q in qs:
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile out of range: {q}")


def _nearest_rank(window: List[float], qs: Sequence[float]
                  ) -> Dict[float, float]:
    window.sort()
    n = len(window)
    out = {}
    for q in qs:
        rank = min(n - 1, max(0, int(q * n + 0.5) - 1))
        out[q] = window[rank]
    return out


class LatencyRing:
    """Last-``capacity`` latency observations, in seconds."""

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._buf = [0.0] * self.capacity
        self._n = 0            # writes into the current window
        self._total = 0        # total ever recorded (survives reset)
        self._delta_mark = 0   # _total at the last delta scrape / mark
        self._lock = threading.Lock()

    def record(self, seconds: float):
        with self._lock:
            self._buf[self._n % self.capacity] = float(seconds)
            self._n += 1
            self._total += 1

    @property
    def count(self) -> int:
        """Total observations ever recorded (monotonic; ``reset()``
        empties the window but does not rewind this)."""
        return self._total

    def reset(self):
        """Drop the stored window (e.g. after a version swap, so stale
        latencies don't poison the new version's quantiles). The
        cumulative ``count`` and the delta mark are preserved — a delta
        scrape after reset only sees post-reset observations."""
        with self._lock:
            self._n = 0
            # observations recorded before the reset are gone; the next
            # delta window must not claim them
            self._delta_mark = self._total

    def mark(self):
        """Start a fresh delta window without reading quantiles."""
        with self._lock:
            self._delta_mark = self._total

    def snapshot(self) -> list:
        """The live window (unordered), at most ``capacity`` samples."""
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> list:
        if self._n >= self.capacity:
            return list(self._buf)
        return self._buf[:self._n]

    def quantiles(self, qs: Sequence[float] = DEFAULT_QUANTILES
                  ) -> Dict[float, float]:
        """Nearest-rank quantiles of the window; empty ring -> {}."""
        _validate_quantiles(qs)
        window = self.snapshot()
        if not window:
            return {}
        return _nearest_rank(window, qs)

    def delta_quantiles(self, qs: Sequence[float] = DEFAULT_QUANTILES
                        ) -> Dict[float, float]:
        """Nearest-rank quantiles over observations since the last
        ``delta_quantiles()``/``mark()`` call; advances the mark. No new
        observations (or more new observations than the ring can hold:
        clamped to the window) -> {} / the newest ``capacity``."""
        _validate_quantiles(qs)
        with self._lock:
            fresh = self._total - self._delta_mark
            self._delta_mark = self._total
            if fresh <= 0:
                return {}
            k = min(fresh, self._n, self.capacity)
            if k <= 0:
                return {}
            if k >= self.capacity and self._n >= self.capacity:
                window = list(self._buf)
            else:
                # the k most recent entries, ending at write position
                end = self._n % self.capacity \
                    if self._n >= self.capacity else self._n
                start = end - k
                if start >= 0:
                    window = self._buf[start:end]
                else:
                    window = self._buf[start:] + self._buf[:end]
        return _nearest_rank(window, qs)
