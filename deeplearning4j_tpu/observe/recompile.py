"""Recompile watchdog: catch silent retrace storms.

Every distinct (shape, dtype) signature a jitted step sees costs a full
XLA compile — minutes on big models — and jax gives no per-call-site
counter. The watchdog fingerprints each dispatch's argument pytree
(shapes/dtypes only, a few µs on host) and records every NEW signature
after the first per step key. New signatures increment the
``dl4j_recompiles_total`` Prometheus counter and log a warning naming
the offending shapes, so a leaky data pipeline (ragged batches, dtype
drift) shows up as a climbing series instead of mystery step-time
spikes.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from deeplearning4j_tpu.observe.registry import (
    MetricsRegistry,
    default_registry,
)

log = logging.getLogger(__name__)


def signature_of(*trees) -> Tuple:
    """Hashable compile signature of argument pytrees: tree structure +
    (shape, dtype) per array leaf; non-arrays contribute their type
    (None vs array flips compiled branches, e.g. optional masks)."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(trees)
    sig = []
    for l in leaves:
        if hasattr(l, "shape") and hasattr(l, "dtype"):
            sig.append((tuple(l.shape), str(l.dtype)))
        elif isinstance(l, (bool, int, float, np.number)):
            sig.append((type(l).__name__, l))
        else:
            sig.append(type(l).__name__)
    return (str(treedef), tuple(sig))


class RecompileWatchdog:
    """Tracks signatures per step key (``train_step``, ``tbptt_step``,
    ...). ``observe`` returns True when the signature is new — i.e. the
    next dispatch almost certainly compiles."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 session_id: str = "train"):
        self.registry = registry if registry is not None else \
            default_registry()
        self.session_id = session_id
        self._sigs: Dict[str, Set[Tuple]] = {}
        self.events: List[dict] = []
        # register the series up front so /metrics shows a 0 count
        # instead of an absent metric on healthy runs
        self._counter = self.registry.counter(
            "dl4j_recompiles_total", "new (shape, dtype) signatures seen "
            "by compiled steps after their first compile")
        self._counter.inc(0.0, session=self.session_id)

    def observe(self, step_key: str, *trees) -> bool:
        sig = signature_of(*trees)
        seen = self._sigs.setdefault(step_key, set())
        if sig in seen:
            return False
        first = not seen
        seen.add(sig)
        if first:
            return True     # the initial compile is expected, not counted
        self.events.append({"step": step_key, "signature": sig})
        self._counter.inc(1.0, session=self.session_id)
        log.warning(
            "recompile: step %r saw new signature #%d %s — check the "
            "data pipeline for ragged shapes/dtype drift",
            step_key, len(seen) - 1, sig[1])
        return True

    def count(self, step_key: Optional[str] = None) -> int:
        """Recompiles beyond the first compile (0 on a healthy run)."""
        if step_key is not None:
            return max(0, len(self._sigs.get(step_key, ())) - 1)
        return sum(max(0, len(s) - 1) for s in self._sigs.values())
