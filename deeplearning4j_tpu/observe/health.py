"""Health evaluation over the metrics registry.

``/healthz`` on the UI server used to be a bare liveness probe; a
process that is alive but training garbage (NaN storm, recompile storm,
desynced replicas) answered "ok". This module turns the registry's
already-published series into a degradation verdict so orchestrators and
probes see a 503 + reason while the run is still salvageable.

Conditions (each tunable via environment):

- any ``dl4j_nonfinite_values_total`` series > 0 — gradients or loss
  went NaN/Inf (the flight recorder has written a post-mortem by now)
- ``dl4j_recompiles_total`` >= ``DL4J_RECOMPILE_STORM`` (default 8) —
  a leaky input pipeline is retracing the step
- ``dl4j_replica_divergence`` > ``DL4J_DIVERGENCE_THRESHOLD`` (default
  2.0, i.e. the per-replica grad-norm spread exceeds 2x its mean
  magnitude) — a data-parallel replica has drifted from the pack
- any ``dl4j_elastic_peer_loss_total`` > 0 — the collective watchdog
  declared a peer dead; this process (or a peer) wrote an emergency
  checkpoint and a ``PEER_LOSS.json`` marker and should be relaunched
- ``dl4j_elastic_staleness`` > ``DL4J_ELASTIC_STALENESS_LIMIT``
  (default: the ASYNC_ELASTIC staleness bound, 3) — some worker has
  been dropped from so many consecutive rounds its contributions can
  no longer be merged
"""

from __future__ import annotations

import math
import os
from typing import Dict, List, Optional

from deeplearning4j_tpu.observe.registry import (
    MetricsRegistry,
    default_registry,
)

DEFAULT_RECOMPILE_STORM = 8
DEFAULT_DIVERGENCE_THRESHOLD = 2.0
DEFAULT_STALENESS_LIMIT = 3.0


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _labels_str(key) -> str:
    return ",".join(f"{k}={v}" for k, v in key) or "-"


def health_status(registry: Optional[MetricsRegistry] = None) -> Dict:
    """``{"status": "ok"|"degraded", "reasons": [...]}`` from the
    registry's current series. Pure read: missing metrics (nothing
    trained yet) are healthy, and the check never creates series."""
    r = registry if registry is not None else default_registry()
    reasons: List[str] = []

    m = r.get_metric("dl4j_nonfinite_values_total")
    if m is not None:
        for key, v in sorted(m.series().items()):
            if v > 0:
                reasons.append(
                    f"nonfinite: {v:g} non-finite gradient/loss values "
                    f"({_labels_str(key)})")

    storm = _env_float("DL4J_RECOMPILE_STORM", DEFAULT_RECOMPILE_STORM)
    m = r.get_metric("dl4j_recompiles_total")
    if m is not None:
        for key, v in sorted(m.series().items()):
            if v >= storm:
                reasons.append(
                    f"recompile_storm: {v:g} recompiles >= threshold "
                    f"{storm:g} ({_labels_str(key)})")

    thresh = _env_float("DL4J_DIVERGENCE_THRESHOLD",
                        DEFAULT_DIVERGENCE_THRESHOLD)
    m = r.get_metric("dl4j_replica_divergence")
    if m is not None:
        for key, v in sorted(m.series().items()):
            if math.isnan(v) or v > thresh:
                reasons.append(
                    f"replica_divergence: spread {v:g} > threshold "
                    f"{thresh:g} ({_labels_str(key)})")

    m = r.get_metric("dl4j_elastic_peer_loss_total")
    if m is not None:
        for key, v in sorted(m.series().items()):
            if v > 0:
                reasons.append(
                    f"peer_loss: {v:g} dead-peer event(s) — emergency "
                    "checkpoint + PEER_LOSS marker written, relaunch to "
                    f"resume ({_labels_str(key)})")

    stale_limit = _env_float("DL4J_ELASTIC_STALENESS_LIMIT",
                             DEFAULT_STALENESS_LIMIT)
    m = r.get_metric("dl4j_elastic_staleness")
    if m is not None:
        for key, v in sorted(m.series().items()):
            if v > stale_limit:
                reasons.append(
                    f"elastic_staleness: a worker has drifted {v:g} "
                    f"rounds > limit {stale_limit:g} — its updates are "
                    f"being discarded ({_labels_str(key)})")

    return {"status": "degraded" if reasons else "ok",
            "reasons": reasons}
