"""Flight recorder: always-on black-box crash forensics for training.

Analog of the reference's ``CrashReportingUtil`` (SURVEY §2.12 — an OOM
during fit writes a full memory/config "crash dump" to disk, on by
default) extended with the device-telemetry machinery this port already
carries: when a run dies, the evidence is the last N decoded ring-buffer
rows, the in-step per-layer histograms, the per-replica rows, the memory
reports and the span/recompile tails — all of which exist WITHOUT extra
steady-state cost because they ride the one-fetch telemetry design
(observe/telemetry.py).

Triggers (the "terminal events" of a fit/solver run):

- **nonfinite** — a flushed telemetry row reports ``nonfinite_count > 0``
  or a non-finite loss, or a per-replica row carries a non-finite value
  (``poll()``, called from the models' per-dispatch epilogue)
- **oom** — an uncaught exception whose message carries XLA's
  ``RESOURCE_EXHAUSTED`` / out-of-memory signature
- **exception** — any other uncaught exception escaping ``fit``

Each trigger writes ONE self-contained post-mortem directory and
announces it through the attached listeners' ``on_crash_dump`` hook. A
reason dumps at most once per recorder (a NaN storm must not write a
thousand dumps), everything inside the recorder is best-effort
(``record_crash`` never raises — the crash handler must not mask the
crash), and the whole feature can be disabled with
``DL4J_CRASH_DUMPS=0``.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import tempfile
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

log = logging.getLogger(__name__)

_ENV_DISABLE = "DL4J_CRASH_DUMPS"
_ENV_DIR = "DL4J_CRASH_DUMP_DIR"

# substrings identifying an accelerator OOM in XLA/jaxlib exception text
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM ", "Resource exhausted", "failed to allocate")


def crash_dumps_enabled() -> bool:
    return os.environ.get(_ENV_DISABLE, "1") != "0"


class FlightRecorder:
    """Black-box recorder bound to nothing until a terminal event fires.

    Zero steady-state cost beyond a length check per telemetry flush:
    ``poll()`` inspects only records the collector has ALREADY decoded on
    host, so arming the recorder performs no device fetches of its own —
    the fetch-counting acceptance test runs with the recorder armed.
    """

    def __init__(self, dump_dir: Optional[str] = None, last_n: int = 100,
                 enabled: Optional[bool] = None, max_dumps: int = 4):
        self.dump_dir = dump_dir or os.environ.get(_ENV_DIR) or \
            os.path.join(tempfile.gettempdir(), "dl4j_crash_dumps")
        self.last_n = int(last_n)
        self.enabled = crash_dumps_enabled() if enabled is None \
            else bool(enabled)
        self.max_dumps = int(max_dumps)
        self.dumps: List[str] = []          # paths written, in order
        self._notes: Dict[str, Any] = {}    # breadcrumbs (see note())
        self._dumped_reasons: set = set()
        self._seen_records = 0
        self._seen_replica = 0
        self._lock = threading.Lock()

    # ---- steady-state hook ----------------------------------------------
    def poll(self, model) -> Optional[str]:
        """Scan telemetry records decoded since the last poll for
        non-finite evidence; write a dump on the first hit. Called from
        the per-dispatch epilogue — returns fast (two length checks) when
        nothing flushed."""
        if not self.enabled:
            return None
        tel = getattr(model, "telemetry", None)
        if tel is None:
            return None
        hit = False
        n = len(tel.history)
        if n > self._seen_records:
            for rec in tel.history[self._seen_records:n]:
                if (rec.get("nonfinite_count", 0.0) > 0
                        or not _finite(rec.get("loss"))):
                    hit = True
                    break
            self._seen_records = n
        rn = len(getattr(tel, "replica_history", ()))
        if not hit and rn > self._seen_replica:
            for rec in tel.replica_history[self._seen_replica:rn]:
                for key, vals in rec.items():
                    if key != "iteration" and isinstance(vals, list) \
                            and not all(_finite(v) for v in vals):
                        hit = True
                        break
                if hit:
                    break
        self._seen_replica = max(self._seen_replica, rn)
        if hit:
            return self.record_crash(model, reason="nonfinite")
        return None

    def note(self, key: str, value: Any):
        """Attach a breadcrumb that rides along in ``context.json`` of
        every FUTURE dump (last write per key wins). For non-fatal
        events worth having in the post-mortem — e.g. the serving
        engine records WHY a persisted quantized AOT cache was rejected
        (the fingerprint field that diverged), so a later crash dump
        explains the cold start that preceded it. Never raises."""
        try:
            with self._lock:
                self._notes[str(key)] = value
        except Exception:
            pass

    # ---- terminal events ------------------------------------------------
    def record_crash(self, model, reason: Optional[str] = None,
                     exc: Optional[BaseException] = None,
                     extra: Optional[Dict[str, Any]] = None
                     ) -> Optional[str]:
        """Write one post-mortem directory. Never raises — a crash
        handler that crashes masks the original failure.

        ``extra`` is caller-supplied structured context (e.g. the
        collective watchdog's dead-peer ranks and heartbeat ages) and
        lands in a ``context.json`` section of the dump.
        """
        try:
            if not self.enabled:
                return None
            if reason is None:
                reason = _classify(exc)
            with self._lock:
                if reason in self._dumped_reasons or \
                        len(self.dumps) >= self.max_dumps:
                    return None
                self._dumped_reasons.add(reason)
            path = self._write_dump(model, reason, exc, extra)
            if path is not None:
                self.dumps.append(path)
                log.error("flight recorder: %s — post-mortem dump "
                          "written to %s", reason, path)
                for lst in list(getattr(model, "listeners", ())):
                    try:
                        hook = getattr(lst, "on_crash_dump", None)
                        if hook is not None:
                            hook(model, path, reason)
                    except Exception:
                        pass        # a listener bug must not mask the dump
            return path
        except Exception:
            log.exception("flight recorder failed to write a crash dump")
            return None

    # ---- dump assembly --------------------------------------------------
    def _write_dump(self, model, reason: str,
                    exc: Optional[BaseException],
                    extra: Optional[Dict[str, Any]] = None
                    ) -> Optional[str]:
        stamp = time.strftime("%Y%m%d_%H%M%S")
        path = os.path.join(self.dump_dir,
                            f"dump_{reason}_{stamp}_{os.getpid()}")
        os.makedirs(path, exist_ok=True)

        sections: Dict[str, bool] = {}

        def write(name: str, obj: Any) -> bool:
            try:
                with open(os.path.join(path, name), "w") as f:
                    json.dump(obj, f, indent=1, default=str)
                sections[name] = True
                return True
            except Exception:
                log.debug("flight recorder: section %s failed", name,
                          exc_info=True)
                sections[name] = False
                return False

        tel = getattr(model, "telemetry", None)
        if tel is not None:
            write("telemetry.json", {
                "metric_names": list(getattr(tel.spec, "metric_names",
                                             ()) if tel.spec else ()),
                "flush_interval": tel.flush_interval,
                "fetch_count": tel.fetch_count,
                "dropped_rows": tel.dropped_rows,
                "records": tel.history[-self.last_n:],
                "replica_metrics": list(getattr(tel.spec,
                                                "replica_metrics", ())
                                        if tel.spec else ()),
                "replica_records": tel.replica_history[-self.last_n:],
            })
            if tel.hist_history:
                write("histograms.json", {
                    "bins": tel.hist_bins,
                    "interval": tel.hist_interval,
                    "records": tel.hist_history[-self.hist_tail:],
                })
        write("memory.json", self._memory_section(model, reason))
        wd = getattr(model, "recompile_watchdog", None)
        if wd is not None:
            write("recompiles.json", {
                "count": wd.count(),
                "events": [{"step": e["step"],
                            "signature": repr(e["signature"])}
                           for e in wd.events[-self.last_n:]],
            })
        tracer = getattr(model, "tracer", None)
        if tracer is not None and getattr(tracer, "enabled", False):
            trace = tracer.to_chrome_trace()
            trace["traceEvents"] = trace["traceEvents"][-500:]
            write("spans.json", trace)
        with self._lock:
            context = dict(self._notes)
        if extra:
            context.update(extra)
        if context:
            write("context.json", context)
        write("environment.json", self._environment_section(model))
        self._write_report(path, model, reason, exc, sections)
        return path

    # hist tail kept small: each record is n_layers * 3 * bins floats
    hist_tail = 8

    def _memory_section(self, model, reason: str) -> Dict:
        """Analytic NetworkMemoryReport + live device watermarks, plus
        XLA's buffer-assignment stats. The XLA analysis compiles an
        executable — skipped for OOM dumps, where another compile against
        a full device would turn the post-mortem into a second crash."""
        out: Dict[str, Any] = {}
        try:
            import jax
            devs = []
            for d in jax.devices():
                entry = {"id": d.id, "platform": d.platform,
                         "kind": getattr(d, "device_kind", "?")}
                try:
                    stats = d.memory_stats()
                    if stats:
                        entry["bytes_in_use"] = stats.get("bytes_in_use")
                        entry["peak_bytes_in_use"] = stats.get(
                            "peak_bytes_in_use")
                        entry["bytes_limit"] = stats.get("bytes_limit")
                except Exception:
                    pass
                devs.append(entry)
            out["devices"] = devs
        except Exception:
            pass
        try:
            from deeplearning4j_tpu.nn.memory import memory_report
            conf = getattr(model, "conf", None)
            if conf is not None and hasattr(conf, "layers"):
                out["analytic"] = json.loads(
                    memory_report(conf, type(model).__name__).to_json())
        except Exception:
            pass
        if reason != "oom":
            try:
                from deeplearning4j_tpu.nn.memory import (
                    xla_memory_analysis)
                out["xla"] = xla_memory_analysis(model, train=True)
            except Exception:
                pass
        return out

    def _environment_section(self, model) -> Dict:
        out: Dict[str, Any] = {
            "python": sys.version,
            "argv": sys.argv,
            "model_class": type(model).__name__,
        }
        try:
            import jax
            out["jax_version"] = jax.__version__
            out["backend"] = jax.default_backend()
            out["device_count"] = jax.device_count()
            out["process_index"] = jax.process_index()
        except Exception:
            pass
        try:
            out["num_params"] = int(model.num_params())
            out["layer_names"] = list(getattr(model, "layer_names", ()))
        except Exception:
            pass
        try:
            conf = getattr(model, "conf", None)
            if conf is not None and hasattr(conf, "to_json"):
                out["model_config"] = json.loads(conf.to_json())
        except Exception:
            pass
        out["env"] = {k: v for k, v in sorted(os.environ.items())
                      if k.startswith(("JAX_", "XLA_", "DL4J_", "TPU_",
                                       "LIBTPU_"))}
        return out

    def _write_report(self, path: str, model, reason: str,
                      exc: Optional[BaseException],
                      sections: Dict[str, bool]):
        """Human entry point (the CrashReportingUtil txt analog):
        report.md summarizes the event and indexes the JSON sections."""
        lines = [f"# Training post-mortem: {reason}", "",
                 f"- written: {time.strftime('%Y-%m-%d %H:%M:%S')}",
                 f"- model: {type(model).__name__}",
                 f"- pid: {os.getpid()}"]
        try:
            it = getattr(model, "_host_iteration", None)
            if it is not None:
                lines.append(f"- host iteration: {it}")
        except Exception:
            pass
        tel = getattr(model, "telemetry", None)
        if tel is not None and tel.last_record() is not None:
            last = tel.last_record()
            lines.append(f"- last flushed row: iteration "
                         f"{last.get('iteration')}, loss "
                         f"{last.get('loss')}, grad_norm "
                         f"{last.get('grad_norm')}, nonfinite_count "
                         f"{last.get('nonfinite_count')}")
        if exc is not None:
            lines += ["", "## Exception", "", "```",
                      "".join(traceback.format_exception(
                          type(exc), exc, exc.__traceback__))[-8000:],
                      "```"]
        lines += ["", "## Sections", ""]
        for name, ok in sorted(sections.items()):
            lines.append(f"- `{name}`: "
                         f"{'written' if ok else 'FAILED'}")
        lines += ["", "Disable these dumps with DL4J_CRASH_DUMPS=0; "
                  f"relocate them with {_ENV_DIR}=<dir>.", ""]
        try:
            with open(os.path.join(path, "report.md"), "w") as f:
                f.write("\n".join(lines))
        except Exception:
            pass


def _finite(v) -> bool:
    try:
        import math
        return v is None or math.isfinite(v)
    except TypeError:
        return True


def _classify(exc: Optional[BaseException]) -> str:
    if exc is None:
        return "exception"
    text = f"{type(exc).__name__}: {exc}"
    if any(m in text for m in _OOM_MARKERS):
        return "oom"
    return "exception"


_default: Optional[FlightRecorder] = None
_default_lock = threading.Lock()


def default_flight_recorder() -> Optional[FlightRecorder]:
    """The process-wide always-on recorder every model polls unless one
    was attached explicitly — or None when DL4J_CRASH_DUMPS=0."""
    if not crash_dumps_enabled():
        return None
    global _default
    with _default_lock:
        if _default is None:
            _default = FlightRecorder()
        return _default
