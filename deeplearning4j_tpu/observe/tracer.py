"""Host-side span tracer with Chrome/Perfetto trace JSON export.

Records named wall-clock spans around the training loop's phases (etl,
host→device transfer, dispatch, telemetry flush, eval, checkpoint) and
writes the Chrome Trace Event Format — load the file at
https://ui.perfetto.dev or chrome://tracing. When
``use_jax_profiler=True`` each span also opens a
``jax.profiler.TraceAnnotation`` so the host spans line up against
device lanes in a jax.profiler capture.

Disabled tracers are free: ``span()`` short-circuits before touching the
clock, so the default NULL_TRACER can stay wired into every fit loop.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import List, Optional


class SpanTracer:
    def __init__(self, enabled: bool = True,
                 use_jax_profiler: bool = False,
                 max_events: int = 200_000):
        self.enabled = enabled
        self.use_jax_profiler = use_jax_profiler
        self.max_events = max_events
        self._events: List[dict] = []
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._dropped = 0

    # ---- recording ------------------------------------------------------
    @contextmanager
    def span(self, name: str, cat: str = "train", **args):
        if not self.enabled:
            yield
            return
        ann = None
        if self.use_jax_profiler:
            try:
                import jax.profiler
                ann = jax.profiler.TraceAnnotation(name)
                ann.__enter__()
            except Exception:
                ann = None
        start = time.perf_counter()
        try:
            yield
        finally:
            end = time.perf_counter()
            if ann is not None:
                ann.__exit__(None, None, None)
            self.add_span(name, start, end, cat=cat, **args)

    def add_span(self, name: str, start_s: float, end_s: float,
                 cat: str = "train", **args):
        """Record a span retroactively from measured endpoints (the fit
        loop already times ETL windows; re-measuring would skew them)."""
        if not self.enabled:
            return
        ev = {
            "name": name, "cat": cat, "ph": "X",
            "ts": (start_s - self._t0) * 1e6,       # µs, trace-relative
            "dur": max(0.0, (end_s - start_s) * 1e6),
            "pid": os.getpid(), "tid": threading.get_ident(),
        }
        if args:
            ev["args"] = args
        with self._lock:
            if len(self._events) >= self.max_events:
                self._dropped += 1
                return
            self._events.append(ev)

    def instant(self, name: str, cat: str = "train", **args):
        """Zero-duration marker (e.g. a recompile sighting)."""
        if not self.enabled:
            return
        now = time.perf_counter()
        ev = {"name": name, "cat": cat, "ph": "i", "s": "t",
              "ts": (now - self._t0) * 1e6,
              "pid": os.getpid(), "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        with self._lock:
            if len(self._events) < self.max_events:
                self._events.append(ev)

    # ---- export ---------------------------------------------------------
    @property
    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    @property
    def dropped_events(self) -> int:
        return self._dropped

    def clear(self):
        with self._lock:
            self._events.clear()
            self._dropped = 0

    def to_chrome_trace(self) -> dict:
        return {"traceEvents": self.events, "displayTimeUnit": "ms",
                "otherData": {"tracer": "deeplearning4j_tpu.observe",
                              "dropped_events": self._dropped}}

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path


class _NullTracer(SpanTracer):
    """Shared always-off tracer; wiring it in costs one ``if``."""

    def __init__(self):
        super().__init__(enabled=False)


NULL_TRACER = _NullTracer()


def get_tracer(model=None) -> SpanTracer:
    """The tracer attached to a model, else the shared no-op."""
    t: Optional[SpanTracer] = getattr(model, "tracer", None)
    return t if t is not None else NULL_TRACER
