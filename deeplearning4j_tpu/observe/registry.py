"""Process-wide metrics registry with Prometheus text exposition.

The scrape side of the telemetry subsystem: collectors, watchdogs and
listeners publish here; ``ui/server.py`` renders ``render()`` at
``GET /metrics``. Dependency-free by design (the container has no
prometheus_client) — the text exposition format is simple enough to emit
directly: https://prometheus.io/docs/instrumenting/exposition_formats/.

Thread-safe: training threads publish while the HTTP server thread
scrapes.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Dict, Optional, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _escape_label_value(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _format_value(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_text: str, lock: threading.Lock):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name: {name!r}")
        self.name = name
        self.help = help_text
        self._lock = lock
        # label tuple (sorted (k, v) pairs) -> value
        self._series: Dict[Tuple[Tuple[str, str], ...], float] = {}

    @staticmethod
    def _key(labels: dict) -> Tuple[Tuple[str, str], ...]:
        for k in labels:
            if not _LABEL_RE.match(k):
                raise ValueError(f"invalid label name: {k!r}")
        return tuple(sorted((k, str(v)) for k, v in labels.items()))

    def get(self, **labels) -> Optional[float]:
        with self._lock:
            return self._series.get(self._key(labels))

    def series(self) -> Dict[Tuple[Tuple[str, str], ...], float]:
        """Snapshot of every label set -> value (health checks iterate
        this; the render path keeps its own copy-under-lock)."""
        with self._lock:
            return dict(self._series)

    def render(self) -> str:
        with self._lock:
            series = dict(self._series)
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        for key, value in sorted(series.items()):
            if key:
                lbl = ",".join(f'{k}="{_escape_label_value(v)}"'
                               for k, v in key)
                lines.append(f"{self.name}{{{lbl}}} {_format_value(value)}")
            else:
                lines.append(f"{self.name} {_format_value(value)}")
        return "\n".join(lines)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels):
        with self._lock:
            self._series[self._key(labels)] = float(value)


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, **labels):
        if value < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + float(value)


class MetricsRegistry:
    """Create-or-get metric handles; render the whole registry as
    Prometheus text. ``counter``/``gauge`` are idempotent per name so
    independent components can share a series."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help_text: str) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_text, threading.Lock())
                self._metrics[name] = m
        if not isinstance(m, cls):
            raise TypeError(f"metric {name!r} already registered as "
                            f"{m.kind}")
        return m

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get_or_create(Counter, name, help_text)

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help_text)

    def get_metric(self, name: str) -> Optional[_Metric]:
        """The registered metric, or None — read-only lookups (health
        checks) must not create empty series as a side effect."""
        with self._lock:
            return self._metrics.get(name)

    def render(self) -> str:
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        chunks = [m.render() for m in metrics]
        return "\n".join(chunks) + ("\n" if chunks else "")


_default: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    """The process-wide registry served at ``/metrics``."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry()
        return _default
