"""Device-resident telemetry: in-step metrics, tracing, Prometheus export.

The reference's observability stack (BaseStatsListener + UI, SURVEY
§2.12/§5.5) polls the JVM from the host; porting that shape verbatim
makes every score/statistic its own device→host sync, stalling the TPU
pipeline. This package inverts it:

- ``telemetry``: a metric spec (loss, global grad-norm, per-layer
  update:param ratio, non-finite counts) compiled INTO the jitted train
  step, accumulated in a fixed-size on-device ring buffer and flushed to
  host every N steps in ONE device fetch — steady-state training
  performs zero extra syncs.
- ``tracer``: host-side span tracer (ETL, host→device transfer,
  dispatch, flush, eval, checkpoint) exporting Chrome/Perfetto trace
  JSON, optionally annotating the jax.profiler timeline.
- ``recompile``: watchdog recording each new (shape, dtype) signature a
  compiled step sees — silent retrace storms become a counter.
- ``registry``: process-wide metrics registry rendered as Prometheus
  text exposition at ``/metrics`` on the UI server.
- ``flight_recorder``: always-on black-box crash forensics — on a
  terminal event (non-finite at flush, OOM, uncaught exception in fit)
  the last-N telemetry rows, in-step histograms, memory reports, span
  and recompile tails are written as one post-mortem dump directory.
- ``health``: degradation verdict over the registry's series backing
  the UI server's ``/healthz`` (503 on nonfinite / recompile storm /
  replica divergence).
"""

from deeplearning4j_tpu.observe.flight_recorder import (
    FlightRecorder,
    crash_dumps_enabled,
    default_flight_recorder,
)
from deeplearning4j_tpu.observe.health import health_status
from deeplearning4j_tpu.observe.latency import LatencyRing
from deeplearning4j_tpu.observe.registry import (
    MetricsRegistry,
    default_registry,
)
from deeplearning4j_tpu.observe.recompile import RecompileWatchdog
from deeplearning4j_tpu.observe.telemetry import (
    HistRing,
    ReplicaRing,
    TelemetryBuffer,
    TelemetryCollector,
    TelemetrySpec,
)
from deeplearning4j_tpu.observe.tracer import NULL_TRACER, SpanTracer

__all__ = [
    "MetricsRegistry",
    "default_registry",
    "FlightRecorder",
    "default_flight_recorder",
    "crash_dumps_enabled",
    "health_status",
    "LatencyRing",
    "RecompileWatchdog",
    "HistRing",
    "ReplicaRing",
    "TelemetryBuffer",
    "TelemetryCollector",
    "TelemetrySpec",
    "SpanTracer",
    "NULL_TRACER",
]
