"""In-step device telemetry: metric spec + on-device ring buffer.

The reference's BaseStatsListener reads score and parameter statistics
from the host after every iteration — each read is a device→host sync
that drains the dispatch pipeline (SURVEY §2.12). Here the metrics are
computed INSIDE the jitted train step, where the loss/grads/updates
already live in registers, and appended to a fixed-size on-device ring
buffer carried in the TrainState. The host fetches the whole buffer in
ONE transfer every ``flush_interval`` steps; between flushes, training
performs zero telemetry-induced syncs.

Metric rows are f32: loss, global grad-norm, non-finite count across
gradients+loss, and (optionally) one update:param mean-magnitude ratio
per layer. Iterations ride in a parallel int32 ring so rows stay exact
past 2^24 steps.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.observe.registry import (
    MetricsRegistry,
    default_registry,
)

log = logging.getLogger(__name__)

# metrics always present, in row order, ahead of per-layer ratios
BASE_METRICS = ("loss", "grad_norm", "nonfinite_count")


class TelemetryBuffer(NamedTuple):
    """Device-resident ring: ``rows[i % capacity]`` is the metric row of
    the i-th recorded step; ``count`` is the total rows ever written."""
    rows: jnp.ndarray    # f32[capacity, n_metrics]
    iters: jnp.ndarray   # i32[capacity]
    count: jnp.ndarray   # i32 scalar


def has_buffer(telemetry) -> bool:
    """True when a TrainState.telemetry slot actually carries a ring
    buffer (the slot defaults to an empty pytree)."""
    return isinstance(telemetry, TelemetryBuffer)


class TelemetrySpec:
    """Compiled-in metric catalog: knows the row layout and how to append
    one row from inside the traced step."""

    def __init__(self, layer_names: Tuple[str, ...] = (),
                 capacity: int = 128, per_layer: bool = True):
        if capacity < 1:
            raise ValueError("telemetry capacity must be >= 1")
        self.capacity = int(capacity)
        self.per_layer = per_layer
        self.layer_names = tuple(layer_names) if per_layer else ()
        self.metric_names: Tuple[str, ...] = BASE_METRICS + tuple(
            f"update_ratio/{n}" for n in self.layer_names)

    def init(self) -> TelemetryBuffer:
        n = len(self.metric_names)
        return TelemetryBuffer(
            rows=jnp.zeros((self.capacity, n), jnp.float32),
            iters=jnp.full((self.capacity,), -1, jnp.int32),
            count=jnp.zeros((), jnp.int32))

    # ---- traced: runs inside the jitted train step ----------------------
    def record(self, buf: TelemetryBuffer, *, loss, grads, params,
               prev_params, iteration) -> TelemetryBuffer:
        """Append one metric row. All inputs are traced values already in
        flight inside the step — recording adds a handful of reductions
        and one dynamic row write, no host interaction.

        The update:param ratio is ``mean|new - prev| / mean|new|`` per
        layer over bounded prefix samples — computed from the parameter
        DELTA, not the optimizer's update tree: depending on the update
        tree would force XLA to materialize it as a buffer instead of
        fusing it into the parameter add (measured at ~8% step time on
        the CPU tier-1 path). The delta also folds in constraint
        projections, matching ui/stats.py's update-statistics convention.
        """
        gleaves = jax.tree_util.tree_leaves(grads)
        loss32 = loss.astype(jnp.float32)
        sumsq = sum(
            (jnp.sum(jnp.square(g.astype(jnp.float32))) for g in gleaves),
            jnp.zeros((), jnp.float32))
        gnorm = jnp.sqrt(sumsq)

        # The elementwise non-finite count is an O(params) pass that the
        # squared-norm already screens for free: any NaN/Inf gradient
        # element makes ``sumsq`` non-finite (squares are >= 0, so no
        # finite cancellation can produce NaN). Steady state takes the
        # zero branch; the full count only runs — and is exact — once
        # training has actually blown up.
        def _count_nonfinite():
            return sum(
                (jnp.sum(~jnp.isfinite(g)).astype(jnp.float32)
                 for g in gleaves), jnp.zeros((), jnp.float32))

        nonfinite = jax.lax.cond(
            jnp.isfinite(sumsq),
            lambda: jnp.zeros((), jnp.float32),
            _count_nonfinite) + (~jnp.isfinite(loss32)).astype(
            jnp.float32)
        vals = [loss32, gnorm, nonfinite]
        for name in self.layer_names:
            new = jax.tree_util.tree_leaves(_subtree(params, name))
            old = jax.tree_util.tree_leaves(_subtree(prev_params, name))
            if not new or len(new) != len(old):
                vals.append(jnp.zeros((), jnp.float32))
                continue
            umag = _mean_abs([n - o for n, o in
                              zip(_samples(new), _samples(old))])
            pmag = _mean_abs(_samples(new))
            vals.append(umag / (pmag + jnp.float32(1e-12)))
        row = jnp.stack(vals)
        idx = buf.count % self.capacity
        return TelemetryBuffer(
            rows=buf.rows.at[idx].set(row),
            iters=buf.iters.at[idx].set(iteration.astype(jnp.int32) + 1),
            count=buf.count + 1)


def _subtree(tree, key):
    if isinstance(tree, dict):
        return tree.get(key, {})
    return {}


# Per-leaf sample cap for the update:param ratio estimate. Full
# reductions over every parameter tensor measured +16% step time on the
# CPU tier-1 path (benchmarks/telemetry_overhead.py) — the ratio is a
# monitoring signal, so bound the work: tensors larger than the cap
# contribute a prefix sample (a 64Ki-element mean is statistically
# indistinguishable for health monitoring). Tensors at or under the cap
# are reduced exactly.
_MEAN_ABS_SAMPLE = 65536


def _samples(leaves):
    """Flattened bounded prefix of each leaf (static slice: no gather)."""
    out = []
    for l in leaves:
        flat = l.reshape(-1)
        if int(np.prod(l.shape)) > _MEAN_ABS_SAMPLE:
            flat = flat[:_MEAN_ABS_SAMPLE]
        out.append(flat)
    return out


def _mean_abs(leaves) -> jnp.ndarray:
    total = jnp.zeros((), jnp.float32)
    n = 0
    for l in leaves:
        total = total + jnp.sum(jnp.abs(l.astype(jnp.float32)))
        n += int(np.prod(l.shape))
    return total / jnp.float32(max(n, 1))


class TelemetryCollector:
    """Host side: owns the spec, decides when to flush, decodes rows, and
    publishes to the Prometheus registry.

    Attach with ``model.set_telemetry(TelemetryCollector(...))``; the
    model compiles the spec into its train step and calls ``on_step``
    after each dispatch. Every ``flush_interval`` recorded steps the
    collector performs exactly ONE device fetch (``fetch_count`` counts
    them — the property the acceptance test asserts). Listener-visible
    values (``last('loss')`` etc.) therefore lag up to one flush
    interval; that staleness is the price of a stall-free pipeline.
    """

    def __init__(self, flush_interval: int = 50,
                 capacity: Optional[int] = None, per_layer: bool = True,
                 registry: Optional[MetricsRegistry] = None,
                 session_id: str = "train"):
        if flush_interval < 1:
            raise ValueError("flush_interval must be >= 1")
        self.flush_interval = int(flush_interval)
        self.capacity = int(capacity) if capacity is not None else max(
            2 * self.flush_interval, 64)
        if self.capacity < self.flush_interval:
            raise ValueError(
                f"capacity {self.capacity} < flush_interval "
                f"{self.flush_interval}: rows would be overwritten "
                "before they are ever fetched")
        self.per_layer = per_layer
        self.session_id = session_id
        self.registry = registry if registry is not None else \
            default_registry()
        self.spec: Optional[TelemetrySpec] = None
        self.history: List[dict] = []
        self.fetch_count = 0
        self.dropped_rows = 0
        self._read_count = 0
        self._pending = 0
        self._last_flush_time: Optional[float] = None

    # ---- wiring ---------------------------------------------------------
    def spec_for(self, model) -> TelemetrySpec:
        """The spec is built once per collector, from the model's layer
        names — reusing one collector across models with different layers
        would mislabel rows, so it is rejected."""
        names = tuple(getattr(model, "layer_names", ()))
        if self.spec is None:
            self.spec = TelemetrySpec(names, capacity=self.capacity,
                                      per_layer=self.per_layer)
        elif self.per_layer and self.spec.layer_names != names:
            raise ValueError(
                "TelemetryCollector is already bound to layers "
                f"{self.spec.layer_names}; use a fresh collector for a "
                "model with different layers")
        return self.spec

    def ensure_buffer(self, train_state):
        """Attach the ring buffer into a TrainState that doesn't carry
        one yet (changes the pytree structure → one recompile, before the
        first monitored dispatch)."""
        if has_buffer(train_state.telemetry):
            return train_state
        if self.spec is None:
            raise RuntimeError("spec_for(model) must run before "
                               "ensure_buffer")
        if self._last_flush_time is None:
            self._last_flush_time = time.perf_counter()
        return train_state._replace(telemetry=self.spec.init())

    # ---- steady-state hook ----------------------------------------------
    def will_flush(self, steps: int = 1) -> bool:
        """Whether the next ``on_step(..., steps)`` will fetch."""
        return self._pending + int(steps) >= self.flush_interval

    def on_step(self, train_state, steps: int = 1):
        """Called after each dispatched train step (``steps`` > 1 for the
        scanned multi-step). Flushes when a full interval has
        accumulated; otherwise free — no device interaction."""
        self._pending += int(steps)
        if self._pending >= self.flush_interval:
            self.flush(train_state)

    def flush(self, train_state) -> List[dict]:
        """ONE device fetch: pull the whole ring + counters, decode every
        row not yet seen, publish the newest values to the registry.
        Returns the newly decoded records."""
        buf = train_state.telemetry
        if not has_buffer(buf):
            return []
        host = jax.device_get(buf)       # the single transfer
        self.fetch_count += 1
        self._pending = 0
        now = time.perf_counter()
        total = int(host.count)
        new = total - self._read_count
        if new <= 0:
            return []
        dropped = max(0, new - self.spec.capacity)
        if dropped:
            self.dropped_rows += dropped
            self.registry.counter(
                "dl4j_telemetry_dropped_rows_total",
                "ring rows overwritten before flush").inc(
                dropped, session=self.session_id)
            log.warning("telemetry ring overwrote %d rows before flush "
                        "(capacity %d); flush more often or grow the "
                        "ring", dropped, self.spec.capacity)
        records = []
        for j in range(self._read_count + dropped, total):
            idx = j % self.spec.capacity
            rec: Dict[str, Any] = {"iteration": int(host.iters[idx])}
            for m, name in enumerate(self.spec.metric_names):
                rec[name] = float(host.rows[idx, m])
            records.append(rec)
        self._read_count = total
        self.history.extend(records)
        self._publish(records, new, now)
        self._last_flush_time = now
        return records

    def _publish(self, records: List[dict], n_steps: int, now: float):
        r = self.registry
        s = self.session_id
        last = records[-1]
        r.gauge("dl4j_loss", "training loss (flushed from the device "
                "ring)").set(last["loss"], session=s)
        r.gauge("dl4j_grad_norm", "global gradient L2 norm").set(
            last["grad_norm"], session=s)
        r.gauge("dl4j_iteration", "latest flushed iteration").set(
            last["iteration"], session=s)
        nonfinite = sum(rec["nonfinite_count"] for rec in records)
        r.counter("dl4j_nonfinite_values_total", "non-finite values seen "
                  "in gradients/loss").inc(nonfinite, session=s)
        if self._last_flush_time is not None:
            dt = now - self._last_flush_time
            if dt > 0:
                r.gauge("dl4j_steps_per_second", "optimizer steps per "
                        "second over the last flush window").set(
                    n_steps / dt, session=s)
        r.counter("dl4j_telemetry_flushes_total", "device fetches "
                  "performed by the telemetry collector").inc(session=s)
        for name in self.spec.layer_names:
            r.gauge("dl4j_update_ratio", "mean |update| / mean |param| "
                    "per layer").set(last[f"update_ratio/{name}"],
                                     session=s, layer=name)

    # ---- read side ------------------------------------------------------
    def last_record(self) -> Optional[dict]:
        return self.history[-1] if self.history else None

    def last(self, metric: str) -> Optional[float]:
        rec = self.last_record()
        return None if rec is None else rec.get(metric)
