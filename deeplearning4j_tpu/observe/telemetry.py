"""In-step device telemetry: metric spec + on-device ring buffer.

The reference's BaseStatsListener reads score and parameter statistics
from the host after every iteration — each read is a device→host sync
that drains the dispatch pipeline (SURVEY §2.12). Here the metrics are
computed INSIDE the jitted train step, where the loss/grads/updates
already live in registers, and appended to a fixed-size on-device ring
buffer carried in the TrainState. The host fetches the whole buffer in
ONE transfer every ``flush_interval`` steps; between flushes, training
performs zero telemetry-induced syncs.

Metric rows are f32: loss, global grad-norm, non-finite count across
gradients+loss, and (optionally) one update:param mean-magnitude ratio
per layer. Iterations ride in a parallel int32 ring so rows stay exact
past 2^24 steps.
"""

from __future__ import annotations

import logging
import math
import time
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.observe.registry import (
    MetricsRegistry,
    default_registry,
)

log = logging.getLogger(__name__)

# metrics always present, in row order, ahead of per-layer ratios
BASE_METRICS = ("loss", "grad_norm", "nonfinite_count")

# histogram kinds, in storage order along the hist ring's third axis
HIST_KINDS = ("param", "grad", "update")


class HistRing(NamedTuple):
    """Device-resident histogram ring: ``counts[i % capacity]`` holds the
    fixed-bin per-layer param/grad/update histograms of the i-th recorded
    histogram step. Rides inside the TelemetryBuffer pytree so it is
    fetched in the SAME single device_get as the metric rows."""
    counts: jnp.ndarray   # f32[capacity, n_layers, len(HIST_KINDS), bins]
    ranges: jnp.ndarray   # f32[capacity, n_layers, len(HIST_KINDS), 2]
    iters: jnp.ndarray    # i32[capacity]
    count: jnp.ndarray    # i32 scalar


class ReplicaRing(NamedTuple):
    """Per-device rows from the parallel wrapper's step (loss/grad-norm
    per worker in AVERAGING mode, a param-norm fingerprint per replica in
    sync DP). Also part of the one-fetch TelemetryBuffer pytree."""
    rows: jnp.ndarray     # f32[capacity, n_workers, n_replica_metrics]
    iters: jnp.ndarray    # i32[capacity]
    count: jnp.ndarray    # i32 scalar


class TelemetryBuffer(NamedTuple):
    """Device-resident ring: ``rows[i % capacity]`` is the metric row of
    the i-th recorded step; ``count`` is the total rows ever written.
    ``hist`` and ``replica`` default to empty pytrees so 3-field
    constructions (and old checkpoints) keep working."""
    rows: jnp.ndarray    # f32[capacity, n_metrics]
    iters: jnp.ndarray   # i32[capacity]
    count: jnp.ndarray   # i32 scalar
    hist: Any = ()       # HistRing when histograms are enabled
    replica: Any = ()    # ReplicaRing when replica rows are enabled


def has_buffer(telemetry) -> bool:
    """True when a TrainState.telemetry slot actually carries a ring
    buffer (the slot defaults to an empty pytree)."""
    return isinstance(telemetry, TelemetryBuffer)


class TelemetrySpec:
    """Compiled-in metric catalog: knows the row layout and how to append
    one row from inside the traced step."""

    def __init__(self, layer_names: Tuple[str, ...] = (),
                 capacity: int = 128, per_layer: bool = True,
                 histograms: bool = False, hist_bins: int = 16,
                 hist_interval: int = 10, hist_capacity: int = 8,
                 replicas: int = 0,
                 replica_metrics: Tuple[str, ...] = ("loss", "grad_norm")):
        if capacity < 1:
            raise ValueError("telemetry capacity must be >= 1")
        if hist_bins < 2 or hist_capacity < 1 or hist_interval < 1:
            raise ValueError("histogram config must be positive "
                             "(bins >= 2)")
        self.capacity = int(capacity)
        self.per_layer = per_layer
        self.layer_names = tuple(layer_names) if per_layer else ()
        self.metric_names: Tuple[str, ...] = BASE_METRICS + tuple(
            f"update_ratio/{n}" for n in self.layer_names)
        # histograms need named layers to bucket by
        self.histograms = bool(histograms) and bool(self.layer_names)
        self.hist_bins = int(hist_bins)
        self.hist_interval = int(hist_interval)
        self.hist_capacity = int(hist_capacity)
        self.replicas = int(replicas)
        self.replica_metrics = tuple(replica_metrics)

    def init(self) -> TelemetryBuffer:
        n = len(self.metric_names)
        hist: Any = ()
        if self.histograms:
            nl, nk = len(self.layer_names), len(HIST_KINDS)
            hist = HistRing(
                counts=jnp.zeros((self.hist_capacity, nl, nk,
                                  self.hist_bins), jnp.float32),
                ranges=jnp.zeros((self.hist_capacity, nl, nk, 2),
                                 jnp.float32),
                iters=jnp.full((self.hist_capacity,), -1, jnp.int32),
                count=jnp.zeros((), jnp.int32))
        replica: Any = ()
        if self.replicas > 1:
            replica = ReplicaRing(
                rows=jnp.zeros((self.capacity, self.replicas,
                                len(self.replica_metrics)), jnp.float32),
                iters=jnp.full((self.capacity,), -1, jnp.int32),
                count=jnp.zeros((), jnp.int32))
        return TelemetryBuffer(
            rows=jnp.zeros((self.capacity, n), jnp.float32),
            iters=jnp.full((self.capacity,), -1, jnp.int32),
            count=jnp.zeros((), jnp.int32),
            hist=hist, replica=replica)

    # ---- traced: runs inside the jitted train step ----------------------
    def record(self, buf: TelemetryBuffer, *, loss, grads, params,
               prev_params, iteration) -> TelemetryBuffer:
        """Append one metric row. All inputs are traced values already in
        flight inside the step — recording adds a handful of reductions
        and one dynamic row write, no host interaction.

        The update:param ratio is ``mean|new - prev| / mean|new|`` per
        layer over bounded prefix samples — computed from the parameter
        DELTA, not the optimizer's update tree: depending on the update
        tree would force XLA to materialize it as a buffer instead of
        fusing it into the parameter add (measured at ~8% step time on
        the CPU tier-1 path). The delta also folds in constraint
        projections, matching ui/stats.py's update-statistics convention.
        """
        gleaves = jax.tree_util.tree_leaves(grads)
        loss32 = loss.astype(jnp.float32)
        sumsq = sum(
            (jnp.sum(jnp.square(g.astype(jnp.float32))) for g in gleaves),
            jnp.zeros((), jnp.float32))
        gnorm = jnp.sqrt(sumsq)

        # The elementwise non-finite count is an O(params) pass that the
        # squared-norm already screens for free: any NaN/Inf gradient
        # element makes ``sumsq`` non-finite (squares are >= 0, so no
        # finite cancellation can produce NaN). Steady state takes the
        # zero branch; the full count only runs — and is exact — once
        # training has actually blown up.
        def _count_nonfinite():
            return sum(
                (jnp.sum(~jnp.isfinite(g)).astype(jnp.float32)
                 for g in gleaves), jnp.zeros((), jnp.float32))

        nonfinite = jax.lax.cond(
            jnp.isfinite(sumsq),
            lambda: jnp.zeros((), jnp.float32),
            _count_nonfinite) + (~jnp.isfinite(loss32)).astype(
            jnp.float32)
        vals = [loss32, gnorm, nonfinite]
        for name in self.layer_names:
            new = jax.tree_util.tree_leaves(_subtree(params, name))
            old = jax.tree_util.tree_leaves(_subtree(prev_params, name))
            if not new or len(new) != len(old):
                vals.append(jnp.zeros((), jnp.float32))
                continue
            umag = _mean_abs([n - o for n, o in
                              zip(_samples(new), _samples(old))])
            pmag = _mean_abs(_samples(new))
            vals.append(umag / (pmag + jnp.float32(1e-12)))
        row = jnp.stack(vals)
        idx = buf.count % self.capacity
        new_buf = buf._replace(
            rows=buf.rows.at[idx].set(row),
            iters=buf.iters.at[idx].set(iteration.astype(jnp.int32) + 1),
            count=buf.count + 1)
        if self.histograms and isinstance(buf.hist, HistRing):
            new_buf = new_buf._replace(hist=self._record_hist(
                buf.hist, buf.count, nonfinite, grads=grads,
                params=params, prev_params=prev_params,
                iteration=iteration))
        return new_buf

    def _record_hist(self, hist: HistRing, step_count, nonfinite, *,
                     grads, params, prev_params, iteration) -> HistRing:
        """Fixed-bin per-layer param/grad/update histograms, written every
        ``hist_interval`` recorded steps — and unconditionally on a
        blown-up step (non-finite seen), so the post-mortem dump always
        carries the histograms of the step that died. The bucketing runs
        inside a ``lax.cond`` branch: amortized steady-state cost is the
        sampling slices plus one predicate."""
        samples = []
        for name in self.layer_names:
            p = jax.tree_util.tree_leaves(_subtree(params, name))
            o = jax.tree_util.tree_leaves(_subtree(prev_params, name))
            g = jax.tree_util.tree_leaves(_subtree(grads, name))
            ps = _concat_samples(p)
            gs = _concat_samples(g) if g else jnp.zeros((1,), jnp.float32)
            us = (ps - _concat_samples(o)
                  if o and len(o) == len(p) else
                  jnp.zeros_like(ps))
            samples.append((ps, gs, us))

        def _update(h: HistRing) -> HistRing:
            per_layer_counts, per_layer_ranges = [], []
            for ps, gs, us in samples:
                kc, kr = [], []
                for x in (ps, gs, us):
                    c, lo, hi = _hist_counts(x, self.hist_bins)
                    kc.append(c)
                    kr.append(jnp.stack([lo, hi]))
                per_layer_counts.append(jnp.stack(kc))
                per_layer_ranges.append(jnp.stack(kr))
            hidx = h.count % self.hist_capacity
            return HistRing(
                counts=h.counts.at[hidx].set(
                    jnp.stack(per_layer_counts)),
                ranges=h.ranges.at[hidx].set(
                    jnp.stack(per_layer_ranges)),
                iters=h.iters.at[hidx].set(
                    iteration.astype(jnp.int32) + 1),
                count=h.count + 1)

        due = (step_count % self.hist_interval == 0) | (nonfinite > 0)
        return jax.lax.cond(due, _update, lambda h: h, hist)

    def record_replica(self, buf: TelemetryBuffer, *, values,
                       iteration) -> TelemetryBuffer:
        """Append one per-device row (``values``: f32[n_workers,
        n_replica_metrics], identical on every device — e.g. the result
        of an ``all_gather``). Traced; called from the parallel wrapper's
        step function."""
        rep = buf.replica
        if not isinstance(rep, ReplicaRing):
            return buf
        idx = rep.count % self.capacity
        return buf._replace(replica=ReplicaRing(
            rows=rep.rows.at[idx].set(values.astype(jnp.float32)),
            iters=rep.iters.at[idx].set(iteration.astype(jnp.int32) + 1),
            count=rep.count + 1))


def _subtree(tree, key):
    if isinstance(tree, dict):
        return tree.get(key, {})
    return {}


# Per-leaf sample cap for the update:param ratio estimate. Full
# reductions over every parameter tensor measured +16% step time on the
# CPU tier-1 path (benchmarks/telemetry_overhead.py) — the ratio is a
# monitoring signal, so bound the work: tensors larger than the cap
# contribute a prefix sample (a 64Ki-element mean is statistically
# indistinguishable for health monitoring). Tensors at or under the cap
# are reduced exactly.
_MEAN_ABS_SAMPLE = 65536


def _samples(leaves):
    """Flattened bounded prefix of each leaf (static slice: no gather)."""
    out = []
    for l in leaves:
        flat = l.reshape(-1)
        if int(np.prod(l.shape)) > _MEAN_ABS_SAMPLE:
            flat = flat[:_MEAN_ABS_SAMPLE]
        out.append(flat)
    return out


def _mean_abs(leaves) -> jnp.ndarray:
    total = jnp.zeros((), jnp.float32)
    n = 0
    for l in leaves:
        total = total + jnp.sum(jnp.abs(l.astype(jnp.float32)))
        n += int(np.prod(l.shape))
    return total / jnp.float32(max(n, 1))


# Histograms use a tighter per-leaf sample cap than the ratio estimate:
# the scatter-add bucketing is a gather-heavy pass, and a 16Ki sample per
# tensor is ample for a 16-bin shape signal.
_HIST_SAMPLE = 16384


def _concat_samples(leaves) -> jnp.ndarray:
    """One flat f32 vector of bounded prefix samples over the leaves."""
    flat = [l.reshape(-1)[:_HIST_SAMPLE].astype(jnp.float32)
            for l in leaves]
    if not flat:
        return jnp.zeros((1,), jnp.float32)
    return jnp.concatenate(flat) if len(flat) > 1 else flat[0]


def _hist_counts(x: jnp.ndarray, bins: int):
    """Fixed-bin histogram of ``x``: (counts[bins], min, max). Non-finite
    elements are zeroed before bucketing (the ``nonfinite_count`` row
    already counts them exactly; a NaN range would poison every bin)."""
    x = jnp.where(jnp.isfinite(x), x, jnp.zeros_like(x))
    lo = jnp.min(x)
    hi = jnp.max(x)
    span = jnp.maximum(hi - lo, jnp.float32(1e-30))
    idx = jnp.clip(((x - lo) / span * bins).astype(jnp.int32), 0, bins - 1)
    counts = jnp.zeros((bins,), jnp.float32).at[idx].add(1.0)
    return counts, lo, hi


class TelemetryCollector:
    """Host side: owns the spec, decides when to flush, decodes rows, and
    publishes to the Prometheus registry.

    Attach with ``model.set_telemetry(TelemetryCollector(...))``; the
    model compiles the spec into its train step and calls ``on_step``
    after each dispatch. Every ``flush_interval`` recorded steps the
    collector performs exactly ONE device fetch (``fetch_count`` counts
    them — the property the acceptance test asserts). Listener-visible
    values (``last('loss')`` etc.) therefore lag up to one flush
    interval; that staleness is the price of a stall-free pipeline.
    """

    def __init__(self, flush_interval: int = 50,
                 capacity: Optional[int] = None, per_layer: bool = True,
                 registry: Optional[MetricsRegistry] = None,
                 session_id: str = "train",
                 histograms: bool = False, hist_bins: int = 16,
                 hist_interval: int = 10, hist_capacity: int = 8):
        if flush_interval < 1:
            raise ValueError("flush_interval must be >= 1")
        self.flush_interval = int(flush_interval)
        self.capacity = int(capacity) if capacity is not None else max(
            2 * self.flush_interval, 64)
        if self.capacity < self.flush_interval:
            raise ValueError(
                f"capacity {self.capacity} < flush_interval "
                f"{self.flush_interval}: rows would be overwritten "
                "before they are ever fetched")
        self.per_layer = per_layer
        self.session_id = session_id
        self.registry = registry if registry is not None else \
            default_registry()
        self.histograms = bool(histograms)
        self.hist_bins = int(hist_bins)
        self.hist_interval = int(hist_interval)
        self.hist_capacity = int(hist_capacity)
        self.spec: Optional[TelemetrySpec] = None
        self.history: List[dict] = []
        self.hist_history: List[dict] = []
        self.replica_history: List[dict] = []
        self.fetch_count = 0
        self.dropped_rows = 0
        self._read_count = 0
        self._hist_read = 0
        self._replica_read = 0
        self._pending = 0
        self._last_flush_time: Optional[float] = None

    # ---- wiring ---------------------------------------------------------
    def spec_for(self, model) -> TelemetrySpec:
        """The spec is built once per collector, from the model's layer
        names — reusing one collector across models with different layers
        would mislabel rows, so it is rejected."""
        names = tuple(getattr(model, "layer_names", ()))
        if self.spec is None:
            self.spec = TelemetrySpec(
                names, capacity=self.capacity, per_layer=self.per_layer,
                histograms=self.histograms, hist_bins=self.hist_bins,
                hist_interval=self.hist_interval,
                hist_capacity=self.hist_capacity)
        elif self.per_layer and self.spec.layer_names != names:
            raise ValueError(
                "TelemetryCollector is already bound to layers "
                f"{self.spec.layer_names}; use a fresh collector for a "
                "model with different layers")
        return self.spec

    def enable_replicas(self, n_workers: int,
                        metrics: Tuple[str, ...] = ("loss", "grad_norm")
                        ) -> bool:
        """Turn on the per-device row ring (the parallel wrapper calls
        this before its first dispatch). Returns True when the spec
        changed — the caller must then re-init any existing buffer so the
        new pytree slot exists."""
        if self.spec is None:
            raise RuntimeError("spec_for(model) must run before "
                               "enable_replicas")
        n = int(n_workers)
        metrics = tuple(metrics)
        changed = (self.spec.replicas != n
                   or self.spec.replica_metrics != metrics)
        self.spec.replicas = n
        self.spec.replica_metrics = metrics
        return changed

    def rebind_buffer(self, train_state):
        """Replace the buffer after a spec change (``enable_replicas``
        altered the pytree): flush whatever the old ring still holds,
        re-init to the new layout and reset the read cursors. One extra
        fetch + one recompile, both before the next monitored dispatch."""
        if self.spec is None:
            raise RuntimeError("spec_for(model) must run before "
                               "rebind_buffer")
        if has_buffer(train_state.telemetry):
            self.flush(train_state)
        self._read_count = 0
        self._hist_read = 0
        self._replica_read = 0
        self._pending = 0
        if self._last_flush_time is None:
            self._last_flush_time = time.perf_counter()
        return train_state._replace(telemetry=self.spec.init())

    def ensure_buffer(self, train_state):
        """Attach the ring buffer into a TrainState that doesn't carry
        one yet (changes the pytree structure → one recompile, before the
        first monitored dispatch)."""
        if has_buffer(train_state.telemetry):
            return train_state
        if self.spec is None:
            raise RuntimeError("spec_for(model) must run before "
                               "ensure_buffer")
        if self._last_flush_time is None:
            self._last_flush_time = time.perf_counter()
        return train_state._replace(telemetry=self.spec.init())

    # ---- steady-state hook ----------------------------------------------
    def will_flush(self, steps: int = 1) -> bool:
        """Whether the next ``on_step(..., steps)`` will fetch."""
        return self._pending + int(steps) >= self.flush_interval

    def on_step(self, train_state, steps: int = 1):
        """Called after each dispatched train step (``steps`` > 1 for the
        scanned multi-step). Flushes when a full interval has
        accumulated; otherwise free — no device interaction."""
        self._pending += int(steps)  # graftlint: disable=release-discipline: flush-interval accumulator reset by flush(), not a capacity claim
        if self._pending >= self.flush_interval:
            self.flush(train_state)

    def flush(self, train_state) -> List[dict]:
        """ONE device fetch: pull the whole ring + counters, decode every
        row not yet seen, publish the newest values to the registry.
        Returns the newly decoded records."""
        buf = train_state.telemetry
        if not has_buffer(buf):
            return []
        host = jax.device_get(buf)       # the single transfer
        self.fetch_count += 1
        self._pending = 0
        now = time.perf_counter()
        total = int(host.count)
        new = total - self._read_count
        records: List[dict] = []
        if new > 0:
            dropped = max(0, new - self.spec.capacity)
            if dropped:
                self.dropped_rows += dropped
                self.registry.counter(
                    "dl4j_telemetry_dropped_rows_total",
                    "ring rows overwritten before flush").inc(
                    dropped, session=self.session_id)
                log.warning("telemetry ring overwrote %d rows before "
                            "flush (capacity %d); flush more often or "
                            "grow the ring", dropped, self.spec.capacity)
            for j in range(self._read_count + dropped, total):
                idx = j % self.spec.capacity
                rec: Dict[str, Any] = {"iteration": int(host.iters[idx])}
                for m, name in enumerate(self.spec.metric_names):
                    rec[name] = float(host.rows[idx, m])
                records.append(rec)
            self._read_count = total
            self.history.extend(records)
        # hist/replica rings advance on their own cadence (the parallel
        # wrapper's AVERAGING step records ONLY replica rows) — decode
        # them even when no new base rows landed
        self._decode_hist(host)
        rep_records = self._decode_replica(host)
        if records:
            self._publish(records, new, now)
        self._publish_replica(rep_records)
        self._last_flush_time = now
        return records

    def _decode_hist(self, host) -> List[dict]:
        """Decode new histogram-ring entries from an already-fetched
        buffer (no device interaction — ``host`` is the flush's one
        transfer)."""
        if not isinstance(host.hist, HistRing) or self.spec is None:
            return []
        h = host.hist
        total = int(h.count)
        new = total - self._hist_read
        if new <= 0:
            return []
        start = self._hist_read + max(0, new - self.spec.hist_capacity)
        out = []
        for j in range(start, total):
            idx = j % self.spec.hist_capacity
            layers: Dict[str, dict] = {}
            for li, lname in enumerate(self.spec.layer_names):
                layers[lname] = {
                    kind: {
                        "counts": h.counts[idx, li, ki].tolist(),
                        "min": float(h.ranges[idx, li, ki, 0]),
                        "max": float(h.ranges[idx, li, ki, 1]),
                    } for ki, kind in enumerate(HIST_KINDS)}
            out.append({"iteration": int(h.iters[idx]),
                        "layers": layers})
        self._hist_read = total
        self.hist_history.extend(out)
        return out

    def _decode_replica(self, host) -> List[dict]:
        """Decode new per-device rows from the fetched buffer."""
        if not isinstance(host.replica, ReplicaRing) or self.spec is None:
            return []
        rep = host.replica
        total = int(rep.count)
        new = total - self._replica_read
        if new <= 0:
            return []
        start = self._replica_read + max(0, new - self.spec.capacity)
        out = []
        for j in range(start, total):
            idx = j % self.spec.capacity
            rec: Dict[str, Any] = {"iteration": int(rep.iters[idx])}
            for m, name in enumerate(self.spec.replica_metrics):
                rec[name] = [float(v) for v in rep.rows[idx, :, m]]
            out.append(rec)
        self._replica_read = total
        self.replica_history.extend(out)
        return out

    def _publish(self, records: List[dict], n_steps: int, now: float):
        r = self.registry
        s = self.session_id
        last = records[-1]
        r.gauge("dl4j_loss", "training loss (flushed from the device "
                "ring)").set(last["loss"], session=s)
        r.gauge("dl4j_grad_norm", "global gradient L2 norm").set(
            last["grad_norm"], session=s)
        r.gauge("dl4j_iteration", "latest flushed iteration").set(
            last["iteration"], session=s)
        nonfinite = sum(rec["nonfinite_count"] for rec in records)
        r.counter("dl4j_nonfinite_values_total", "non-finite values seen "
                  "in gradients/loss").inc(nonfinite, session=s)
        if self._last_flush_time is not None:
            dt = now - self._last_flush_time
            if dt > 0:
                r.gauge("dl4j_steps_per_second", "optimizer steps per "
                        "second over the last flush window").set(
                    n_steps / dt, session=s)
        r.counter("dl4j_telemetry_flushes_total", "device fetches "
                  "performed by the telemetry collector").inc(session=s)
        for name in self.spec.layer_names:
            r.gauge("dl4j_update_ratio", "mean |update| / mean |param| "
                    "per layer").set(last[f"update_ratio/{name}"],
                                     session=s, layer=name)

    def _publish_replica(self, records: List[dict]):
        """Per-device gauges + the cross-replica divergence metric: the
        relative spread (max − min over workers, over the mean magnitude)
        of the divergence column — ``grad_norm`` when present, else the
        last replica metric. ~0 on healthy synchronous replicas; a
        desynced/straggling worker pushes it up before the averaged
        parameters are corrupted."""
        if not records or self.spec is None:
            return
        r = self.registry
        s = self.session_id
        names = self.spec.replica_metrics
        last = records[-1]
        nonfinite = 0
        for rec in records:
            for name in names:
                nonfinite += sum(1 for v in rec[name]
                                 if not math.isfinite(v))
        if nonfinite:
            r.counter("dl4j_nonfinite_values_total", "non-finite values "
                      "seen in gradients/loss").inc(nonfinite, session=s)
        for name in names:
            g = r.gauge(f"dl4j_replica_{name}",
                        f"per-device {name} from the parallel wrapper")
            for w, v in enumerate(last[name]):
                g.set(v, session=s, replica=str(w))
        div_col = "grad_norm" if "grad_norm" in names else names[-1]
        div = 0.0
        for rec in records:
            vals = [v for v in rec[div_col] if math.isfinite(v)]
            if len(vals) >= 2:
                scale = sum(abs(v) for v in vals) / len(vals)
                div = max(div,
                          (max(vals) - min(vals)) / (scale + 1e-12))
            elif len(vals) < len(rec[div_col]):
                div = float("inf")   # a non-finite replica IS divergence
        r.gauge("dl4j_replica_divergence", "relative max pairwise "
                "spread of per-replica grad norms (0 = replicas in "
                "sync)").set(div, session=s)

    # ---- read side ------------------------------------------------------
    def last_record(self) -> Optional[dict]:
        return self.history[-1] if self.history else None

    def last(self, metric: str) -> Optional[float]:
        rec = self.last_record()
        return None if rec is None else rec.get(metric)

    def last_histograms(self) -> Optional[dict]:
        """Latest decoded per-layer histograms
        (``{"iteration": i, "layers": {name: {param/grad/update:
        {counts, min, max}}}}``), or None before the first flush of a
        histogram-enabled ring."""
        return self.hist_history[-1] if self.hist_history else None

    def last_replica_record(self) -> Optional[dict]:
        return self.replica_history[-1] if self.replica_history else None
