"""Serializable evaluation-curve exports.

Analogs of the reference's ``eval/curves`` package
(deeplearning4j-nn/.../eval/curves/): ``RocCurve`` (RocCurve.java),
``PrecisionRecallCurve`` (PrecisionRecallCurve.java),
``ReliabilityDiagram`` (ReliabilityDiagram.java) and ``Histogram``
(Histogram.java) — point-list objects the UI charts consume, with JSON
round-trip like the reference's Jackson serde (BaseCurve.java:toJson).

Produced by ``ROC.get_roc_curve()`` / ``ROC.get_precision_recall_curve()``
and ``EvaluationCalibration.get_reliability_diagram()`` /
``get_*_histogram()``; rendered by the dashboard's Evaluation tab
(ui/server.py) via ``UIServer.upload_evaluation``.
"""

from __future__ import annotations

import json
from typing import List, Optional, Sequence

import numpy as np


def _area(x: np.ndarray, y: np.ndarray) -> float:
    """Trapezoidal area under (x, y) — reference: BaseCurve.calculateArea
    (BaseCurve.java:48)."""
    if len(x) < 2:
        return 0.0
    return float(abs(np.trapezoid(y, x)))


class _JsonSerde:
    """Shared dict<->JSON surface (reference: BaseCurve.toJson /
    BaseHistogram.toJson)."""

    def to_dict(self) -> dict:
        raise NotImplementedError

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def from_json(cls, s: str):
        return cls.from_dict(json.loads(s))


class BaseCurve(_JsonSerde):
    """Common x/y + area surface (reference: BaseCurve.java)."""

    def num_points(self) -> int:
        return len(self.get_x())

    def get_x(self) -> np.ndarray:
        raise NotImplementedError

    def get_y(self) -> np.ndarray:
        raise NotImplementedError

    def calculate_area(self) -> float:
        return _area(self.get_x(), self.get_y())


class RocCurve(BaseCurve):
    """(threshold, fpr, tpr) point lists (reference: RocCurve.java:15).
    x = false positive rate, y = true positive rate."""

    def __init__(self, threshold: Sequence[float], fpr: Sequence[float],
                 tpr: Sequence[float]):
        self.threshold = np.asarray(threshold, np.float64)
        self.fpr = np.asarray(fpr, np.float64)
        self.tpr = np.asarray(tpr, np.float64)
        if not (len(self.threshold) == len(self.fpr) == len(self.tpr)):
            raise ValueError("threshold/fpr/tpr lengths differ")

    def get_x(self) -> np.ndarray:
        return self.fpr

    def get_y(self) -> np.ndarray:
        return self.tpr

    def get_threshold(self, i: int) -> float:
        return float(self.threshold[i])

    def get_true_positive_rate(self, i: int) -> float:
        return float(self.tpr[i])

    def get_false_positive_rate(self, i: int) -> float:
        return float(self.fpr[i])

    def calculate_auc(self) -> float:
        return self.calculate_area()

    @property
    def title(self) -> str:
        return f"ROC (Area={self.calculate_auc():.4f})"

    def to_dict(self) -> dict:
        return {"@type": "RocCurve",
                "threshold": self.threshold.tolist(),
                "fpr": self.fpr.tolist(), "tpr": self.tpr.tolist()}

    @classmethod
    def from_dict(cls, d: dict) -> "RocCurve":
        return cls(d["threshold"], d["fpr"], d["tpr"])


class PrecisionRecallCurve(BaseCurve):
    """(threshold, precision, recall) + per-point tp/fp/fn counts
    (reference: PrecisionRecallCurve.java:18). x = recall,
    y = precision."""

    def __init__(self, threshold, precision, recall, tp_count=None,
                 fp_count=None, fn_count=None, total_count: int = 0):
        self.threshold = np.asarray(threshold, np.float64)
        self.precision = np.asarray(precision, np.float64)
        self.recall = np.asarray(recall, np.float64)
        n = len(self.threshold)
        z = np.zeros(n, np.int64)
        self.tp_count = (np.asarray(tp_count, np.int64)
                         if tp_count is not None else z.copy())
        self.fp_count = (np.asarray(fp_count, np.int64)
                         if fp_count is not None else z.copy())
        self.fn_count = (np.asarray(fn_count, np.int64)
                         if fn_count is not None else z.copy())
        self.total_count = int(total_count)
        if not (n == len(self.precision) == len(self.recall)
                == len(self.tp_count) == len(self.fp_count)
                == len(self.fn_count)):
            raise ValueError("PR-curve arrays have differing lengths")

    def get_x(self) -> np.ndarray:
        return self.recall

    def get_y(self) -> np.ndarray:
        return self.precision

    def get_threshold(self, i: int) -> float:
        return float(self.threshold[i])

    def get_precision(self, i: int) -> float:
        return float(self.precision[i])

    def get_recall(self, i: int) -> float:
        return float(self.recall[i])

    def calculate_auprc(self) -> float:
        return self.calculate_area()

    def get_point_at_threshold(self, threshold: float):
        """(threshold, precision, recall) at the smallest curve
        threshold >= the requested one (reference:
        PrecisionRecallCurve.getPointAtThreshold)."""
        idx = int(np.searchsorted(self.threshold, threshold, "left"))
        idx = min(idx, len(self.threshold) - 1)
        return (float(self.threshold[idx]), float(self.precision[idx]),
                float(self.recall[idx]))

    def get_point_at_precision(self, precision: float):
        """First point (lowest threshold) with precision >= the given
        value (reference: getPointAtPrecision)."""
        ok = np.nonzero(self.precision >= precision)[0]
        idx = int(ok[0]) if len(ok) else len(self.threshold) - 1
        return (float(self.threshold[idx]), float(self.precision[idx]),
                float(self.recall[idx]))

    def get_point_at_recall(self, recall: float):
        """Point with the HIGHEST precision among those with
        recall >= the given value (reference: getPointAtRecall)."""
        ok = np.nonzero(self.recall >= recall)[0]
        if len(ok):
            idx = int(ok[np.argmax(self.precision[ok])])
        else:
            idx = 0
        return (float(self.threshold[idx]), float(self.precision[idx]),
                float(self.recall[idx]))

    @property
    def title(self) -> str:
        return (f"Precision-Recall Curve (Area="
                f"{self.calculate_auprc():.4f})")

    def to_dict(self) -> dict:
        return {"@type": "PrecisionRecallCurve",
                "threshold": self.threshold.tolist(),
                "precision": self.precision.tolist(),
                "recall": self.recall.tolist(),
                "tpCount": self.tp_count.tolist(),
                "fpCount": self.fp_count.tolist(),
                "fnCount": self.fn_count.tolist(),
                "totalCount": self.total_count}

    @classmethod
    def from_dict(cls, d: dict) -> "PrecisionRecallCurve":
        return cls(d["threshold"], d["precision"], d["recall"],
                   d.get("tpCount"), d.get("fpCount"), d.get("fnCount"),
                   d.get("totalCount", 0))


class ReliabilityDiagram(_JsonSerde):
    """Mean-predicted vs fraction-positive per probability bin
    (reference: ReliabilityDiagram.java:14)."""

    def __init__(self, title: str, mean_predicted_value,
                 fraction_positives):
        self.title = title
        self.mean_predicted_value = np.asarray(mean_predicted_value,
                                               np.float64)
        self.fraction_positives = np.asarray(fraction_positives,
                                             np.float64)
        if len(self.mean_predicted_value) != len(self.fraction_positives):
            raise ValueError("mean_predicted/fraction_positives lengths "
                             "differ")

    def get_x(self) -> np.ndarray:
        return self.mean_predicted_value

    def get_y(self) -> np.ndarray:
        return self.fraction_positives

    def num_points(self) -> int:
        return len(self.mean_predicted_value)

    def to_dict(self) -> dict:
        return {"@type": "ReliabilityDiagram", "title": self.title,
                "meanPredictedValueX": self.mean_predicted_value.tolist(),
                "fractionPositivesY": self.fraction_positives.tolist()}

    @classmethod
    def from_dict(cls, d: dict) -> "ReliabilityDiagram":
        return cls(d.get("title", ""), d["meanPredictedValueX"],
                   d["fractionPositivesY"])


class Histogram(_JsonSerde):
    """Equal-width histogram export (reference: Histogram.java:14 —
    title, lower/upper bound, bin counts)."""

    def __init__(self, title: str, lower: float, upper: float,
                 bin_counts):
        self.title = title
        self.lower = float(lower)
        self.upper = float(upper)
        self.bin_counts = np.asarray(bin_counts, np.int64)

    @property
    def n_bins(self) -> int:
        return len(self.bin_counts)

    def get_bin_lower_bounds(self) -> np.ndarray:
        return (self.lower + (self.upper - self.lower)
                * np.arange(self.n_bins) / self.n_bins)

    def get_bin_upper_bounds(self) -> np.ndarray:
        return (self.lower + (self.upper - self.lower)
                * np.arange(1, self.n_bins + 1) / self.n_bins)

    def get_bin_mid_values(self) -> np.ndarray:
        return (self.get_bin_lower_bounds()
                + self.get_bin_upper_bounds()) / 2

    def to_dict(self) -> dict:
        return {"@type": "Histogram", "title": self.title,
                "lower": self.lower, "upper": self.upper,
                "binCounts": self.bin_counts.tolist()}

    @classmethod
    def from_dict(cls, d: dict) -> "Histogram":
        return cls(d.get("title", ""), d["lower"], d["upper"],
                   d["binCounts"])


def from_json(s: str):
    """Polymorphic decode on the ``@type`` tag (reference:
    BaseCurve.fromJson dispatch)."""
    d = json.loads(s)
    t = d.get("@type")
    for cls in (RocCurve, PrecisionRecallCurve, ReliabilityDiagram,
                Histogram):
        if t == cls.__name__:
            return cls.from_dict(d)
    raise ValueError(f"unknown curve type {t!r}")
