"""Evaluation metrics.

Analogs of the reference's eval package (deeplearning4j-nn/.../eval/):
``Evaluation`` (accuracy/precision/recall/F1 + confusion matrix,
Evaluation.java:88), ``RegressionEvaluation``, ``ROC``/``ROCBinary``
(AUC via exact thresholding), ``EvaluationBinary``,
``EvaluationCalibration``.

Accumulation happens on host in numpy (cheap relative to inference);
the model's forward pass that produces predictions is the jitted XLA path.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class Evaluation:
    """Multi-class classification metrics over one-hot or index labels."""

    def __init__(self, num_classes: Optional[int] = None,
                 label_names: Optional[List[str]] = None):
        self.num_classes = num_classes
        self.label_names = label_names
        self._confusion: Optional[np.ndarray] = None

    def _ensure(self, n: int):
        if self._confusion is None:
            self.num_classes = self.num_classes or n
            self._confusion = np.zeros((self.num_classes, self.num_classes),
                                       dtype=np.int64)

    def eval(self, labels, predictions, mask=None):
        """labels: one-hot (N, C) or int (N,); predictions: prob (N, C).
        Time-series (N, T, C) flattens with optional (N, T) mask — same
        as the reference's evalTimeSeries."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if predictions.ndim == 3:
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
            labels = labels.reshape(-1, labels.shape[-1]) if labels.ndim == 3 \
                else labels.reshape(-1)
            predictions = predictions.reshape(-1, predictions.shape[-1])
            if mask is not None:
                labels = labels[m]
                predictions = predictions[m]
        pred_idx = np.argmax(predictions, axis=-1)
        if labels.ndim == 2:
            true_idx = np.argmax(labels, axis=-1)
        else:
            true_idx = labels.astype(np.int64)
        self._ensure(predictions.shape[-1])
        np.add.at(self._confusion, (true_idx, pred_idx), 1)

    # ---- metrics --------------------------------------------------------
    def accuracy(self) -> float:
        c = self._confusion
        return float(np.trace(c) / max(c.sum(), 1))

    def _tp(self):
        return np.diag(self._confusion).astype(np.float64)

    def precision(self, cls: Optional[int] = None) -> float:
        c = self._confusion
        denom = c.sum(axis=0).astype(np.float64)
        prec = np.divide(self._tp(), denom, out=np.zeros_like(denom),
                         where=denom > 0)
        if cls is not None:
            return float(prec[cls])
        present = c.sum(axis=1) > 0
        return float(prec[present].mean()) if present.any() else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        c = self._confusion
        denom = c.sum(axis=1).astype(np.float64)
        rec = np.divide(self._tp(), denom, out=np.zeros_like(denom),
                        where=denom > 0)
        if cls is not None:
            return float(rec[cls])
        present = denom > 0
        return float(rec[present].mean()) if present.any() else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        p = self.precision(cls)
        r = self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) > 0 else 0.0

    def confusion_matrix(self) -> np.ndarray:
        return self._confusion

    def stats(self) -> str:
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes:    {self.num_classes}",
            f" Accuracy:        {self.accuracy():.4f}",
            f" Precision:       {self.precision():.4f}",
            f" Recall:          {self.recall():.4f}",
            f" F1 Score:        {self.f1():.4f}",
            "==================================================================",
        ]
        return "\n".join(lines)


class RegressionEvaluation:
    """Column-wise MSE/MAE/RMSE/R²/correlation (reference:
    RegressionEvaluation.java)."""

    def __init__(self, num_columns: Optional[int] = None):
        self.n = 0
        self._sum_sq = None
        self._sum_abs = None
        self._sum_label = None
        self._sum_label_sq = None
        self._sum_pred = None
        self._sum_pred_sq = None
        self._sum_lp = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
                labels, predictions = labels[m], predictions[m]
        if self._sum_sq is None:
            c = labels.shape[-1]
            self._sum_sq = np.zeros(c)
            self._sum_abs = np.zeros(c)
            self._sum_label = np.zeros(c)
            self._sum_label_sq = np.zeros(c)
            self._sum_pred = np.zeros(c)
            self._sum_pred_sq = np.zeros(c)
            self._sum_lp = np.zeros(c)
        err = predictions - labels
        self.n += labels.shape[0]
        self._sum_sq += (err ** 2).sum(axis=0)
        self._sum_abs += np.abs(err).sum(axis=0)
        self._sum_label += labels.sum(axis=0)
        self._sum_label_sq += (labels ** 2).sum(axis=0)
        self._sum_pred += predictions.sum(axis=0)
        self._sum_pred_sq += (predictions ** 2).sum(axis=0)
        self._sum_lp += (labels * predictions).sum(axis=0)

    def mean_squared_error(self, col: int = 0) -> float:
        return float(self._sum_sq[col] / max(self.n, 1))

    def mean_absolute_error(self, col: int = 0) -> float:
        return float(self._sum_abs[col] / max(self.n, 1))

    def root_mean_squared_error(self, col: int = 0) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def r_squared(self, col: int = 0) -> float:
        ss_tot = self._sum_label_sq[col] - self._sum_label[col] ** 2 / self.n
        ss_res = self._sum_sq[col]
        return float(1.0 - ss_res / max(ss_tot, 1e-12))

    def pearson_correlation(self, col: int = 0) -> float:
        n = self.n
        cov = self._sum_lp[col] - self._sum_label[col] * self._sum_pred[col] / n
        vl = self._sum_label_sq[col] - self._sum_label[col] ** 2 / n
        vp = self._sum_pred_sq[col] - self._sum_pred[col] ** 2 / n
        return float(cov / max(np.sqrt(vl * vp), 1e-12))

    def average_mean_squared_error(self) -> float:
        return float(self._sum_sq.mean() / max(self.n, 1))


class ROC:
    """Binary ROC/AUC + precision-recall (exact, threshold-free — the
    reference's ROC.java with thresholdSteps=0 'exact' mode)."""

    def __init__(self):
        self._scores: List[np.ndarray] = []
        self._labels: List[np.ndarray] = []

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 2 and labels.shape[-1] == 2:
            labels = labels[:, 1]
            predictions = predictions[:, 1]
        labels = labels.reshape(-1)
        predictions = predictions.reshape(-1)
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, predictions = labels[m], predictions[m]
        self._labels.append(labels)
        self._scores.append(predictions)

    def calculate_auc(self) -> float:
        y = np.concatenate(self._labels)
        s = np.concatenate(self._scores)
        order = np.argsort(-s, kind="mergesort")
        y = y[order]
        tps = np.cumsum(y)
        fps = np.cumsum(1 - y)
        tpr = tps / max(tps[-1], 1)
        fpr = fps / max(fps[-1], 1)
        return float(np.trapezoid(tpr, fpr))

    def calculate_auprc(self) -> float:
        y = np.concatenate(self._labels)
        s = np.concatenate(self._scores)
        order = np.argsort(-s, kind="mergesort")
        y = y[order]
        tps = np.cumsum(y)
        precision = tps / np.arange(1, len(y) + 1)
        recall = tps / max(tps[-1], 1)
        return float(np.trapezoid(precision, recall))


class ROCMultiClass:
    """One-vs-all ROC per class (reference: ROCMultiClass.java)."""

    def __init__(self):
        self._rocs: Dict[int, ROC] = {}

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        for c in range(predictions.shape[-1]):
            self._rocs.setdefault(c, ROC()).eval(
                labels[..., c], predictions[..., c], mask)

    def calculate_auc(self, cls: int) -> float:
        return self._rocs[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs.values()]))


class EvaluationBinary:
    """Per-output binary metrics for multi-label sigmoid outputs
    (reference: EvaluationBinary.java)."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self._tp = None
        self._fp = None
        self._tn = None
        self._fn = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels) > 0.5
        preds = np.asarray(predictions) > self.threshold
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            preds = preds.reshape(-1, preds.shape[-1])
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
                labels, preds = labels[m], preds[m]
        elif mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, preds = labels[m], preds[m]
        if self._tp is None:
            c = labels.shape[-1]
            self._tp = np.zeros(c, np.int64)
            self._fp = np.zeros(c, np.int64)
            self._tn = np.zeros(c, np.int64)
            self._fn = np.zeros(c, np.int64)
        self._tp += (labels & preds).sum(axis=0)
        self._fp += (~labels & preds).sum(axis=0)
        self._tn += (~labels & ~preds).sum(axis=0)
        self._fn += (labels & ~preds).sum(axis=0)

    def accuracy(self, col: int = 0) -> float:
        total = self._tp[col] + self._fp[col] + self._tn[col] + self._fn[col]
        return float((self._tp[col] + self._tn[col]) / max(total, 1))

    def precision(self, col: int = 0) -> float:
        d = self._tp[col] + self._fp[col]
        return float(self._tp[col] / d) if d else 0.0

    def recall(self, col: int = 0) -> float:
        d = self._tp[col] + self._fn[col]
        return float(self._tp[col] / d) if d else 0.0

    def f1(self, col: int = 0) -> float:
        p, r = self.precision(col), self.recall(col)
        return 2 * p * r / (p + r) if (p + r) > 0 else 0.0


class ROCBinary:
    """Per-output ROC for multi-label binary outputs
    (reference: ROCBinary.java) — one ROC per output column."""

    def __init__(self):
        self._rocs: Dict[int, ROC] = {}

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 1:
            labels = labels[:, None]
            predictions = predictions[:, None]
        for c in range(labels.shape[-1]):
            self._rocs.setdefault(c, ROC()).eval(
                labels[..., c], predictions[..., c], mask)

    def calculate_auc(self, col: int = 0) -> float:
        return self._rocs[col].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc()
                              for r in self._rocs.values()]))


class EvaluationCalibration:
    """Reliability diagram + histograms of residuals/probabilities
    (reference: EvaluationCalibration.java)."""

    def __init__(self, reliability_bins: int = 10,
                 histogram_bins: int = 50):
        self.reliability_bins = reliability_bins
        self.histogram_bins = histogram_bins
        self._probs: List[np.ndarray] = []
        self._labels: List[np.ndarray] = []

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, np.float64)
        preds = np.asarray(predictions, np.float64)
        labels = labels.reshape(-1, labels.shape[-1])
        preds = preds.reshape(-1, preds.shape[-1])
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, preds = labels[m], preds[m]
        self._labels.append(labels)
        self._probs.append(preds)

    def _flat(self):
        y = np.concatenate(self._labels).reshape(-1)
        p = np.concatenate(self._probs).reshape(-1)
        return y, p

    def reliability_diagram(self):
        """Returns (bin_centers, mean_predicted, fraction_positive,
        counts) over equal-width probability bins."""
        y, p = self._flat()
        edges = np.linspace(0.0, 1.0, self.reliability_bins + 1)
        idx = np.clip(np.digitize(p, edges) - 1, 0,
                      self.reliability_bins - 1)
        centers = (edges[:-1] + edges[1:]) / 2
        mean_p = np.zeros(self.reliability_bins)
        frac_pos = np.zeros(self.reliability_bins)
        counts = np.zeros(self.reliability_bins, np.int64)
        for b in range(self.reliability_bins):
            sel = idx == b
            counts[b] = sel.sum()
            if counts[b]:
                mean_p[b] = p[sel].mean()
                frac_pos[b] = y[sel].mean()
        return centers, mean_p, frac_pos, counts

    def expected_calibration_error(self) -> float:
        _, mean_p, frac_pos, counts = self.reliability_diagram()
        total = counts.sum()
        if total == 0:
            return 0.0
        return float(np.sum(counts / total * np.abs(mean_p - frac_pos)))

    def residual_histogram(self):
        y, p = self._flat()
        return np.histogram(np.abs(y - p), bins=self.histogram_bins,
                            range=(0.0, 1.0))

    def probability_histogram(self):
        _, p = self._flat()
        return np.histogram(p, bins=self.histogram_bins, range=(0.0, 1.0))


class ConfusionMatrix:
    """Standalone confusion-matrix accumulator
    (reference: ConfusionMatrix.java). ``Evaluation`` embeds the same
    counts; this is the independently-usable variant."""

    def __init__(self, classes: Optional[List] = None):
        self.classes = list(classes) if classes is not None else None
        self._counts: Dict[tuple, int] = {}

    def add(self, actual, predicted, count: int = 1):
        self._counts[(actual, predicted)] = \
            self._counts.get((actual, predicted), 0) + count

    def add_all(self, other: "ConfusionMatrix"):
        for k, v in other._counts.items():
            self._counts[k] = self._counts.get(k, 0) + v

    def get_count(self, actual, predicted) -> int:
        return self._counts.get((actual, predicted), 0)

    def actual_total(self, actual) -> int:
        return sum(v for (a, _), v in self._counts.items() if a == actual)

    def predicted_total(self, predicted) -> int:
        return sum(v for (_, p), v in self._counts.items()
                   if p == predicted)

    def to_array(self) -> np.ndarray:
        cls = self.classes
        seen = sorted({c for k in self._counts for c in k})
        if cls is None:
            cls = seen
        else:
            # labels recorded outside the declared class list still get a
            # row/column instead of a KeyError
            cls = cls + [c for c in seen if c not in cls]
        n = len(cls)
        arr = np.zeros((n, n), np.int64)
        index = {c: i for i, c in enumerate(cls)}
        for (a, p), v in self._counts.items():
            arr[index[a], index[p]] = v
        return arr
