"""Evaluation metrics.

Analogs of the reference's eval package (deeplearning4j-nn/.../eval/):
``Evaluation`` (accuracy incl. top-N, precision/recall/F1/fBeta/
gMeasure/MCC with macro/micro averaging, false positive/negative/alarm
rates, per-class stats table + confusion matrix — Evaluation.java:88),
``RegressionEvaluation``, ``ROC``/``ROCBinary`` (AUC via exact
thresholding + RocCurve/PrecisionRecallCurve exports),
``EvaluationBinary``, ``EvaluationCalibration`` (ReliabilityDiagram/
Histogram exports). Curve objects live in evaluation/curves.py.

Accumulation happens on host in numpy (cheap relative to inference);
the model's forward pass that produces predictions is the jitted XLA path.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class Evaluation:
    """Multi-class classification metrics over one-hot or index labels.

    Reference surface: Evaluation.java — accuracy, precision/recall/F1
    (per-class, macro, micro), top-N accuracy (Evaluation.java:96,1287),
    fBeta/gMeasure (:1119,:1225), Matthews correlation (:52,1306),
    false positive/negative/alarm rates (:1093), per-class stats table.

    ``top_n``: scores a row correct when the true class is within the
    top N predicted probabilities (<=1: standard accuracy; only applies
    to the probability form of ``eval``, like the reference).
    ``binary_positive_class``: for 2-class problems the no-arg
    precision/recall/f1 report this class only (reference default 1);
    pass None to macro-average instead.
    """

    def __init__(self, num_classes: Optional[int] = None,
                 label_names: Optional[List[str]] = None,
                 top_n: int = 1,
                 binary_positive_class: Optional[int] = 1):
        self.num_classes = num_classes
        self.label_names = label_names
        self.top_n = max(int(top_n), 1)
        self.binary_positive_class = binary_positive_class
        self._confusion: Optional[np.ndarray] = None
        self._top_n_correct = 0
        self._top_n_total = 0

    def _ensure(self, n: int):
        if self._confusion is None:
            self.num_classes = self.num_classes or n
            self._confusion = np.zeros((self.num_classes, self.num_classes),
                                       dtype=np.int64)

    def eval(self, labels, predictions, mask=None):
        """labels: one-hot (N, C) or int (N,); predictions: prob (N, C).
        Time-series (N, T, C) flattens with optional (N, T) mask — same
        as the reference's evalTimeSeries."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if predictions.ndim == 3:
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
            labels = labels.reshape(-1, labels.shape[-1]) if labels.ndim == 3 \
                else labels.reshape(-1)
            predictions = predictions.reshape(-1, predictions.shape[-1])
            if mask is not None:
                labels = labels[m]
                predictions = predictions[m]
        pred_idx = np.argmax(predictions, axis=-1)
        if labels.ndim == 2:
            true_idx = np.argmax(labels, axis=-1)
        else:
            true_idx = labels.astype(np.int64)
        self._ensure(predictions.shape[-1])
        np.add.at(self._confusion, (true_idx, pred_idx), 1)
        if self.top_n > 1 and predictions.ndim == 2 \
                and predictions.shape[-1] > 1:
            # correct when < topN entries score strictly higher than the
            # true class (reference: Evaluation.java:502-518)
            true_scores = predictions[np.arange(len(true_idx)), true_idx]
            greater = (predictions > true_scores[:, None]).sum(axis=-1)
            self._top_n_correct += int((greater < self.top_n).sum())
            self._top_n_total += len(true_idx)

    # ---- per-class counts (reference: Evaluation.java:1410-1460) -------
    def _tp(self):
        return np.diag(self._confusion).astype(np.float64)

    def _fp(self):
        c = self._confusion
        return c.sum(axis=0).astype(np.float64) - self._tp()

    def _fn(self):
        c = self._confusion
        return c.sum(axis=1).astype(np.float64) - self._tp()

    def _tn(self):
        return float(self._confusion.sum()) - self._tp() - self._fp() \
            - self._fn()

    def true_positives(self) -> Dict[int, int]:
        return {i: int(v) for i, v in enumerate(self._tp())}

    def false_positives(self) -> Dict[int, int]:
        return {i: int(v) for i, v in enumerate(self._fp())}

    def false_negatives(self) -> Dict[int, int]:
        return {i: int(v) for i, v in enumerate(self._fn())}

    def true_negatives(self) -> Dict[int, int]:
        return {i: int(v) for i, v in enumerate(self._tn())}

    def _is_binary_mode(self) -> bool:
        return (self.binary_positive_class is not None
                and self.num_classes == 2)

    # ---- metrics --------------------------------------------------------
    def accuracy(self) -> float:
        c = self._confusion
        return float(np.trace(c) / max(c.sum(), 1))

    def top_n_accuracy(self) -> float:
        """Reference: Evaluation.java:1287 (topNAccuracy). Equal to
        ``accuracy()`` when top_n <= 1."""
        if self.top_n <= 1:
            return self.accuracy()
        if self._top_n_total == 0:
            return 0.0
        return self._top_n_correct / self._top_n_total

    def _per_class_precision(self) -> np.ndarray:
        denom = self._tp() + self._fp()
        return np.divide(self._tp(), denom, out=np.zeros_like(denom),
                         where=denom > 0)

    def _per_class_recall(self) -> np.ndarray:
        denom = self._tp() + self._fn()
        return np.divide(self._tp(), denom, out=np.zeros_like(denom),
                         where=denom > 0)

    def precision(self, cls: Optional[int] = None,
                  averaging: Optional[str] = None) -> float:
        """Per-class, or binary-positive-class / averaged when cls is
        None. ``averaging=None`` (the default) means: positive class
        only for 2-class problems, else macro. An explicit
        "macro"/"micro" is always honored (the reference's
        EvaluationAveraging overloads ignore binaryPositiveClass).
        Macro averaging excludes never-predicted classes (the
        reference's averagePrecisionNumClassesExcluded handling)."""
        prec = self._per_class_precision()
        if cls is not None:
            return float(prec[cls])
        if averaging is None:
            if self._is_binary_mode():
                return float(prec[self.binary_positive_class])
            averaging = "macro"
        if averaging == "micro":
            tp, fp = self._tp().sum(), self._fp().sum()
            return float(tp / (tp + fp)) if tp + fp > 0 else 0.0
        predicted = (self._tp() + self._fp()) > 0
        return float(prec[predicted].mean()) if predicted.any() else 0.0

    def recall(self, cls: Optional[int] = None,
               averaging: Optional[str] = None) -> float:
        """Same cls/averaging contract as ``precision``. Macro averaging
        excludes classes with no actual examples."""
        rec = self._per_class_recall()
        if cls is not None:
            return float(rec[cls])
        if averaging is None:
            if self._is_binary_mode():
                return float(rec[self.binary_positive_class])
            averaging = "macro"
        if averaging == "micro":
            tp, fn = self._tp().sum(), self._fn().sum()
            return float(tp / (tp + fn)) if tp + fn > 0 else 0.0
        present = (self._tp() + self._fn()) > 0
        return float(rec[present].mean()) if present.any() else 0.0

    def f1(self, cls: Optional[int] = None,
           averaging: Optional[str] = None) -> float:
        return self.f_beta(1.0, cls, averaging)

    def f_beta(self, beta: float, cls: Optional[int] = None,
               averaging: Optional[str] = None) -> float:
        """F_beta = (1+β²)·P·R / (β²·P + R) — reference:
        Evaluation.java:1119 / EvaluationUtils.fBeta. Macro averages
        the per-class F_beta values; micro computes F_beta of the
        micro P/R."""
        if cls is None:
            if averaging is None and self._is_binary_mode():
                cls = self.binary_positive_class
            elif averaging != "micro":
                # macro: per-class P/R arrays computed ONCE, vectorized
                # (per-class f_beta calls would redo the O(n²) confusion
                # reductions n times over)
                p = self._per_class_precision()
                r = self._per_class_recall()
                denom = beta * beta * p + r
                f = np.divide((1 + beta * beta) * p * r, denom,
                              out=np.zeros_like(p), where=denom > 0)
                return float(f.mean()) if len(f) else 0.0
        p = self.precision(cls, averaging)
        r = self.recall(cls, averaging)
        denom = beta * beta * p + r
        return float((1 + beta * beta) * p * r / denom) if denom > 0 \
            else 0.0

    def g_measure(self, cls: Optional[int] = None,
                  averaging: Optional[str] = None) -> float:
        """G = sqrt(precision · recall) — reference:
        Evaluation.java:1225 / EvaluationUtils.gMeasure."""
        if cls is None:
            if averaging is None and self._is_binary_mode():
                cls = self.binary_positive_class
            elif averaging != "micro":
                g = np.sqrt(self._per_class_precision()
                            * self._per_class_recall())
                return float(g.mean()) if len(g) else 0.0
        p = self.precision(cls, averaging)
        r = self.recall(cls, averaging)
        return float(np.sqrt(p * r))

    def _per_class_mcc(self) -> np.ndarray:
        tp, fp, fn, tn = self._tp(), self._fp(), self._fn(), self._tn()
        denom = np.sqrt((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
        num = tp * tn - fp * fn
        return np.divide(num, denom, out=np.zeros_like(num),
                         where=denom > 0)

    def matthews_correlation(self, cls: Optional[int] = None,
                             averaging: Optional[str] = None) -> float:
        """Binary MCC per class (one-vs-all), macro/micro averaged when
        cls is None — reference: Evaluation.java:1306
        (MCC = (TP·TN-FP·FN)/sqrt((TP+FP)(TP+FN)(TN+FP)(TN+FN)); NOT
        the multiclass R_k statistic, same caveat as the reference)."""
        if cls is None and averaging is None and self._is_binary_mode():
            cls = self.binary_positive_class
        if cls is not None:
            return float(self._per_class_mcc()[cls])
        if averaging == "micro":
            tp, fp = self._tp().sum(), self._fp().sum()
            fn, tn = self._fn().sum(), self._tn().sum()
            denom = np.sqrt((tp + fp) * (tp + fn) * (tn + fp)
                            * (tn + fn))
            return float((tp * tn - fp * fn) / denom) if denom > 0 \
                else 0.0
        mcc = self._per_class_mcc()
        return float(mcc.mean()) if len(mcc) else 0.0

    def false_positive_rate(self, cls: Optional[int] = None) -> float:
        """FPR = FP/(FP+TN); macro-averaged (or binary positive class)
        when cls is None — reference: Evaluation.java falsePositiveRate."""
        fp, tn = self._fp(), self._tn()
        denom = fp + tn
        rates = np.divide(fp, denom, out=np.zeros_like(fp),
                          where=denom > 0)
        if cls is not None:
            return float(rates[cls])
        if self._is_binary_mode():
            return float(rates[self.binary_positive_class])
        return float(rates.mean()) if len(rates) else 0.0

    def false_negative_rate(self, cls: Optional[int] = None) -> float:
        """FNR = FN/(FN+TP) — reference: Evaluation.java:1046."""
        fn, tp = self._fn(), self._tp()
        denom = fn + tp
        rates = np.divide(fn, denom, out=np.zeros_like(fn),
                          where=denom > 0)
        if cls is not None:
            return float(rates[cls])
        if self._is_binary_mode():
            return float(rates[self.binary_positive_class])
        return float(rates.mean()) if len(rates) else 0.0

    def false_alarm_rate(self) -> float:
        """FAR = (FPR + FNR) / 2 — reference: Evaluation.java:1093."""
        return (self.false_positive_rate() + self.false_negative_rate()) \
            / 2.0

    def confusion_matrix(self) -> np.ndarray:
        return self._confusion

    # ---- report ---------------------------------------------------------
    def _label(self, i: int) -> str:
        if self.label_names is not None and i < len(self.label_names):
            return self.label_names[i]
        return str(i)

    def stats(self, suppress_warnings: bool = False) -> str:
        """Multi-line classification report: confusion lines, macro
        scores, and a per-class statistics table (reference:
        Evaluation.java:571 stats())."""
        c = self._confusion
        if c is None:
            return "Evaluation: no data"
        n = self.num_classes
        lines: List[str] = []
        for a in range(n):
            for p in range(n):
                if c[a, p] and a != p:
                    lines.append(
                        f"Predictions labeled as {self._label(a)} "
                        f"classified by model as {self._label(p)}: "
                        f"{int(c[a, p])} times")
        tp, fp, fn, tn = self._tp(), self._fp(), self._fn(), self._tn()
        if not suppress_warnings:
            # mirrors the reference's warningHelper: never-predicted
            # classes are excluded from macro precision; classes with no
            # actual examples from macro recall
            never_pred = [self._label(i) for i in range(n)
                          if tp[i] == 0 and fp[i] == 0]
            if never_pred:
                lines.append(
                    f"Warning: {len(never_pred)} class(es) were never "
                    f"predicted by the model and were excluded from "
                    f"average precision: {never_pred}")
            no_actual = [self._label(i) for i in range(n)
                         if tp[i] == 0 and fn[i] == 0]
            if no_actual:
                lines.append(
                    f"Warning: {len(no_actual)} class(es) had no "
                    f"examples and were excluded from average recall: "
                    f"{no_actual}")
        lines += [
            "========================Evaluation Metrics========================",
            f" # of classes:    {n}",
            f" Accuracy:        {self.accuracy():.4f}",
        ]
        if self.top_n > 1:
            lines.append(f" Top {self.top_n} Accuracy:  "
                         f"{self.top_n_accuracy():.4f}")
        lines += [
            f" Precision:       {self.precision():.4f}",
            f" Recall:          {self.recall():.4f}",
            f" F1 Score:        {self.f1():.4f}",
        ]
        if self._is_binary_mode():
            lines.append(
                f"Precision, recall & F1: reported for positive class "
                f"(class {self.binary_positive_class}) only")
        else:
            lines.append(
                f"Precision, recall & F1: macro-averaged (equally "
                f"weighted avg. of {n} classes)")
        lines.append(
            "=======================Per-class Statistics=======================")
        lines.append(f"{'Class':<12}{'TP':>7}{'FP':>7}{'FN':>7}{'TN':>9}"
                     f"{'Precision':>11}{'Recall':>9}{'F1':>9}{'MCC':>9}")
        # vectorized once — per-row metric calls would redo O(n²)
        # confusion reductions n times over
        prec = self._per_class_precision()
        rec = self._per_class_recall()
        pr = prec + rec
        f1s = np.divide(2 * prec * rec, pr, out=np.zeros_like(pr),
                        where=pr > 0)
        mcc = self._per_class_mcc()
        for i in range(n):
            lines.append(
                f"{self._label(i):<12}{int(tp[i]):>7}{int(fp[i]):>7}"
                f"{int(fn[i]):>7}{int(tn[i]):>9}"
                f"{prec[i]:>11.4f}{rec[i]:>9.4f}"
                f"{f1s[i]:>9.4f}{mcc[i]:>9.4f}")
        lines.append(
            "==================================================================")
        return "\n".join(lines)


class RegressionEvaluation:
    """Column-wise MSE/MAE/RMSE/R²/correlation (reference:
    RegressionEvaluation.java)."""

    def __init__(self, num_columns: Optional[int] = None):
        self.n = 0
        self._sum_sq = None
        self._sum_abs = None
        self._sum_label = None
        self._sum_label_sq = None
        self._sum_pred = None
        self._sum_pred_sq = None
        self._sum_lp = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
                labels, predictions = labels[m], predictions[m]
        if self._sum_sq is None:
            c = labels.shape[-1]
            self._sum_sq = np.zeros(c)
            self._sum_abs = np.zeros(c)
            self._sum_label = np.zeros(c)
            self._sum_label_sq = np.zeros(c)
            self._sum_pred = np.zeros(c)
            self._sum_pred_sq = np.zeros(c)
            self._sum_lp = np.zeros(c)
        err = predictions - labels
        self.n += labels.shape[0]
        self._sum_sq += (err ** 2).sum(axis=0)
        self._sum_abs += np.abs(err).sum(axis=0)
        self._sum_label += labels.sum(axis=0)
        self._sum_label_sq += (labels ** 2).sum(axis=0)
        self._sum_pred += predictions.sum(axis=0)
        self._sum_pred_sq += (predictions ** 2).sum(axis=0)
        self._sum_lp += (labels * predictions).sum(axis=0)

    def mean_squared_error(self, col: int = 0) -> float:
        return float(self._sum_sq[col] / max(self.n, 1))

    def mean_absolute_error(self, col: int = 0) -> float:
        return float(self._sum_abs[col] / max(self.n, 1))

    def root_mean_squared_error(self, col: int = 0) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def r_squared(self, col: int = 0) -> float:
        ss_tot = self._sum_label_sq[col] - self._sum_label[col] ** 2 / self.n
        ss_res = self._sum_sq[col]
        return float(1.0 - ss_res / max(ss_tot, 1e-12))

    def pearson_correlation(self, col: int = 0) -> float:
        n = self.n
        cov = self._sum_lp[col] - self._sum_label[col] * self._sum_pred[col] / n
        vl = self._sum_label_sq[col] - self._sum_label[col] ** 2 / n
        vp = self._sum_pred_sq[col] - self._sum_pred[col] ** 2 / n
        return float(cov / max(np.sqrt(vl * vp), 1e-12))

    def average_mean_squared_error(self) -> float:
        return float(self._sum_sq.mean() / max(self.n, 1))


class ROC:
    """Binary ROC/AUC + precision-recall (exact, threshold-free — the
    reference's ROC.java with thresholdSteps=0 'exact' mode)."""

    def __init__(self):
        self._scores: List[np.ndarray] = []
        self._labels: List[np.ndarray] = []

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 2 and labels.shape[-1] == 2:
            labels = labels[:, 1]
            predictions = predictions[:, 1]
        labels = labels.reshape(-1)
        predictions = predictions.reshape(-1)
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, predictions = labels[m], predictions[m]
        self._labels.append(labels)
        self._scores.append(predictions)

    def calculate_auc(self) -> float:
        """AUC over the tie-collapsed threshold points (so the scalar
        agrees with get_roc_curve().calculate_auc(): a cut inside a
        tie group is not a realizable threshold, and per-sample cumsums
        would make the result depend on eval() insertion order)."""
        _, tp, fp, pos, neg, _ = self._threshold_counts()
        tpr = tp / pos if pos > 0 else np.zeros_like(tp)
        fpr = fp / neg if neg > 0 else np.zeros_like(fp)
        return float(np.trapezoid(np.concatenate([[0.0], tpr]),
                                  np.concatenate([[0.0], fpr])))

    def calculate_auprc(self) -> float:
        """AUPRC over the same tie-collapsed points, with the
        (recall=0, precision=1) anchor (reference: ROC.java exact
        mode)."""
        _, tp, fp, pos, neg, _ = self._threshold_counts()
        pred_pos = tp + fp
        prec = np.divide(tp, pred_pos, out=np.ones_like(tp),
                         where=pred_pos > 0)
        rec = tp / pos if pos > 0 else np.zeros_like(tp)
        return float(np.trapezoid(np.concatenate([[1.0], prec]),
                                  np.concatenate([[0.0], rec])))

    # ---- curve exports (reference: ROC.getRocCurve /
    # getPrecisionRecallCurve over eval/curves/*.java) -------------------
    def _threshold_counts(self):
        """Distinct score thresholds (descending) with cumulative
        TP/FP counts when classifying score >= threshold as positive.
        Tied scores collapse to one point — a cut inside a tie group is
        not a realizable threshold."""
        if not self._labels:
            z = np.zeros(0, np.float64)
            return z, z, z, 0.0, 0.0, 0
        y = np.concatenate(self._labels).astype(np.float64)
        s = np.concatenate(self._scores).astype(np.float64)
        order = np.argsort(-s, kind="mergesort")
        y, s = y[order], s[order]
        y = (y > 0.5).astype(np.float64)
        # last index of each tie group (s is descending)
        idx = np.append(np.nonzero(np.diff(s))[0], len(s) - 1)
        tp = np.cumsum(y)[idx]
        fp = np.cumsum(1.0 - y)[idx]
        thr = s[idx]
        return thr, tp, fp, float(tp[-1]) if len(tp) else 0.0, \
            float(fp[-1]) if len(fp) else 0.0, len(s)

    def get_roc_curve(self):
        """Exact ROC curve export (reference: ROC.getRocCurve →
        RocCurve.java). Starts at (0,0) with a threshold above every
        score; ends at (1,1) at the minimum score."""
        from deeplearning4j_tpu.evaluation.curves import RocCurve
        thr, tp, fp, pos, neg, _ = self._threshold_counts()
        tpr = tp / pos if pos > 0 else np.zeros_like(tp)
        fpr = fp / neg if neg > 0 else np.zeros_like(fp)
        top = max(1.0, float(thr[0])) if len(thr) else 1.0
        return RocCurve(np.concatenate([[top], thr]),
                        np.concatenate([[0.0], fpr]),
                        np.concatenate([[0.0], tpr]))

    def get_precision_recall_curve(self):
        """Exact PR curve export, thresholds ascending (reference:
        ROC.getPrecisionRecallCurve → PrecisionRecallCurve.java). The
        synthetic (recall=0, precision=1) anchor sits at a threshold
        above every score, like the reference's first point."""
        from deeplearning4j_tpu.evaluation.curves import (
            PrecisionRecallCurve)
        thr, tp, fp, pos, neg, total = self._threshold_counts()
        pred_pos = tp + fp
        prec = np.divide(tp, pred_pos, out=np.ones_like(tp),
                         where=pred_pos > 0)
        rec = tp / pos if pos > 0 else np.zeros_like(tp)
        # ascending thresholds + anchor point at the top
        top = max(1.0, float(thr[0])) if len(thr) else 1.0
        thr_a = np.concatenate([thr[::-1], [top]])
        prec_a = np.concatenate([prec[::-1], [1.0]])
        rec_a = np.concatenate([rec[::-1], [0.0]])
        tp_a = np.concatenate([tp[::-1], [0]]).astype(np.int64)
        fp_a = np.concatenate([fp[::-1], [0]]).astype(np.int64)
        fn_a = (pos - tp_a).astype(np.int64)
        return PrecisionRecallCurve(thr_a, prec_a, rec_a, tp_a, fp_a,
                                    fn_a, total)


class ROCMultiClass:
    """One-vs-all ROC per class (reference: ROCMultiClass.java)."""

    def __init__(self):
        self._rocs: Dict[int, ROC] = {}

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        for c in range(predictions.shape[-1]):
            self._rocs.setdefault(c, ROC()).eval(
                labels[..., c], predictions[..., c], mask)

    def calculate_auc(self, cls: int) -> float:
        return self._rocs[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs.values()]))

    def get_roc_curve(self, cls: int):
        """One-vs-all RocCurve for a class (reference:
        ROCMultiClass.getRocCurve)."""
        return self._rocs[cls].get_roc_curve()

    def get_precision_recall_curve(self, cls: int):
        return self._rocs[cls].get_precision_recall_curve()


class EvaluationBinary:
    """Per-output binary metrics for multi-label sigmoid outputs
    (reference: EvaluationBinary.java)."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self._tp = None
        self._fp = None
        self._tn = None
        self._fn = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels) > 0.5
        preds = np.asarray(predictions) > self.threshold
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            preds = preds.reshape(-1, preds.shape[-1])
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
                labels, preds = labels[m], preds[m]
        elif mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, preds = labels[m], preds[m]
        if self._tp is None:
            c = labels.shape[-1]
            self._tp = np.zeros(c, np.int64)
            self._fp = np.zeros(c, np.int64)
            self._tn = np.zeros(c, np.int64)
            self._fn = np.zeros(c, np.int64)
        self._tp += (labels & preds).sum(axis=0)
        self._fp += (~labels & preds).sum(axis=0)
        self._tn += (~labels & ~preds).sum(axis=0)
        self._fn += (labels & ~preds).sum(axis=0)

    def accuracy(self, col: int = 0) -> float:
        total = self._tp[col] + self._fp[col] + self._tn[col] + self._fn[col]
        return float((self._tp[col] + self._tn[col]) / max(total, 1))

    def precision(self, col: int = 0) -> float:
        d = self._tp[col] + self._fp[col]
        return float(self._tp[col] / d) if d else 0.0

    def recall(self, col: int = 0) -> float:
        d = self._tp[col] + self._fn[col]
        return float(self._tp[col] / d) if d else 0.0

    def f1(self, col: int = 0) -> float:
        p, r = self.precision(col), self.recall(col)
        return 2 * p * r / (p + r) if (p + r) > 0 else 0.0


class ROCBinary:
    """Per-output ROC for multi-label binary outputs
    (reference: ROCBinary.java) — one ROC per output column."""

    def __init__(self):
        self._rocs: Dict[int, ROC] = {}

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 1:
            labels = labels[:, None]
            predictions = predictions[:, None]
        for c in range(labels.shape[-1]):
            self._rocs.setdefault(c, ROC()).eval(
                labels[..., c], predictions[..., c], mask)

    def calculate_auc(self, col: int = 0) -> float:
        return self._rocs[col].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc()
                              for r in self._rocs.values()]))

    def get_roc_curve(self, col: int = 0):
        """Per-output RocCurve (reference: ROCBinary.getRocCurve)."""
        return self._rocs[col].get_roc_curve()

    def get_precision_recall_curve(self, col: int = 0):
        return self._rocs[col].get_precision_recall_curve()


class EvaluationCalibration:
    """Reliability diagram + histograms of residuals/probabilities
    (reference: EvaluationCalibration.java)."""

    def __init__(self, reliability_bins: int = 10,
                 histogram_bins: int = 50):
        self.reliability_bins = reliability_bins
        self.histogram_bins = histogram_bins
        self._probs: List[np.ndarray] = []
        self._labels: List[np.ndarray] = []

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, np.float64)
        preds = np.asarray(predictions, np.float64)
        labels = labels.reshape(-1, labels.shape[-1])
        preds = preds.reshape(-1, preds.shape[-1])
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, preds = labels[m], preds[m]
        self._labels.append(labels)
        self._probs.append(preds)

    def _flat(self):
        if not self._labels:          # nothing eval'd yet: empty curves,
            z = np.zeros(0)           # not a concatenate ValueError
            return z, z
        y = np.concatenate(self._labels).reshape(-1)
        p = np.concatenate(self._probs).reshape(-1)
        return y, p

    def reliability_diagram(self):
        """Returns (bin_centers, mean_predicted, fraction_positive,
        counts) over equal-width probability bins."""
        y, p = self._flat()
        edges = np.linspace(0.0, 1.0, self.reliability_bins + 1)
        idx = np.clip(np.digitize(p, edges) - 1, 0,
                      self.reliability_bins - 1)
        centers = (edges[:-1] + edges[1:]) / 2
        mean_p = np.zeros(self.reliability_bins)
        frac_pos = np.zeros(self.reliability_bins)
        counts = np.zeros(self.reliability_bins, np.int64)
        for b in range(self.reliability_bins):
            sel = idx == b
            counts[b] = sel.sum()
            if counts[b]:
                mean_p[b] = p[sel].mean()
                frac_pos[b] = y[sel].mean()
        return centers, mean_p, frac_pos, counts

    def expected_calibration_error(self) -> float:
        _, mean_p, frac_pos, counts = self.reliability_diagram()
        total = counts.sum()
        if total == 0:
            return 0.0
        return float(np.sum(counts / total * np.abs(mean_p - frac_pos)))

    def residual_histogram(self):
        y, p = self._flat()
        return np.histogram(np.abs(y - p), bins=self.histogram_bins,
                            range=(0.0, 1.0))

    def probability_histogram(self):
        _, p = self._flat()
        return np.histogram(p, bins=self.histogram_bins, range=(0.0, 1.0))

    # ---- curve exports (reference: EvaluationCalibration
    # .getReliabilityDiagram / getResidualPlot / getProbabilityHistogram
    # returning eval/curves objects) -------------------------------------
    def get_reliability_diagram(self):
        """ReliabilityDiagram export (reference:
        EvaluationCalibration.getReliabilityDiagram). Empty bins are
        dropped, like the reference's count-filtered output."""
        from deeplearning4j_tpu.evaluation.curves import (
            ReliabilityDiagram)
        _, mean_p, frac_pos, counts = self.reliability_diagram()
        keep = counts > 0
        return ReliabilityDiagram("Reliability Diagram",
                                  mean_p[keep], frac_pos[keep])

    def get_residual_histogram(self):
        from deeplearning4j_tpu.evaluation.curves import Histogram
        counts, _edges = self.residual_histogram()
        return Histogram("Residual Plot - |label - P(class)|", 0.0, 1.0,
                         counts)

    def get_probability_histogram(self):
        from deeplearning4j_tpu.evaluation.curves import Histogram
        counts, _edges = self.probability_histogram()
        return Histogram("Predicted Probabilities", 0.0, 1.0, counts)


class ConfusionMatrix:
    """Standalone confusion-matrix accumulator
    (reference: ConfusionMatrix.java). ``Evaluation`` embeds the same
    counts; this is the independently-usable variant."""

    def __init__(self, classes: Optional[List] = None):
        self.classes = list(classes) if classes is not None else None
        self._counts: Dict[tuple, int] = {}

    def add(self, actual, predicted, count: int = 1):
        self._counts[(actual, predicted)] = \
            self._counts.get((actual, predicted), 0) + count

    def add_all(self, other: "ConfusionMatrix"):
        for k, v in other._counts.items():
            self._counts[k] = self._counts.get(k, 0) + v

    def get_count(self, actual, predicted) -> int:
        return self._counts.get((actual, predicted), 0)

    def actual_total(self, actual) -> int:
        return sum(v for (a, _), v in self._counts.items() if a == actual)

    def predicted_total(self, predicted) -> int:
        return sum(v for (_, p), v in self._counts.items()
                   if p == predicted)

    def to_array(self) -> np.ndarray:
        cls = self.classes
        seen = sorted({c for k in self._counts for c in k})
        if cls is None:
            cls = seen
        else:
            # labels recorded outside the declared class list still get a
            # row/column instead of a KeyError
            cls = cls + [c for c in seen if c not in cls]
        n = len(cls)
        arr = np.zeros((n, n), np.int64)
        index = {c: i for i, c in enumerate(cls)}
        for (a, p), v in self._counts.items():
            arr[index[a], index[p]] = v
        return arr
