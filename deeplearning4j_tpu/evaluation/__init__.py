"""Evaluation harnesses (quantization accuracy gate)."""

from deeplearning4j_tpu.evaluation.quant_gate import (
    GateResult,
    QuantGate,
    QuantGateError,
    enforce_quant_gate,
    run_quant_gate,
    run_zoo_gates,
    zoo_gate_cases,
)

__all__ = [
    "GateResult",
    "QuantGate",
    "QuantGateError",
    "enforce_quant_gate",
    "run_quant_gate",
    "run_zoo_gates",
    "zoo_gate_cases",
]
