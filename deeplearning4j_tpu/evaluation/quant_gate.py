"""Accuracy gate for int8 serving: quantized vs f32, budgeted.

A quantized model is only a win if it answers the same. This harness
runs a quantized build (parallel/quant.py) and the f32 reference over
the same evaluation stream and scores:

- **top-1 agreement** — fraction of examples (or (example, timestep)
  positions for sequence outputs) whose argmax class matches f32;
  ``top1_delta = 1 - agreement`` must stay within ``top1_budget``
- **output delta** — max / mean absolute difference of the final
  (post-activation) output vector, bounded by ``logit_budget``

``enforce_quant_gate`` is the HARD form: it raises ``QuantGateError``
on a failed budget, and the FleetRouter calls it before a quantized
version's engines are even built — a quantized model that disagrees
with its f32 self never reaches the warm-swap path (parallel/fleet.py).

``zoo_gate_cases()`` yields the committed-pretrained zoo models
(zoo/weights) with deterministic evaluation streams; the acceptance
tests run the gate over them so "int8 is accurate enough to serve" is
checked against real trained weights, not random ones.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.parallel.quant import (
    PrecisionPolicy,
    QuantizedModel,
    _calib_batches,
    quantize_model,
)


@dataclasses.dataclass(frozen=True)
class QuantGate:
    """Budgets + evaluation stream for one gate run. ``samples`` (an
    (N, ...) feature array, iterable of arrays, or DataSets) defaults
    to the policy's calibration stream when omitted — fine for smoke
    gates, but a real rollout should hold out separate eval data."""
    top1_budget: float = 0.02
    logit_budget: Optional[float] = 0.25
    samples: Any = dataclasses.field(default=None, repr=False,
                                     compare=False)
    batch_size: int = 64
    max_batches: int = 16


@dataclasses.dataclass
class GateResult:
    model: str
    n_examples: int
    n_positions: int                 # argmax comparisons (N or N*T)
    top1_agreement: float
    top1_delta: float
    max_logit_delta: float
    mean_logit_delta: float
    top1_budget: float
    logit_budget: Optional[float]
    layer_errors: Dict[str, float]
    fallback: List[str]
    passed: bool

    def summary(self) -> str:
        verdict = "PASS" if self.passed else "FAIL"
        lb = ("-" if self.logit_budget is None
              else f"{self.logit_budget:g}")
        return (f"[{verdict}] {self.model}: top1_delta "
                f"{self.top1_delta:.4f} (budget {self.top1_budget:g}) "
                f"max|dy| {self.max_logit_delta:.4f} (budget {lb}) "
                f"over {self.n_examples} examples; "
                f"fallback={self.fallback or 'none'}")


class QuantGateError(RuntimeError):
    """A quantized model failed its accuracy budget. Carries the
    ``GateResult`` so the caller (and the swap-path log) can show the
    exact deltas."""

    def __init__(self, result: GateResult):
        super().__init__(result.summary())
        self.result = result


def run_quant_gate(model, policy: PrecisionPolicy,
                   gate: Optional[QuantGate] = None, *,
                   model_name: Optional[str] = None,
                   quantized: Optional[QuantizedModel] = None,
                   registry=None) -> GateResult:
    """Score a quantized build against its f32 self; never raises on a
    failed budget (``passed`` records it) — use ``enforce_quant_gate``
    for the hard form. Pass ``quantized`` to reuse an existing build
    (calibration is deterministic, so re-quantizing is equivalent but
    slower)."""
    import jax
    gate = gate if gate is not None else QuantGate()
    qm = quantized if quantized is not None else quantize_model(
        model, policy, registry=registry)
    eval_policy = policy if gate.samples is None else \
        dataclasses.replace(policy, samples=gate.samples,
                            calib_batch_size=gate.batch_size,
                            max_calib_batches=gate.max_batches)
    batches = _calib_batches(eval_policy)
    fwd_q = jax.jit(  # graftlint: disable=recompile-hazard — offline gate, runs once per candidate version; a fresh trace per run is the cost model
        lambda p, s, x: qm.build_inference_fn()(p, s, x, None))
    fwd_f = jax.jit(  # graftlint: disable=recompile-hazard — same: pre-admission evaluation, not a serving path
        lambda p, s, x: model.build_inference_fn()(p, s, x, None))
    params_f = model.train_state.params
    mstate = model.train_state.model_state
    n_examples = n_pos = n_agree = 0
    max_d = 0.0
    sum_d = 0.0
    sum_n = 0
    for b in batches:
        x = b.features
        y_f = np.asarray(fwd_f(params_f, mstate, x))  # host-sync-ok: offline gate evaluation, pre-rollout
        y_q = np.asarray(fwd_q(qm.params, mstate, x))  # host-sync-ok: offline gate evaluation, pre-rollout
        d = np.abs(y_q.astype(np.float32) - y_f.astype(np.float32))
        max_d = max(max_d, float(d.max()))
        sum_d += float(d.sum())
        sum_n += d.size
        a_f = y_f.argmax(axis=-1).reshape(-1)
        a_q = y_q.argmax(axis=-1).reshape(-1)
        n_agree += int((a_f == a_q).sum())
        n_pos += a_f.size
        n_examples += int(np.shape(x)[0])
    agreement = n_agree / max(n_pos, 1)
    top1_delta = 1.0 - agreement
    passed = top1_delta <= gate.top1_budget and (
        gate.logit_budget is None or max_d <= gate.logit_budget)
    return GateResult(
        model=model_name or type(model).__name__,
        n_examples=n_examples, n_positions=n_pos,
        top1_agreement=agreement, top1_delta=top1_delta,
        max_logit_delta=max_d,
        mean_logit_delta=sum_d / max(sum_n, 1),
        top1_budget=gate.top1_budget, logit_budget=gate.logit_budget,
        layer_errors={n: r["error"] for n, r in qm.report.items()},
        fallback=list(qm.fallback), passed=passed)


def enforce_quant_gate(model, policy: PrecisionPolicy,
                       gate: Optional[QuantGate] = None, *,
                       model_name: Optional[str] = None,
                       registry=None) -> GateResult:
    """The hard gate: raise ``QuantGateError`` when the budget fails."""
    result = run_quant_gate(model, policy, gate, model_name=model_name,
                            registry=registry)
    if not result.passed:
        raise QuantGateError(result)
    return result


# ---- committed zoo-weight cases ------------------------------------------

def zoo_gate_cases() -> List[Tuple[str, Any, np.ndarray]]:
    """(name, pretrained model, deterministic eval features) for every
    committed zoo artifact: LeNet on the real digits test split and
    TextGenerationLSTM on deterministic one-hot character streams
    (the gate scores quantized-vs-f32 agreement, so synthetic-but-valid
    sequences exercise the rnn dense path without the corpus)."""
    from deeplearning4j_tpu.datasets.fetchers import DigitsDataSetIterator
    from deeplearning4j_tpu.zoo.models import LeNet, TextGenerationLSTM
    cases: List[Tuple[str, Any, np.ndarray]] = []

    lenet = LeNet().init_pretrained(flavor="digits")
    digits, _ = DigitsDataSetIterator.fetch(train=False)
    cases.append(("LeNet", lenet, digits.astype(np.float32)))

    textgen = TextGenerationLSTM().init_pretrained()
    vocab = textgen.layers[-1].n_out
    t = 60
    rng = np.random.default_rng(1234)
    ids = rng.integers(0, vocab, size=(96, t))
    cases.append(("TextGenerationLSTM", textgen,
                  np.eye(vocab, dtype=np.float32)[ids]))
    return cases


def run_zoo_gates(policy_kwargs: Optional[Dict[str, Any]] = None,
                  gate: Optional[QuantGate] = None) -> List[GateResult]:
    """Gate every committed zoo artifact (the acceptance sweep)."""
    out = []
    for name, model, feats in zoo_gate_cases():
        policy = PrecisionPolicy.int8(feats, **(policy_kwargs or {}))
        out.append(run_quant_gate(model, policy, gate, model_name=name))
    return out


if __name__ == "__main__":
    for r in run_zoo_gates():
        print(r.summary())
