"""Simple classification result holders.

Analogs of the reference's ``nn/simple`` result APIs:
- ``RankClassificationResult`` (deeplearning4j-nn/.../nn/simple/multiclass/
  RankClassificationResult.java:1): per-row descending rank of class
  probabilities with optional string labels.
- ``BinaryClassificationResult`` (deeplearning4j-nn/.../nn/simple/binary/
  BinaryClassificationResult.java:1): thresholded binary decisions with
  optional class weights.

Pure-numpy convenience types over model ``output()`` arrays; listed in
SURVEY §2.1 row 30 (previously folded away — VERDICT missing#8).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


class RankClassificationResult:
    """Ranks each row's class probabilities in descending order."""

    def __init__(self, outcome, labels: Optional[Sequence[str]] = None):
        outcome = np.asarray(outcome, np.float32)
        if outcome.ndim == 1:
            outcome = outcome[None, :]
        if outcome.ndim != 2:
            raise ValueError(
                f"only vectors and matrices are supported; got rank"
                f" {outcome.ndim}")
        n_classes = outcome.shape[1]
        self.labels: List[str] = (
            [str(i) for i in range(n_classes)] if labels is None
            else [str(l) for l in labels])
        if len(self.labels) != n_classes:
            raise ValueError(f"{len(self.labels)} labels for {n_classes}"
                             " classes")
        # descending sort, ties broken by lower index first (stable)
        self.ranked_indices = np.argsort(-outcome, axis=1,
                                         kind="stable").astype(np.int32)
        self.probabilities = outcome

    def max_outcome_for_row(self, r: int) -> str:
        return self.labels[int(self.ranked_indices[r][0])]

    def max_outcomes(self) -> List[str]:
        return [self.max_outcome_for_row(r)
                for r in range(self.ranked_indices.shape[0])]


class BinaryClassificationResult:
    """Thresholded decisions over positive-class probabilities."""

    def __init__(self, probabilities=None, decision_threshold: float = 0.5,
                 class_weights: Optional[Sequence[float]] = None):
        self.decision_threshold = float(decision_threshold)
        self.class_weights = (None if class_weights is None
                              else np.asarray(class_weights, np.float64))
        self.probabilities = (None if probabilities is None
                              else np.asarray(probabilities, np.float32))

    def decisions(self) -> np.ndarray:
        """0/1 decisions; accepts (N,) positive-class probs or (N, 2)
        softmax outputs (column 1 = positive)."""
        if self.probabilities is None:
            raise ValueError("no probabilities supplied")
        p = self.probabilities
        if p.ndim == 2:
            p = p[:, -1]
        return (p >= self.decision_threshold).astype(np.int32)
