"""Stats storage: persistence for training-stats records.

Analog of the reference's StatsStorage SPI
(deeplearning4j-core/.../api/storage/StatsStorage.java, SURVEY §2.2) and
its implementations (ui-model mapdb/sqlite/in-memory, §2.12). Records are
JSON dicts (the SBE wire format's role is served by compact JSON):
  {"session_id", "type_id", "worker_id", "timestamp", ...payload}

``RemoteUIStatsStorageRouter`` posts records to a remote UI server
(reference: RemoteUIStatsStorageRouter HTTP POST → RemoteReceiverModule),
which is how distributed workers report to one dashboard (§5.5).
"""

from __future__ import annotations

import contextlib
import json
import logging
import queue
import sqlite3
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional


class StatsStorageRouter:
    """Write-side SPI (reference: api/storage/StatsStorageRouter.java)."""

    def put_static_info(self, record: dict):
        raise NotImplementedError

    def put_update(self, record: dict):
        raise NotImplementedError


class StatsStorage(StatsStorageRouter):
    """Read side (reference: StatsStorage.java): list sessions/workers,
    fetch updates; listeners fire on new records."""

    def __init__(self):
        self._listeners: List[Callable[[dict], None]] = []

    def register_stats_storage_listener(self, fn: Callable[[dict], None]):
        self._listeners.append(fn)

    def _notify(self, record: dict):
        for fn in self._listeners:
            fn(record)

    # read API
    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def list_workers(self, session_id: str) -> List[str]:
        raise NotImplementedError

    def get_all_updates(self, session_id: str,
                        worker_id: Optional[str] = None) -> List[dict]:
        raise NotImplementedError

    def get_static_info(self, session_id: str) -> Optional[dict]:
        raise NotImplementedError

    def get_latest_update(self, session_id: str) -> Optional[dict]:
        ups = self.get_all_updates(session_id)
        return ups[-1] if ups else None


class InMemoryStatsStorage(StatsStorage):
    """reference: ui-model/.../storage/impl/ InMemoryStatsStorage."""

    def __init__(self):
        super().__init__()
        self._static: Dict[str, dict] = {}
        self._updates: Dict[str, List[dict]] = {}
        self._lock = threading.Lock()

    def put_static_info(self, record: dict):
        with self._lock:
            self._static[record["session_id"]] = record
        self._notify(record)

    def put_update(self, record: dict):
        with self._lock:
            self._updates.setdefault(record["session_id"], []).append(record)
        self._notify(record)

    def list_session_ids(self) -> List[str]:
        with self._lock:
            return sorted(set(self._static) | set(self._updates))

    def list_workers(self, session_id: str) -> List[str]:
        with self._lock:
            return sorted({u.get("worker_id", "w0")
                           for u in self._updates.get(session_id, [])})

    def get_all_updates(self, session_id: str,
                        worker_id: Optional[str] = None) -> List[dict]:
        with self._lock:
            ups = list(self._updates.get(session_id, []))
        if worker_id is not None:
            ups = [u for u in ups if u.get("worker_id") == worker_id]
        return ups

    def get_static_info(self, session_id: str) -> Optional[dict]:
        with self._lock:
            return self._static.get(session_id)


class SqliteStatsStorage(StatsStorage):
    """File-backed storage (reference: J7FileStatsStorage over MapDB /
    sqlite, §2.12). One table of records; safe across processes.
    Round 4: records persist in the compact binary stats codec
    (ui/codec.py — the SBE-codec role), cutting blob size ~2-4× on
    histogram-bearing updates; pre-existing JSON rows still read.
    The codec carries float arrays (and numeric lists of >=8 items) at
    f32 width, matching the reference's 32-bit SBE floats — f64 stats
    values lose precision on round-trip (advisor r4, documented)."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._lock = threading.Lock()
        with self._conn() as c:
            c.execute("CREATE TABLE IF NOT EXISTS records ("
                      "session_id TEXT, kind TEXT, ts REAL, blob TEXT)")
            c.execute("CREATE INDEX IF NOT EXISTS idx_sess ON records "
                      "(session_id, kind, ts)")

    @contextlib.contextmanager
    def _conn(self):
        # sqlite3's context manager only commits; close explicitly so a
        # per-iteration put doesn't leak a file descriptor
        conn = sqlite3.connect(self.path)
        try:
            with conn:
                yield conn
        finally:
            conn.close()

    def put_static_info(self, record: dict):
        self._put(record, "static")

    def put_update(self, record: dict):
        self._put(record, "update")

    def _put(self, record: dict, kind: str):
        from deeplearning4j_tpu.ui.codec import encode_stats_record
        with self._lock, self._conn() as c:
            c.execute("INSERT INTO records VALUES (?,?,?,?)",
                      (record["session_id"], kind,
                       record.get("timestamp", 0.0),
                       encode_stats_record(record)))
        self._notify(record)

    @staticmethod
    def _load(blob) -> dict:
        """Binary codec rows (current) or JSON rows (pre-round-4)."""
        from deeplearning4j_tpu.ui.codec import (
            decode_stats_record, is_stats_record)
        if isinstance(blob, (bytes, bytearray)) and is_stats_record(
                bytes(blob)):
            return decode_stats_record(bytes(blob))
        if isinstance(blob, (bytes, bytearray)):
            blob = blob.decode("utf-8")
        return json.loads(blob)

    def list_session_ids(self) -> List[str]:
        with self._lock, self._conn() as c:
            rows = c.execute(
                "SELECT DISTINCT session_id FROM records").fetchall()
        return sorted(r[0] for r in rows)

    def list_workers(self, session_id: str) -> List[str]:
        return sorted({u.get("worker_id", "w0")
                       for u in self.get_all_updates(session_id)})

    def get_all_updates(self, session_id: str,
                        worker_id: Optional[str] = None) -> List[dict]:
        with self._lock, self._conn() as c:
            rows = c.execute(
                "SELECT blob FROM records WHERE session_id=? AND kind="
                "'update' ORDER BY ts, rowid", (session_id,)).fetchall()
        ups = [self._load(r[0]) for r in rows]
        if worker_id is not None:
            ups = [u for u in ups if u.get("worker_id") == worker_id]
        return ups

    def get_static_info(self, session_id: str) -> Optional[dict]:
        with self._lock, self._conn() as c:
            rows = c.execute(
                "SELECT blob FROM records WHERE session_id=? AND kind="
                "'static' ORDER BY ts DESC LIMIT 1",
                (session_id,)).fetchall()
        return self._load(rows[0][0]) if rows else None


class RemoteUIStatsStorageRouter(StatsStorageRouter):
    """POST records to a remote UI server (reference:
    api/storage/impl/RemoteUIStatsStorageRouter.java → received by
    RemoteReceiverModule).

    Posts happen on a background thread (``async_mode=True``, the
    default, matching the reference's async queue): a dead dashboard
    slows nothing and, after retries, records are logged-and-dropped
    rather than crashing the training loop. ``async_mode=False`` posts
    synchronously and raises — for tests and one-shot scripts.

    The binary wire format (ui/codec.py) carries float arrays and
    numeric lists of >=8 items at f32 width (like the reference's SBE
    encoders) — f64 values in posted records are quantized in transit.
    """

    def __init__(self, url: str, timeout: float = 5.0,
                 retry_count: int = 3, async_mode: bool = True,
                 queue_limit: int = 1000):
        self.url = url.rstrip("/") + "/remote"
        self.timeout = timeout
        self.retry_count = retry_count
        self.async_mode = async_mode
        self._queue: "queue.Queue[dict]" = queue.Queue(maxsize=queue_limit)
        self._worker: Optional[threading.Thread] = None
        if async_mode:
            self._worker = threading.Thread(target=self._run, daemon=True)
            self._worker.start()

    def put_static_info(self, record: dict):
        self._submit({"kind": "static", "record": record})

    def put_update(self, record: dict):
        self._submit({"kind": "update", "record": record})

    def _submit(self, payload: dict):
        if not self.async_mode:
            self._post(payload)
            return
        try:
            self._queue.put_nowait(payload)
        except queue.Full:    # monitoring never stalls training
            logging.getLogger(__name__).warning(
                "stats queue full; dropping record")

    def _run(self):
        while True:
            payload = self._queue.get()
            try:
                self._post(payload)
            except Exception as e:   # noqa: BLE001 — log-and-drop
                logging.getLogger(__name__).warning(
                    "dropping stats record after %d retries: %s",
                    self.retry_count, e)
            finally:
                self._queue.task_done()

    def flush(self, timeout: float = 10.0):
        """Block until queued records are posted (best effort). Polls the
        queue's unfinished count with a deadline — no helper thread, so a
        never-draining queue (remote down) can't leak blocked threads."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._queue.all_tasks_done:
                if self._queue.unfinished_tasks == 0:
                    return
            time.sleep(0.05)

    def _post(self, payload: dict):
        # binary stats codec on the wire (ui/codec.py — the SBE role);
        # the receiver also accepts JSON from third-party posters
        from deeplearning4j_tpu.ui.codec import encode_stats_record
        data = encode_stats_record(payload)
        req = urllib.request.Request(
            self.url, data=data,
            headers={"Content-Type": "application/octet-stream"})
        last = None
        for _ in range(self.retry_count):
            try:
                with urllib.request.urlopen(req, timeout=self.timeout):
                    return
            except urllib.error.HTTPError as e:
                if e.code < 500:
                    # client error: retrying the same payload can't help
                    raise ConnectionError(
                        f"stats POST rejected by {self.url}: {e}") from e
                last = e          # transient server error: retry
            except Exception as e:    # noqa: BLE001 — network layer
                last = e
        raise ConnectionError(
            f"failed to post stats to {self.url}: {last}")
