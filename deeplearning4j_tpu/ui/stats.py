"""StatsListener: per-iteration training statistics.

Analog of the reference's BaseStatsListener
(deeplearning4j-ui-model/.../stats/BaseStatsListener.java:43,
iterationDone:304; SURVEY §2.12, §5.5): collects score, timing
(samples/sec, minibatches/sec), per-layer parameter/update histograms and
mean-magnitude norms, plus device/runtime static info, and routes records
into a StatsStorageRouter. Where the reference polls JVM/GC/JITA
counters, this reads jax device memory stats when the backend exposes
them.
"""

from __future__ import annotations

import socket
import time
import uuid
from typing import Dict, List, Optional

import jax
import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.ui.storage import StatsStorageRouter


def _histogram(a: np.ndarray, bins: int = 20) -> dict:
    a = np.asarray(a, np.float64).ravel()
    if a.size == 0:
        return {"counts": [], "min": 0.0, "max": 0.0}
    lo, hi = float(a.min()), float(a.max())
    if lo == hi:
        hi = lo + 1e-12
    counts, _edges = np.histogram(a, bins=bins, range=(lo, hi))
    return {"counts": counts.tolist(), "min": lo, "max": hi}


class StatsListener(TrainingListener):
    """Attach to a model with ``model.set_listeners(StatsListener(storage))``
    then open the dashboard (ui/server.py)."""

    def __init__(self, router: StatsStorageRouter,
                 session_id: Optional[str] = None,
                 worker_id: str = "w0",
                 update_frequency: int = 1,
                 collect_histograms: bool = True,
                 histogram_bins: int = 20):
        self.router = router
        self.session_id = session_id or f"sess_{uuid.uuid4().hex[:10]}"
        self.worker_id = worker_id
        self.update_frequency = max(1, update_frequency)
        self.collect_histograms = collect_histograms
        self.histogram_bins = histogram_bins
        self._static_sent = False
        # seed timing from a start timestamp so the FIRST flushed record
        # carries real dt-based throughput instead of None (the record
        # used to be emitted with absent timing)
        self._start_time: float = time.time()
        self._last_time: Optional[float] = None
        self._prev_params: Optional[Dict] = None
        # accumulated across skipped iterations when update_frequency > 1
        self._acc_samples = 0
        self._acc_iters = 0

    # ---- TrainingListener hooks -----------------------------------------
    def on_epoch_start(self, model, epoch: int):
        # re-anchor the start stamp to when training actually begins
        # (construction can predate fit() by a long time); only until the
        # first record is out
        if self._last_time is None:
            self._start_time = time.time()

    def iteration_done(self, model, iteration: int, epoch: int, loss,
                       etl_ms: float, batch_size: int):
        if not self._static_sent:
            self._send_static(model)
        self._acc_samples += int(batch_size)
        self._acc_iters += 1
        if iteration % self.update_frequency != 0:
            return
        now = time.time()
        anchor = self._last_time if self._last_time is not None \
            else self._start_time
        dt = now - anchor
        self._last_time = now
        samples, iters = self._acc_samples, self._acc_iters
        self._acc_samples = 0
        self._acc_iters = 0

        tel = getattr(model, "telemetry", None)
        if tel is not None:
            # flushed from the on-device ring: no device sync here
            score = tel.last("loss")
        else:
            score = float(loss)  # host-sync-ok: unmonitored fallback
        record = {
            "session_id": self.session_id,
            "worker_id": self.worker_id,
            "timestamp": now,
            "iteration": iteration,
            "epoch": epoch,
            "score": score,
            "etl_ms": float(etl_ms),
            "batch_size": int(batch_size),
            # throughput over ALL iterations since the last report, not
            # just the reported one
            "samples_per_sec": (samples / dt) if dt > 0 else None,
            "minibatches_per_sec": (iters / dt) if dt > 0 else None,
        }
        if tel is not None and tel.last_record() is not None:
            # device-computed series (grad norm, update ratios, NaN
            # counts) ride along for the dashboard
            record["device_metrics"] = dict(tel.last_record())
        if self.collect_histograms:
            # histogram-enabled telemetry already computed fixed-bin
            # param/grad/update histograms INSIDE the train step and
            # flushed them in the ring's one fetch — consume those and
            # skip the device→host parameter copy entirely
            from_tel = (self._stats_from_telemetry(tel)
                        if tel is not None else None)
            if from_tel is not None:
                record["param_stats"] = from_tel["param"]
                if from_tel["update"]:
                    record["update_stats"] = from_tel["update"]
                if from_tel["grad"]:
                    record["grad_stats"] = from_tel["grad"]
            else:
                params = model.train_state.params
                record["param_stats"] = self._layer_stats(params)
                if self._prev_params is not None:
                    record["update_stats"] = self._update_stats(
                        self._prev_params, params)
                # device→host param copy only when histograms consume it
                self._prev_params = jax.tree_util.tree_map(np.asarray,
                                                           params)
        record["memory"] = self._memory_stats()
        self.router.put_update(record)

    # ---- payload builders ------------------------------------------------
    def _send_static(self, model):
        devs = jax.devices()
        self.router.put_static_info({
            "session_id": self.session_id,
            "worker_id": self.worker_id,
            "timestamp": time.time(),
            "hostname": socket.gethostname(),
            "backend": devs[0].platform if devs else "unknown",
            "device_count": len(devs),
            "device_kind": getattr(devs[0], "device_kind", "?")
            if devs else "?",
            "model_class": type(model).__name__,
            "num_params": int(model.num_params()),
            "layer_names": list(getattr(model, "layer_names", ())) or
            list(model.train_state.params.keys()),
            "model_graph": self._model_graph(model),
        })
        self._static_sent = True

    @staticmethod
    def _model_graph(model) -> List[dict]:
        """Network DAG for the dashboard's Model tab: one node per layer
        or vertex with its inputs (MLN = a chain; CG = the real DAG)."""
        def count(tree):
            return int(sum(np.asarray(l).size
                           for l in jax.tree_util.tree_leaves(tree)))

        params = model.train_state.params
        nodes: List[dict] = []
        if hasattr(model, "layers"):           # MultiLayerNetwork
            prev = "input"
            for layer in model.layers:
                nodes.append({
                    "name": layer.name,
                    "type": type(layer).__name__,
                    "inputs": [prev],
                    "n_params": count(params.get(layer.name, {})),
                })
                prev = layer.name
        elif hasattr(model, "_nodes"):         # ComputationGraph
            for name in model._topo:
                node = model._nodes.get(name)
                if node is None:               # a network input
                    nodes.append({"name": name, "type": "Input",
                                  "inputs": [], "n_params": 0})
                    continue
                kind = (type(node.layer).__name__ if node.layer is not None
                        else type(node.vertex).__name__)
                nodes.append({
                    "name": name,
                    "type": kind,
                    "inputs": list(node.inputs),
                    "n_params": count(params.get(name, {})),
                })
        return nodes

    def _stats_from_telemetry(self, tel) -> Optional[Dict[str, dict]]:
        """param/update/grad stats rebuilt from the device-computed
        histograms the collector last flushed, or None when the ring has
        no histograms (collector not histogram-enabled, or nothing
        flushed yet). Moment estimates come from bin centers — a
        bounded-error approximation that is ample for dashboard charts
        and costs zero device transfers."""
        hist = getattr(tel, "last_histograms", lambda: None)()
        if not hist:
            return None
        kinds: Dict[str, Dict[str, dict]] = {
            "param": {}, "update": {}, "grad": {}}
        for lname, by_kind in hist.get("layers", {}).items():
            for kind, h in by_kind.items():
                if kind not in kinds:
                    continue
                counts = np.asarray(h.get("counts", ()), np.float64)
                total = counts.sum()
                if counts.size == 0 or total <= 0:
                    continue
                lo, hi = float(h["min"]), float(h["max"])
                centers = lo + (np.arange(counts.size) + 0.5) \
                    * (hi - lo) / counts.size
                mean = float((counts * centers).sum() / total)
                var = float((counts * (centers - mean) ** 2).sum()
                            / total)
                kinds[kind][lname] = {
                    "mean_magnitude": float(
                        (counts * np.abs(centers)).sum() / total),
                    "stdev": float(np.sqrt(max(var, 0.0))),
                    "histogram": {"counts": counts.tolist(),
                                  "min": lo, "max": hi},
                }
        return kinds if kinds["param"] else None

    def _layer_stats(self, params) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for lname, tree in params.items():
            leaves = jax.tree_util.tree_leaves(tree)
            if not leaves:
                continue
            flat = np.concatenate([np.asarray(l, np.float64).ravel()
                                   for l in leaves])
            out[lname] = {
                "mean_magnitude": float(np.mean(np.abs(flat))),
                "stdev": float(np.std(flat)),
                "histogram": _histogram(flat, self.histogram_bins),
            }
        return out

    def _update_stats(self, prev, cur) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for lname, tree in cur.items():
            pl = jax.tree_util.tree_leaves(prev.get(lname, {}))
            cl = jax.tree_util.tree_leaves(tree)
            if not cl or len(pl) != len(cl):
                continue
            diffs = np.concatenate([
                (np.asarray(c, np.float64) - np.asarray(p, np.float64))
                .ravel() for p, c in zip(pl, cl)])
            out[lname] = {
                "mean_magnitude": float(np.mean(np.abs(diffs))),
                "histogram": _histogram(diffs, self.histogram_bins),
            }
        return out

    @staticmethod
    def _memory_stats() -> dict:
        try:
            stats = jax.devices()[0].memory_stats()
            if stats:
                return {"bytes_in_use": stats.get("bytes_in_use"),
                        "peak_bytes_in_use": stats.get(
                            "peak_bytes_in_use"),
                        "bytes_limit": stats.get("bytes_limit")}
        except Exception:   # backend without memory_stats
            pass
        return {}
