"""Dashboard internationalization (reference:
deeplearning4j-ui-parent/deeplearning4j-play/.../i18n/I18NProvider.java +
DefaultI18N.java, which read per-language message bundles for the Play
templates).

Here the bundles are in-code maps (the reference ships
``messages_*.properties`` resources); ``I18N.get_instance()`` is the
provider singleton, ``get_message(key, lang)`` the lookup with
English fallback, and the server substitutes ``{{i18n:key}}``
placeholders in the page per-request (``?lang=xx``) — the same
template-substitution job Play's message interpolation does.
"""

from __future__ import annotations

import functools
import re
from typing import Dict, Optional

DEFAULT_LANGUAGE = "en"

_PLACEHOLDER = re.compile(r"\{\{i18n:([a-zA-Z0-9_.]+)\}\}")

_BUNDLES: Dict[str, Dict[str, str]] = {
    "en": {
        "train.nav.overview": "Overview",
        "train.nav.model": "Model",
        "train.nav.system": "System",
        "train.nav.activations": "Activations",
        "train.nav.tsne": "t-SNE",
        "train.nav.evaluation": "Evaluation",
        "train.overview.title": "Training overview",
        "train.overview.score": "Score vs iteration",
        "train.overview.throughput": "Samples/sec",
        "train.model.title": "Model graph",
        "train.system.title": "System",
        "train.activations.title": "Layer activations",
        "train.evaluation.title": "Evaluation",
    },
    "ja": {
        "train.nav.overview": "概要",
        "train.nav.model": "モデル",
        "train.nav.system": "システム",
        "train.nav.activations": "活性化",
        "train.nav.tsne": "t-SNE",
        "train.nav.evaluation": "評価",
        "train.overview.title": "トレーニング概要",
        "train.overview.score": "スコア/イテレーション",
        "train.overview.throughput": "サンプル/秒",
        "train.model.title": "モデルグラフ",
        "train.system.title": "システム",
        "train.activations.title": "レイヤー活性化",
        "train.evaluation.title": "評価",
    },
    "de": {
        "train.nav.overview": "Übersicht",
        "train.nav.model": "Modell",
        "train.nav.system": "System",
        "train.nav.activations": "Aktivierungen",
        "train.nav.tsne": "t-SNE",
        "train.nav.evaluation": "Auswertung",
        "train.overview.title": "Trainingsübersicht",
        "train.overview.score": "Score je Iteration",
        "train.overview.throughput": "Beispiele/Sekunde",
        "train.model.title": "Modellgraph",
        "train.system.title": "System",
        "train.activations.title": "Schicht-Aktivierungen",
        "train.evaluation.title": "Auswertung",
    },
}


class I18N:
    """DefaultI18N analog: singleton provider with a default language
    and per-key English fallback."""

    _instance: Optional["I18N"] = None

    def __init__(self):
        self.default_language = DEFAULT_LANGUAGE

    @classmethod
    def get_instance(cls) -> "I18N":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def languages(self):
        return sorted(_BUNDLES)

    def set_default_language(self, lang: str):
        if lang not in _BUNDLES:
            raise ValueError(f"unknown language {lang!r}; have "
                             f"{self.languages()}")
        self.default_language = lang

    def resolve_language(self, lang: Optional[str]) -> str:
        """The language actually served: unknown/absent codes fall back
        to the default (clients must see the EFFECTIVE language, not an
        echo of what they asked for)."""
        if lang and lang in _BUNDLES:
            return lang
        return self.default_language

    def get_message(self, key: str, lang: Optional[str] = None) -> str:
        bundle = _BUNDLES[self.resolve_language(lang)]
        return bundle.get(key, _BUNDLES[DEFAULT_LANGUAGE].get(key, key))

    def messages(self, lang: Optional[str] = None) -> Dict[str, str]:
        out = dict(_BUNDLES[DEFAULT_LANGUAGE])
        out.update(_BUNDLES[self.resolve_language(lang)])
        return out

    def render(self, template: str, lang: Optional[str] = None) -> str:
        """Substitute ``{{i18n:key}}`` placeholders (cached per
        language — the bundles and template are static)."""
        return _render_cached(self, template, self.resolve_language(lang))


@functools.lru_cache(maxsize=16)
def _render_cached(i18n: "I18N", template: str, lang: str) -> str:
    return _PLACEHOLDER.sub(
        lambda m: i18n.get_message(m.group(1), lang), template)
