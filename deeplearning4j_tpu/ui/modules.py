"""UI module SPI (reference: deeplearning4j-play/.../api/UIModule.java —
modules contribute Routes and receive the attached StatsStorage; the
Play server discovers them and merges their routes into the dashboard).

A module declares ``get_routes()`` → [Route]; ``UIServer.
register_module`` merges them (built-in routes win on conflict, like
the reference's core TrainModule). Handlers are plain callables:

    handler(ctx: UIModuleContext, query: dict, body: dict | None)
        -> dict (JSON) | (bytes, content_type)

``ctx.storage`` is the attached StatsStorage — the same object pushed
to the reference modules through onAttach/StatsStorageEvent.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional


@dataclasses.dataclass(frozen=True)
class Route:
    """One HTTP route contributed by a module (reference: Route.java —
    method + path + the function producing the result)."""
    method: str                    # "GET" | "POST"
    path: str                      # e.g. "/api/mymodule/data"
    handler: Callable              # handler(ctx, query, body)

    def __post_init__(self):
        if self.method not in ("GET", "POST"):
            raise ValueError(f"unsupported method {self.method!r}")
        if not self.path.startswith("/"):
            raise ValueError(f"route path must start with '/': "
                             f"{self.path!r}")


@dataclasses.dataclass
class UIModuleContext:
    """What a handler sees: the attached storage + the live server,
    plus the request headers (an ``email.message.Message``-like mapping,
    or None in direct-call tests) so handlers can read per-request
    metadata like ``X-Deadline-Ms``."""
    storage: object
    server: object
    headers: object = None


class UIModule:
    """SPI base (reference: UIModule.java). Subclass and implement
    ``get_routes``; override ``on_attach`` to observe the storage."""

    def get_routes(self) -> List[Route]:
        raise NotImplementedError

    def on_attach(self, storage) -> None:
        """Called when a StatsStorage is attached (reference:
        UIModule.onAttach)."""

    def on_update(self, record: dict) -> None:
        """Called for every remote-routed record the server receives
        (reference: UIModule.reportStorageEvents)."""
