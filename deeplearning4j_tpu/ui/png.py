"""Minimal dependency-free PNG encoding for the dashboard.

The reference's ConvolutionalListenerModule streams conv activations to
the UI as PNGs rendered with java.awt (deeplearning4j-play/.../
ConvolutionalListenerModule.java:1). Here: an 8-bit grayscale PNG writer
over zlib — enough for activation heat-maps, no imaging library needed.
"""

from __future__ import annotations

import struct
import zlib

import numpy as np


def _chunk(tag: bytes, payload: bytes) -> bytes:
    return (struct.pack(">I", len(payload)) + tag + payload
            + struct.pack(">I", zlib.crc32(tag + payload) & 0xFFFFFFFF))


def encode_png_gray(img: np.ndarray) -> bytes:
    """(H, W) array (any float/int range) → 8-bit grayscale PNG bytes.
    Floats are min-max scaled to [0, 255]."""
    a = np.asarray(img)
    if a.ndim != 2:
        raise ValueError(f"expected (H, W), got {a.shape}")
    if a.dtype != np.uint8:
        a = a.astype(np.float64)
        lo, hi = float(a.min()), float(a.max())
        a = ((a - lo) / (hi - lo or 1.0) * 255.0).astype(np.uint8)
    h, w = a.shape
    raw = b"".join(b"\x00" + a[i].tobytes() for i in range(h))
    return (b"\x89PNG\r\n\x1a\n"
            + _chunk(b"IHDR", struct.pack(">IIBBBBB", w, h, 8, 0, 0, 0, 0))
            + _chunk(b"IDAT", zlib.compress(raw, 6))
            + _chunk(b"IEND", b""))


def activation_grid(act: np.ndarray, max_channels: int = 64) -> np.ndarray:
    """(H, W, C) activation → one (gridH, gridW) mosaic of per-channel
    heat-maps (the reference UI's channel tile layout)."""
    a = np.asarray(act, np.float64)
    if a.ndim == 1:        # (N_features,) dense activations → one row
        # image, ONE channel — per-pixel tiles would each min-max
        # normalize to a black 1x1 square
        a = a[None, :, None]
    if a.ndim == 2:        # (H, W) single-channel map
        a = a[:, :, None]
    h, w, c = a.shape
    c = min(c, max_channels)
    cols = int(np.ceil(np.sqrt(c)))
    rows = int(np.ceil(c / cols))
    pad = 1
    grid = np.zeros((rows * (h + pad) + pad, cols * (w + pad) + pad))
    for i in range(c):
        r, col = divmod(i, cols)
        ch = a[:, :, i]
        lo, hi = ch.min(), ch.max()
        grid[pad + r * (h + pad): pad + r * (h + pad) + h,
             pad + col * (w + pad): pad + col * (w + pad) + w] = \
            (ch - lo) / ((hi - lo) or 1.0)
    return grid
