"""UI chart/table/text components with JSON serialization.

Analog of the reference's ``deeplearning4j-ui-components`` module
(SURVEY §2.12): typed chart/bean components (ChartLine, ChartHistogram,
ChartScatter, ComponentTable, ComponentText, StyleChart) that serialize
to JSON for a JS frontend. The UI server's endpoints emit these, and
they render standalone via :func:`render_html` (self-contained inline-SVG
export — no JS dependency, works air-gapped).
"""

from __future__ import annotations

import html as _html
import json
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple


def _esc(v) -> str:
    return _html.escape(str(v), quote=True)


@dataclass
class StyleChart:
    """(reference: StyleChart.Builder)"""
    width: int = 640
    height: int = 360
    title_size: int = 14
    series_colors: Tuple[str, ...] = ("#2563eb", "#dc2626", "#059669",
                                      "#d97706", "#7c3aed", "#0891b2")
    margin: int = 40

    def to_dict(self) -> dict:
        return {"width": self.width, "height": self.height,
                "titleSize": self.title_size,
                "seriesColors": list(self.series_colors),
                "margin": self.margin}


class Component:
    component_type = "component"

    def to_dict(self) -> dict:
        raise NotImplementedError

    def to_json(self) -> str:
        return json.dumps(self.to_dict())


@dataclass
class ChartLine(Component):
    """Multi-series line chart (reference: ChartLine.Builder.addSeries)."""
    title: str = ""
    style: StyleChart = field(default_factory=StyleChart)
    series: List[dict] = field(default_factory=list)
    component_type = "ChartLine"

    def add_series(self, name: str, x: Sequence[float],
                   y: Sequence[float]) -> "ChartLine":
        if len(x) != len(y):
            raise ValueError(f"series {name!r}: len(x)={len(x)} != "
                             f"len(y)={len(y)}")
        self.series.append({"name": name, "x": [float(v) for v in x],
                            "y": [float(v) for v in y]})
        return self

    def to_dict(self) -> dict:
        return {"componentType": self.component_type, "title": self.title,
                "style": self.style.to_dict(), "series": self.series}


@dataclass
class ChartScatter(ChartLine):
    component_type = "ChartScatter"


@dataclass
class ChartHistogram(Component):
    """Binned bars (reference: ChartHistogram.Builder.addBin)."""
    title: str = ""
    style: StyleChart = field(default_factory=StyleChart)
    bins: List[dict] = field(default_factory=list)
    component_type = "ChartHistogram"

    def add_bin(self, lower: float, upper: float, count: float
                ) -> "ChartHistogram":
        self.bins.append({"lower": float(lower), "upper": float(upper),
                          "count": float(count)})
        return self

    def to_dict(self) -> dict:
        return {"componentType": self.component_type, "title": self.title,
                "style": self.style.to_dict(), "bins": self.bins}


@dataclass
class ComponentTable(Component):
    """(reference: ComponentTable)"""
    header: List[str] = field(default_factory=list)
    rows: List[List[str]] = field(default_factory=list)
    title: str = ""
    component_type = "ComponentTable"

    def to_dict(self) -> dict:
        return {"componentType": self.component_type, "title": self.title,
                "header": self.header, "rows": self.rows}


@dataclass
class ComponentText(Component):
    """(reference: ComponentText)"""
    text: str = ""
    component_type = "ComponentText"

    def to_dict(self) -> dict:
        return {"componentType": self.component_type, "text": self.text}


@dataclass
class ComponentDiv(Component):
    """Container of child components (reference: ComponentDiv)."""
    children: List[Component] = field(default_factory=list)
    component_type = "ComponentDiv"

    def add(self, c: Component) -> "ComponentDiv":
        self.children.append(c)
        return self

    def to_dict(self) -> dict:
        return {"componentType": self.component_type,
                "children": [c.to_dict() for c in self.children]}


# ---------------------------------------------------------------------------
# standalone SVG/HTML rendering (air-gapped export)
# ---------------------------------------------------------------------------

def _svg_chart_line(c: ChartLine) -> str:
    st = c.style
    m, w, h = st.margin, st.width, st.height
    pw, ph = w - 2 * m, h - 2 * m
    all_x = [v for s in c.series for v in s["x"]] or [0.0, 1.0]
    all_y = [v for s in c.series for v in s["y"]] or [0.0, 1.0]
    x0, x1 = min(all_x), max(all_x)
    y0, y1 = min(all_y), max(all_y)
    if x1 == x0:
        x1 = x0 + 1
    if y1 == y0:
        y1 = y0 + 1
    sx = lambda v: m + (v - x0) / (x1 - x0) * pw
    sy = lambda v: h - m - (v - y0) / (y1 - y0) * ph
    parts = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{w}" '
             f'height="{h}">',
             f'<text x="{w//2}" y="{m//2}" text-anchor="middle" '
             f'font-size="{st.title_size}">{_esc(c.title)}</text>',
             f'<rect x="{m}" y="{m}" width="{pw}" height="{ph}" '
             f'fill="none" stroke="#888"/>']
    scatter = isinstance(c, ChartScatter)
    for i, s in enumerate(c.series):
        color = st.series_colors[i % len(st.series_colors)]
        pts = [(sx(x), sy(y)) for x, y in zip(s["x"], s["y"])]
        if scatter:
            parts += [f'<circle cx="{x:.1f}" cy="{y:.1f}" r="3" '
                      f'fill="{color}"/>' for x, y in pts]
        elif pts:
            d = " ".join(f"{x:.1f},{y:.1f}" for x, y in pts)
            parts.append(f'<polyline points="{d}" fill="none" '
                         f'stroke="{color}" stroke-width="1.5"/>')
        parts.append(f'<text x="{m + 4}" y="{m + 14 + 14 * i}" '
                     f'fill="{color}" font-size="11">{_esc(s["name"])}</text>')
    parts.append("</svg>")
    return "".join(parts)


def _svg_chart_histogram(c: ChartHistogram) -> str:
    st = c.style
    m, w, h = st.margin, st.width, st.height
    pw, ph = w - 2 * m, h - 2 * m
    if not c.bins:
        return f'<svg xmlns="http://www.w3.org/2000/svg" width="{w}" ' \
               f'height="{h}"></svg>'
    lo = min(b["lower"] for b in c.bins)
    hi = max(b["upper"] for b in c.bins)
    top = max(b["count"] for b in c.bins) or 1.0
    sx = lambda v: m + (v - lo) / ((hi - lo) or 1.0) * pw
    parts = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{w}" '
             f'height="{h}">',
             f'<text x="{w//2}" y="{m//2}" text-anchor="middle" '
             f'font-size="{st.title_size}">{_esc(c.title)}</text>']
    color = st.series_colors[0]
    for b in c.bins:
        x = sx(b["lower"])
        bw = max(sx(b["upper"]) - x - 1, 1)
        bh = b["count"] / top * ph
        parts.append(f'<rect x="{x:.1f}" y="{h - m - bh:.1f}" '
                     f'width="{bw:.1f}" height="{bh:.1f}" fill="{color}"/>')
    parts.append("</svg>")
    return "".join(parts)


def render_html(components: Sequence[Component],
                title: str = "dl4j-tpu report") -> str:
    """Self-contained HTML (inline SVG) for a list of components."""
    body = []
    for c in components:
        if isinstance(c, ChartHistogram):
            body.append(_svg_chart_histogram(c))
        elif isinstance(c, ChartLine):   # covers ChartScatter
            body.append(_svg_chart_line(c))
        elif isinstance(c, ComponentTable):
            rows = "".join(
                "<tr>" + "".join(f"<td>{_esc(v)}</td>" for v in r) + "</tr>"
                for r in c.rows)
            head = "".join(f"<th>{_esc(v)}</th>" for v in c.header)
            body.append(f"<h3>{_esc(c.title)}</h3><table border='1' "
                        f"cellpadding='4'><tr>{head}</tr>{rows}</table>")
        elif isinstance(c, ComponentText):
            body.append(f"<p>{_esc(c.text)}</p>")
        elif isinstance(c, ComponentDiv):
            body.append(render_html(c.children, title=""))
    inner = "\n".join(body)
    if not title:
        return inner
    return (f"<!DOCTYPE html><html><head><meta charset='utf-8'>"
            f"<title>{_esc(title)}</title></head><body>{inner}</body></html>")
