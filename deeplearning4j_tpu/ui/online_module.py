"""OnlineModule: UI routes for the online-learning runtime.

Rides the same UIModule SPI as serving_module.py, in front of an
``online.runtime.OnlineServing``:

- ``GET /api/online/stats`` — learner progress, stream counters,
  holdout depth, last promotion decision, sentinel state, pool view.
- ``POST /api/online/promote`` — run one promotion cycle NOW instead
  of waiting for the background interval; body ``{"force": true}``
  skips the score comparison (operator override — the sentinel still
  watches the result). Answers the full PromotionDecision.
- ``POST /api/online/rollback`` — manual param rollback to the
  standby captured at the last promotion.

The ``dl4j_online_*`` Prometheus series are scraped from the server's
existing ``/metrics``; this module only adds the JSON surface.
"""

from __future__ import annotations

from typing import List

from deeplearning4j_tpu.ui.modules import Route, UIModule


class OnlineModule(UIModule):
    def __init__(self, online):
        self.online = online

    def get_routes(self) -> List[Route]:
        return [
            Route("GET", "/api/online/stats", self._stats),
            Route("POST", "/api/online/promote", self._promote),
            Route("POST", "/api/online/rollback", self._rollback),
        ]

    def _stats(self, ctx, query, body):
        return self.online.stats()

    def _promote(self, ctx, query, body):
        force = bool((body or {}).get("force", False))
        d = self.online.promoter.run_once(force=force)
        return {
            "promoted": d.promoted, "reason": d.reason,
            "candidate_score": d.candidate_score,
            "active_score": d.active_score,
            "version": d.version, "iteration": d.iteration,
            "score_seconds": d.score_seconds,
            "over_budget": d.over_budget,
        }

    def _rollback(self, ctx, query, body):
        name = self.online.model_name
        try:
            pool = self.online.router.rollback_params(name)
        except RuntimeError as e:
            return ({"error": str(e)}, None, 409)
        self.online.promoter.notify_rollback()
        return {"model": name, "active_version": pool.active_version,
                "param_standby_version": pool.param_standby[0]
                if pool.param_standby else None}
