"""GenerationModule: the HTTP surface for decode serving.

- ``POST /api/generate``  {"prompt": str|[ids], "max_new_tokens"?,
  "greedy"?, "temperature"?, "top_k"?, "seed"?, "stop"?, "stream"?,
  "model"?}

  With ``"stream": true`` (the default) the response is a
  ``text/event-stream``: one ``data:`` event per sampled token
  (``{"token": id, "text": ch, "i": n}``) and a terminal
  ``{"done": true, "reason": ..., "n": ..., "ttft_ms": ...}`` —
  tokens arrive as they decode, riding ui/server.py's generator-payload
  streaming. ``"stream": false`` blocks and answers one JSON object.

  Behind a FleetRouter the submit passes admission control; a shed
  answers **HTTP 503** + Retry-After exactly like the predict route,
  BEFORE any stream bytes go out. An engine-only module maps its
  queue-full refusal the same way.

- ``GET /api/generation/stats``  engine snapshot: per-token p50/p99,
  time-to-first-token, active/max slots, retirement outcomes, stream
  errors, recompiles-after-warmup (plus admission state when routed).

The ``dl4j_gen_*`` Prometheus family is scraped from the server's
existing ``/metrics``; this module only adds the JSON/SSE ingress.
"""

from __future__ import annotations

import math
from typing import List

from deeplearning4j_tpu.ui.modules import Route, UIModule

_RESULT_TIMEOUT_S = 300.0


class GenerationModule(UIModule):
    """Routes for one GenerationEngine, optionally behind a
    FleetRouter's admission control (pass ``router`` + the pool's
    ``model`` name)."""

    def __init__(self, engine=None, router=None, model=None):
        if (engine is None) == (router is None):
            raise ValueError("pass exactly one of engine= or router=")
        self.engine = engine
        self.router = router
        self.model = model

    def get_routes(self) -> List[Route]:
        return [
            Route("POST", "/api/generate", self._generate),
            Route("GET", "/api/generation/stats", self._stats),
        ]

    def _submit(self, body):
        kw = {}
        for key in ("max_new_tokens", "top_k", "seed"):
            if key in body:
                kw[key] = int(body[key])
        if "temperature" in body:
            kw["temperature"] = float(body["temperature"])  # host-sync-ok: request parsing, host scalar
        if "greedy" in body:
            kw["greedy"] = bool(body["greedy"])
        if "stop" in body:
            kw["stop"] = body["stop"]
        prompt = body.get("prompt", "")
        if self.router is not None:
            return self.router.generate(
                prompt, model=body.get("model", self.model), **kw)
        return self.engine.submit(prompt, **kw)

    def _generate(self, ctx, query, body):
        from deeplearning4j_tpu.parallel.fleet import ShedError
        if not isinstance(body, dict):
            raise ValueError('expected {"prompt": ...}')
        try:
            stream = self._submit(body)
        except ShedError as e:
            retry_after = max(1, int(math.ceil(
                getattr(self.router, "window_s", 1.0))))
            return ({"error": "shed", "model": e.model,
                     "reason": e.reason},
                    {"Retry-After": str(retry_after)}, 503)
        except RuntimeError as e:
            if "queue full" in str(e):
                return ({"error": "shed", "reason": "queue"},
                        {"Retry-After": "1"}, 503)
            raise
        if not body.get("stream", True):
            res = stream.result(timeout=_RESULT_TIMEOUT_S)
            vocab = self._vocab()
            res["text"] = vocab.decode(res["ids"]) if vocab else None
            return res
        return self._sse(stream)

    def _vocab(self):
        if self.engine is not None:
            return self.engine.vocab
        try:
            return self.router.generation_pool(self.model).engine.vocab
        except Exception:
            return None

    def _sse(self, stream):
        """Generator payload for ui/server.py's event-stream path. The
        server close()s this generator when the client disconnects
        mid-stream; the finally turns that into a cancel so the engine
        retires the slot instead of decoding into the void."""
        def events():
            try:
                for ev in stream:
                    yield ev
            finally:
                if not stream.done:
                    stream.cancel()
        return events()

    def _stats(self, ctx, query, body):
        if self.router is not None:
            return self.router.generation_pool(self.model).stats()
        return self.engine.stats()
