"""GenerationModule: the HTTP surface for decode serving.

- ``POST /api/generate``  {"prompt": str|[ids], "max_new_tokens"?,
  "greedy"?, "temperature"?, "top_k"?, "seed"?, "stop"?, "stream"?,
  "model"?, "session"?}

  ``"session"`` names a resumable carry: the engine continues the
  session's (h, c)/PRNG state instead of replaying the prefix, and
  re-captures it when the sequence retires — including across nodes
  via the shared ArtifactStore checkpoint (see generation/session.py).
  The terminal event echoes the token back.

  With ``"stream": true`` (the default) the response is a
  ``text/event-stream``: one ``data:`` event per sampled token
  (``{"token": id, "text": ch, "i": n}``) and a terminal
  ``{"done": true, "reason": ..., "n": ..., "ttft_ms": ...}`` —
  tokens arrive as they decode, riding ui/server.py's generator-payload
  streaming. ``"stream": false`` blocks and answers one JSON object.

  Behind a FleetRouter the submit passes admission control; a shed
  answers **HTTP 503** + Retry-After exactly like the predict route,
  BEFORE any stream bytes go out. An engine-only module maps its
  queue-full refusal the same way. An ``X-Deadline-Ms`` header or
  ``"deadline_ms"`` body field arms an end-to-end deadline: expired at
  submit → **HTTP 504** ``{"error": "deadline"}`` before any stream
  bytes; expired mid-decode → the sequence retires with reason
  ``"deadline"``. A client that disconnects mid-stream cancels its
  sequence and frees the slot (``dl4j_gen_client_disconnect_total``).

- ``GET /api/generation/stats``  engine snapshot: per-token p50/p99,
  time-to-first-token, active/max slots, retirement outcomes, stream
  errors, recompiles-after-warmup (plus admission state when routed).

The ``dl4j_gen_*`` Prometheus family is scraped from the server's
existing ``/metrics``; this module only adds the JSON/SSE ingress.
"""

from __future__ import annotations

import math
from typing import List

from deeplearning4j_tpu.parallel.deadline import Deadline, DeadlineExceeded
from deeplearning4j_tpu.ui.modules import Route, UIModule

_RESULT_TIMEOUT_S = 300.0


class GenerationModule(UIModule):
    """Routes for one GenerationEngine, optionally behind a
    FleetRouter's admission control (pass ``router`` + the pool's
    ``model`` name)."""

    def __init__(self, engine=None, router=None, model=None):
        if (engine is None) == (router is None):
            raise ValueError("pass exactly one of engine= or router=")
        self.engine = engine
        self.router = router
        self.model = model

    def get_routes(self) -> List[Route]:
        return [
            Route("POST", "/api/generate", self._generate),
            Route("GET", "/api/generation/stats", self._stats),
        ]

    def _submit(self, body, deadline=None):
        kw = {}
        for key in ("max_new_tokens", "top_k", "seed"):
            if key in body:
                kw[key] = int(body[key])
        if "temperature" in body:
            kw["temperature"] = float(body["temperature"])  # host-sync-ok: request parsing, host scalar
        if "greedy" in body:
            kw["greedy"] = bool(body["greedy"])
        if "stop" in body:
            kw["stop"] = body["stop"]
        if body.get("session") is not None:
            # resumable-session token: the engine restores the carry
            # (local tier or cross-node store checkpoint) and re-saves
            # it at retirement; behind a router it also picks the pool
            # already holding the carry (session affinity)
            kw["session"] = str(body["session"])
        prompt = body.get("prompt", "")
        if self.router is not None:
            return self.router.generate(
                prompt, model=body.get("model", self.model),
                deadline=deadline, **kw)
        return self.engine.submit(prompt, deadline=deadline, **kw)

    def _generate(self, ctx, query, body):
        from deeplearning4j_tpu.parallel.fleet import ShedError
        if not isinstance(body, dict):
            raise ValueError('expected {"prompt": ...}')
        deadline = Deadline.from_ingress(getattr(ctx, "headers", None), body)
        try:
            stream = self._submit(body, deadline=deadline)
        except DeadlineExceeded:
            return ({"error": "deadline", "reason": "deadline"},
                    None, 504)
        except ShedError as e:
            if e.reason == "deadline":
                return ({"error": "deadline", "model": e.model,
                         "reason": "deadline"}, None, 504)
            retry_after = max(1, int(math.ceil(
                getattr(self.router, "window_s", 1.0))))
            return ({"error": "shed", "model": e.model,
                     "reason": e.reason},
                    {"Retry-After": str(retry_after)}, 503)
        except RuntimeError as e:
            if "queue full" in str(e):
                return ({"error": "shed", "reason": "queue"},
                        {"Retry-After": "1"}, 503)
            raise
        if not body.get("stream", True):
            res = stream.result(timeout=_RESULT_TIMEOUT_S)
            vocab = self._vocab()
            res["text"] = vocab.decode(res["ids"]) if vocab else None
            if res.get("reason") == "deadline":
                # budget ran out mid-decode: the partial result ships,
                # but under 504 so the caller knows it was truncated
                return (res, None, 504)
            return res
        return self._sse(stream)

    def _engine(self):
        if self.engine is not None:
            return self.engine
        try:
            return self.router.generation_pool(self.model).engine
        except Exception:
            return None

    def _vocab(self):
        eng = self._engine()
        return eng.vocab if eng is not None else None

    def _sse(self, stream):
        """Generator payload for ui/server.py's event-stream path. The
        server close()s this generator when the client disconnects
        mid-stream; the finally turns that into an engine-level cancel
        (``dl4j_gen_client_disconnect_total``) so the scheduler retires
        the slot — even one still in prefill — and frees it for the
        next sequence instead of decoding into the void."""
        def events():
            try:
                for ev in stream:
                    yield ev
            finally:
                if not stream.done:
                    eng = self._engine()
                    if eng is not None:
                        eng.cancel(stream, disconnect=True)
                    else:
                        stream.cancel()
        return events()

    def _stats(self, ctx, query, body):
        if self.router is not None:
            return self.router.generation_pool(self.model).stats()
        return self.engine.stats()
