"""ServingModule / FleetModule: UI routes for live serving.

Plugs the serving engine (parallel/serving.py) and the fleet router
(parallel/fleet.py) into the dashboard via the UIModule SPI — the same
extension point custom reference modules use (UIModule.java).

``ServingModule`` (one engine):

- ``POST /api/predict``   {"features": [[...], ...]} -> {"output": ...}
  A convenience ingress for smoke tests and the CLI demo; production
  traffic should call ``ServingEngine.submit`` in-process. Requests ride
  the exact same queue/batching path, so a curl during a load test lands
  in the same buckets as everything else.
- ``GET /api/serving/stats``  engine snapshot: streaming p50/p95/p99,
  in-flight depth, queue depth, ladder, recompiles-after-warmup.

``FleetModule`` (a FleetRouter in front of one or more pools) replaces
the predict route with the admission-controlled one and adds the fleet
lifecycle surface:

- ``POST /api/predict``  {"features": ..., "model": optional} — passes
  through the router's admission control; a shed request answers **HTTP
  503** with ``{"error": "shed", "model": ..., "reason": "queue"|"slo"}``
  so external load balancers can react (retry-after, spillover).

Both predict routes honor an end-to-end deadline: ``X-Deadline-Ms``
header or ``"deadline_ms"`` body field (body wins). An expired budget
answers **HTTP 504** ``{"error": "deadline"}`` — shed synchronously at
whichever tier noticed first (admission, batch forming, dispatch), so
an expired request never reaches the device.
- ``GET /api/fleet/stats``  router snapshot: per-pool active/standby
  version, pending depth, shed fraction, windowed p99.
- ``POST /api/fleet/swap``  {"model": name, "version": v, "path": zip}
  restores the weights at ``path`` and hot-swaps them in (warm-first,
  atomic switch, previous version kept as standby).
- ``POST /api/fleet/rollback``  {"model": name} — back to the standby.

The Prometheus series (``dl4j_serving_*``, ``dl4j_fleet_*``) are
scraped from the server's existing ``/metrics``; these modules only add
the JSON/ingress surface.
"""

from __future__ import annotations

from typing import List

import numpy as np

from deeplearning4j_tpu.parallel.deadline import Deadline, DeadlineExceeded
from deeplearning4j_tpu.ui.modules import Route, UIModule


def _deadline_response(model=None):
    """504 Gateway Timeout: the request's own deadline ran out — not an
    overload (503, retryable here) and not a bug (500). No Retry-After:
    re-sending the same expired budget cannot succeed."""
    out = {"error": "deadline", "reason": "deadline"}
    if model is not None:
        out["model"] = model
    return (out, None, 504)


class ServingModule(UIModule):
    def __init__(self, engine):
        self.engine = engine

    def get_routes(self) -> List[Route]:
        return [
            Route("POST", "/api/predict", self._predict),
            Route("GET", "/api/serving/stats", self._stats),
        ]

    def _predict(self, ctx, query, body):
        if not isinstance(body, dict) or "features" not in body:
            raise ValueError('expected {"features": [[...], ...]}')
        deadline = Deadline.from_ingress(getattr(ctx, "headers", None), body)
        x = np.asarray(body["features"],  # host-sync-ok: decoding the JSON request body, already host data
                       dtype=self.engine.dtype)
        try:
            # forward the deadline only when the client sent one, so
            # duck-typed engines without the kwarg keep working
            out = (self.engine.output(x, deadline=deadline)
                   if deadline is not None else self.engine.output(x))
        except DeadlineExceeded:
            return _deadline_response()
        return {"output": np.asarray(out).tolist(),  # host-sync-ok: HTTP response must be host JSON
                "n": int(x.shape[0])}

    def _stats(self, ctx, query, body):
        return self.engine.stats()


class FleetModule(UIModule):
    """Routes for a FleetRouter front door (see module docstring)."""

    def __init__(self, router):
        self.router = router

    def get_routes(self) -> List[Route]:
        return [
            Route("POST", "/api/predict", self._predict),
            Route("GET", "/api/fleet/stats", self._stats),
            Route("POST", "/api/fleet/swap", self._swap),
            Route("POST", "/api/fleet/rollback", self._rollback),
        ]

    def _predict(self, ctx, query, body):
        from deeplearning4j_tpu.parallel.fleet import ShedError
        if not isinstance(body, dict) or "features" not in body:
            raise ValueError('expected {"features": [[...], ...]}')
        deadline = Deadline.from_ingress(getattr(ctx, "headers", None), body)
        x = np.asarray(body["features"], dtype=np.float32)  # host-sync-ok: decoding the JSON request body, already host data
        try:
            # forward the deadline only when the client sent one, so
            # duck-typed routers without the kwarg keep working
            out = (self.router.output(x, model=body.get("model"),
                                      deadline=deadline)
                   if deadline is not None
                   else self.router.output(x, model=body.get("model")))
        except DeadlineExceeded:
            return _deadline_response(model=body.get("model"))
        except ShedError as e:
            if e.reason == "deadline":
                return _deadline_response(model=e.model)
            # 503 = "overloaded, retry elsewhere/later" — distinct from
            # a 500 module bug, and the worker/soak driver counts it.
            # Retry-After tells remote retries to back off instead of
            # hammering: one p99 window is when the AIMD controller's
            # view of this pool can actually have changed
            import math
            retry_after = max(1, int(math.ceil(
                getattr(self.router, "window_s", 1.0))))
            return ({"error": "shed", "model": e.model,
                     "reason": e.reason},
                    {"Retry-After": str(retry_after)}, 503)
        return {"output": np.asarray(out).tolist(),  # host-sync-ok: HTTP response must be host JSON
                "n": int(x.shape[0])}

    def _stats(self, ctx, query, body):
        return self.router.stats()

    def _swap(self, ctx, query, body):
        if not isinstance(body, dict) or "version" not in body \
                or "path" not in body:
            raise ValueError(
                'expected {"model": name?, "version": v, "path": zip}')
        from deeplearning4j_tpu.models.serialization import restore_model
        name = body.get("model") or self.router.pool().name
        model = restore_model(body["path"])
        pool = self.router.swap(name, model, str(body["version"]))
        return {"model": name, "active_version": pool.active_version,
                "standby_version": pool.standby[0] if pool.standby
                else None}

    def _rollback(self, ctx, query, body):
        name = (body or {}).get("model") or self.router.pool().name
        pool = self.router.rollback(name)
        return {"model": name, "active_version": pool.active_version,
                "standby_version": pool.standby[0] if pool.standby
                else None}
