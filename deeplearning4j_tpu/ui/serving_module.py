"""ServingModule: UI routes for a live ServingEngine.

Plugs the serving engine (parallel/serving.py) into the dashboard via
the UIModule SPI — the same extension point custom reference modules
use (UIModule.java). Two routes:

- ``POST /api/predict``   {"features": [[...], ...]} -> {"output": ...}
  A convenience ingress for smoke tests and the CLI demo; production
  traffic should call ``ServingEngine.submit`` in-process. Requests ride
  the exact same queue/batching path, so a curl during a load test lands
  in the same buckets as everything else.
- ``GET /api/serving/stats``  engine snapshot: streaming p50/p95/p99,
  in-flight depth, queue depth, ladder, recompiles-after-warmup.

The Prometheus series the engine publishes (``dl4j_serving_*``) are
scraped from the server's existing ``/metrics``; this module only adds
the JSON/ingress surface.
"""

from __future__ import annotations

from typing import List

import numpy as np

from deeplearning4j_tpu.ui.modules import Route, UIModule


class ServingModule(UIModule):
    def __init__(self, engine):
        self.engine = engine

    def get_routes(self) -> List[Route]:
        return [
            Route("POST", "/api/predict", self._predict),
            Route("GET", "/api/serving/stats", self._stats),
        ]

    def _predict(self, ctx, query, body):
        if not isinstance(body, dict) or "features" not in body:
            raise ValueError('expected {"features": [[...], ...]}')
        x = np.asarray(body["features"],  # host-sync-ok: decoding the JSON request body, already host data
                       dtype=self.engine.dtype)
        out = self.engine.output(x)
        return {"output": np.asarray(out).tolist(),  # host-sync-ok: HTTP response must be host JSON
                "n": int(x.shape[0])}

    def _stats(self, ctx, query, body):
        return self.engine.stats()
