"""Observability: stats collection, storage, web dashboard.

TPU-native analog of deeplearning4j-ui-parent (SURVEY §2.12): the
StatsListener → StatsStorage → UI server pipeline, with HTTP-POST remote
routing for multi-host training (§5.5). SBE binary codecs become compact
JSON records; the Play server becomes a dependency-free http.server
dashboard.
"""

from deeplearning4j_tpu.ui.stats import StatsListener
from deeplearning4j_tpu.ui.storage import (
    InMemoryStatsStorage,
    SqliteStatsStorage,
    RemoteUIStatsStorageRouter,
)
from deeplearning4j_tpu.ui.server import UIServer
from deeplearning4j_tpu.ui.tsne_listener import TsneListener

__all__ = ["StatsListener", "InMemoryStatsStorage", "SqliteStatsStorage",
           "RemoteUIStatsStorageRouter", "UIServer", "TsneListener"]
