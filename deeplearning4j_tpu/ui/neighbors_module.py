"""NeighborsModule: HTTP ingress for nearest-neighbor retrieval.

The JSON face of retrieval/engine.py behind a FleetRouter retrieval
pool — the modern replacement for the legacy NearestNeighborsServer
(clustering/server.py, now a shim over this stack).

- ``POST /api/neighbors``  {"vector": [...]} (single) or
  {"queries": [[...], ...]} (batch), optional ``k`` (default 10),
  ``mode`` ("brute"|"ivf"), ``deadline_ms``/``X-Deadline-Ms``.
  Rides the pool's admission control: a shed answers HTTP 503 with
  ``Retry-After`` (one AIMD window), an expired deadline answers 504 —
  identical semantics to ``/api/predict`` so load balancers and the
  RemoteDispatcher treat both ingresses the same way.
- ``POST /api/neighbors/shard``  internal scatter-gather target used
  by NeighborsDispatcher: same body plus ``"shards": [ids]`` limiting
  the search to this node's slice of the corpus. Also rides admission —
  fan-out legs inherit shed/deadline semantics, and a 503 here is a
  retriable attempt for the dispatcher's breaker, not an error.
- ``GET  /api/neighbors/stats``  engine + pool snapshot.
- ``POST /api/neighbors/refresh``  {"key": optional} — gated hot
  promotion of a rebuilt index from the ArtifactStore (geometry must
  match the warmed executables; self-recall gate; zero live compiles).
  404-less: answers the refresh outcome dict (promoted|rejected|noop).

Distances are squared L2 (the kernel's native metric); ids are corpus
row ids, ``-1`` marking padded "no result" slots (k larger than the
corpus slice). The ``dl4j_nn_*`` series are scraped from ``/metrics``.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.parallel.deadline import Deadline, DeadlineExceeded
from deeplearning4j_tpu.ui.modules import Route, UIModule
from deeplearning4j_tpu.ui.serving_module import _deadline_response

DEFAULT_K = 10


class NeighborsModule(UIModule):
    def __init__(self, router, *, model: str = "neighbors",
                 store=None, index_key: Optional[str] = None):
        self.router = router
        self.model = model
        self.store = store
        self.index_key = index_key

    def get_routes(self) -> List[Route]:
        return [
            Route("POST", "/api/neighbors", self._neighbors),
            Route("POST", "/api/neighbors/shard", self._shard),
            Route("GET", "/api/neighbors/stats", self._stats),
            Route("POST", "/api/neighbors/refresh", self._refresh),
        ]

    # ---- request decoding ----------------------------------------------
    @staticmethod
    def _decode(body):
        if not isinstance(body, dict) or \
                ("vector" not in body and "queries" not in body):
            raise ValueError('expected {"vector": [...]} or '
                             '{"queries": [[...], ...]}')
        if "vector" in body:
            q = np.asarray(body["vector"], np.float32)  # host-sync-ok: decoding the JSON request body, already host data
            if q.ndim != 1:
                raise ValueError('"vector" must be a flat list')
            return q, True
        q = np.asarray(body["queries"], np.float32)  # host-sync-ok: decoding the JSON request body, already host data
        if q.ndim != 2:
            raise ValueError('"queries" must be a list of rows')
        return q, False

    def _search(self, ctx, body, **extra):
        from deeplearning4j_tpu.parallel.fleet import ShedError
        # malformed client input is a 400, not a 500 module bug (same
        # contract the legacy /knn surface kept)
        try:
            q, single = self._decode(body)
            k = int(body.get("k", DEFAULT_K))
        except (ValueError, TypeError) as e:
            return ({"error": str(e)}, None, 400)
        deadline = Deadline.from_ingress(getattr(ctx, "headers", None),
                                         body)
        try:
            d, i = self.router.neighbors(
                q, k, model=self.model, mode=body.get("mode"),
                deadline=deadline, **extra)
        except DeadlineExceeded:
            return _deadline_response(model=self.model)
        except ValueError as e:
            # e.g. k above the warmed ladder — client input, not a bug
            return ({"error": str(e)}, None, 400)
        except ShedError as e:
            if e.reason == "deadline":
                return _deadline_response(model=e.model)
            import math
            retry_after = max(1, int(math.ceil(
                getattr(self.router, "window_s", 1.0))))
            return ({"error": "shed", "model": e.model,
                     "reason": e.reason},
                    {"Retry-After": str(retry_after)}, 503)
        pool = self.router.retrieval_pool(self.model)
        out = {"distances": np.asarray(d).tolist(),  # host-sync-ok: HTTP response must be host JSON
               "ids": np.asarray(i).tolist(),  # host-sync-ok: HTTP response must be host JSON
               "k": k, "n": 1 if single else int(q.shape[0]),
               "index_version": pool.engine.version}
        return out

    # ---- routes ----------------------------------------------------------
    def _neighbors(self, ctx, query, body):
        return self._search(ctx, body)

    def _shard(self, ctx, query, body):
        if not isinstance(body, dict) or "shards" not in body:
            raise ValueError('expected {"queries": ..., "shards": [...]}')
        shard_ids = [int(s) for s in body["shards"]]
        engine = self.router.retrieval_pool(self.model).engine
        # answer only the slice this node actually holds; the
        # dispatcher treats unserved shards as missing and retries
        # them on a replica
        local = [s for s in shard_ids if s in set(engine.shard_ids)]
        if not local:
            return ({"error": "no local shards",
                     "requested": shard_ids,
                     "local": list(engine.shard_ids)}, None, 404)
        return self._search(ctx, body, shard_ids=local)

    def _stats(self, ctx, query, body):
        out = dict(self.router.stats())
        pool = self.router.retrieval_pool(self.model)
        out["engine"] = pool.engine.stats()
        return out

    def _refresh(self, ctx, query, body):
        body = body or {}
        key = body.get("key") or self.index_key
        if self.store is None or not key:
            return ({"error": "no artifact store wired for refresh"},
                    None, 503)
        engine = self.router.retrieval_pool(self.model).engine
        return engine.refresh(self.store, key)
