"""Compact binary wire codec for stats records.

Fills the role of the reference's SBE stats codecs
(deeplearning4j-ui-parent/deeplearning4j-ui-model/.../stats/sbe/ —
UpdateEncoder/StaticInfoEncoder): training-stats records travel and
persist as a compact type-tagged binary format instead of JSON
(VERDICT r3 #8). Numeric arrays ride the SAME self-describing frame
format as the streaming module (streaming/serde.py serialize_ndarray),
so histograms/param summaries serialize at raw little-endian width —
the dominant payload — while scalars/keys use a minimal tag+payload
scheme. No pickle anywhere: decoding is bounds-checked and safe on
untrusted bytes; unknown tags raise.

JSON remains the dashboard-facing representation (the HTTP GET API) —
this codec covers listener → storage → remote-router transport.
"""

from __future__ import annotations

import struct
from typing import Any

import numpy as np

from deeplearning4j_tpu.streaming.serde import (
    deserialize_ndarray,
    serialize_ndarray,
)

MAGIC = b"DL4JSTA1"

_T_NONE = 0
_T_FALSE = 1
_T_TRUE = 2
_T_INT = 3          # int64 LE
_T_FLOAT = 4        # float64 LE
_T_STR = 5          # u32 len + utf-8
_T_LIST = 6         # u32 count + items
_T_DICT = 7         # u32 count + (str key, value) pairs
_T_NDARRAY = 8      # u32 len + streaming/serde frame

_MAX_ITEMS = 1 << 24        # sanity caps for untrusted input
_MAX_STR = 1 << 26


def _enc(value: Any, out: list):
    if value is None:
        out.append(bytes([_T_NONE]))
    elif value is True:
        out.append(bytes([_T_TRUE]))
    elif value is False:
        out.append(bytes([_T_FALSE]))
    elif isinstance(value, (int, np.integer)):
        out.append(struct.pack("<Bq", _T_INT, int(value)))
    elif isinstance(value, (float, np.floating)):
        out.append(struct.pack("<Bd", _T_FLOAT, float(value)))
    elif isinstance(value, str):
        b = value.encode("utf-8")
        out.append(struct.pack("<BI", _T_STR, len(b)))
        out.append(b)
    elif isinstance(value, np.ndarray):
        # stats payloads travel at f32 width, like the reference's SBE
        # UpdateEncoder (histogram/summary floats are 32-bit on its
        # wire too); integer arrays keep their exact dtype. NOTE
        # (advisor r4): f64 arrays — and numeric lists of >=8 items via
        # the fast path below — are quantized to f32 on this wire;
        # tuples decode as lists. Callers needing exact f64 round-trips
        # should keep values as scalars or short (<8) lists.
        if value.dtype == np.float64:
            value = value.astype(np.float32)
        frame = serialize_ndarray(value)
        out.append(struct.pack("<BI", _T_NDARRAY, len(frame)))
        out.append(frame)
    elif isinstance(value, dict):
        out.append(struct.pack("<BI", _T_DICT, len(value)))
        for k, v in value.items():
            kb = str(k).encode("utf-8")
            out.append(struct.pack("<I", len(kb)))
            out.append(kb)
            _enc(v, out)
    elif isinstance(value, (list, tuple)):
        # homogeneous numeric lists (histograms, norms) ride the array
        # frame — that is where the bytes are
        if len(value) >= 8:
            arr = np.asarray(value)
            if arr.dtype.kind in "if" and arr.ndim >= 1:
                _enc(arr, out)
                return
        out.append(struct.pack("<BI", _T_LIST, len(value)))
        for v in value:
            _enc(v, out)
    else:
        raise TypeError(f"stats codec: unsupported type {type(value)}")


def encode_stats_record(record: dict) -> bytes:
    """record dict → compact binary bytes (MAGIC + encoded dict)."""
    out = [MAGIC]
    _enc(record, out)
    return b"".join(out)


class _Reader:
    def __init__(self, data: bytes, off: int):
        self.data = data
        self.off = off

    def take(self, n: int) -> bytes:
        if n < 0 or self.off + n > len(self.data):
            raise ValueError("truncated stats record")
        b = self.data[self.off:self.off + n]
        self.off += n
        return b

    def u32(self) -> int:
        return struct.unpack("<I", self.take(4))[0]


def _dec(r: _Reader) -> Any:
    tag = r.take(1)[0]
    if tag == _T_NONE:
        return None
    if tag == _T_TRUE:
        return True
    if tag == _T_FALSE:
        return False
    if tag == _T_INT:
        return struct.unpack("<q", r.take(8))[0]
    if tag == _T_FLOAT:
        return struct.unpack("<d", r.take(8))[0]
    if tag == _T_STR:
        n = r.u32()
        if n > _MAX_STR:
            raise ValueError("string exceeds cap")
        return r.take(n).decode("utf-8")
    if tag == _T_NDARRAY:
        n = r.u32()
        arr, _ts = deserialize_ndarray(r.take(n))
        # lists went in, lists come out: storage/dashboard consumers
        # expect JSON-shaped records
        return arr.tolist()
    if tag == _T_LIST:
        n = r.u32()
        if n > _MAX_ITEMS:
            raise ValueError("list exceeds cap")
        return [_dec(r) for _ in range(n)]
    if tag == _T_DICT:
        n = r.u32()
        if n > _MAX_ITEMS:
            raise ValueError("dict exceeds cap")
        out = {}
        for _ in range(n):
            kn = r.u32()
            if kn > _MAX_STR:
                raise ValueError("key exceeds cap")
            k = r.take(kn).decode("utf-8")
            out[k] = _dec(r)
        return out
    raise ValueError(f"unknown tag {tag}")


def decode_stats_record(data: bytes) -> dict:
    if data[:len(MAGIC)] != MAGIC:
        raise ValueError("bad magic; not a stats record")
    r = _Reader(data, len(MAGIC))
    rec = _dec(r)
    if not isinstance(rec, dict):
        raise ValueError("stats record root must be a dict")
    return rec


def is_stats_record(data: bytes) -> bool:
    return data[:len(MAGIC)] == MAGIC
