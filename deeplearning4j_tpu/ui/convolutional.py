"""ConvolutionalListener — per-layer activation visualization.

Analog of the reference's ConvolutionalIterationListener +
ConvolutionalListenerModule (deeplearning4j-play/.../module/convolutional/
ConvolutionalListenerModule.java:1): every ``frequency`` iterations,
forward one example from the current batch, tile each layer's activation
channels into a heat-map mosaic, PNG-encode, and route to the stats
storage; the dashboard's Activations tab (ui/server.py) shows them live.
"""

from __future__ import annotations

import base64
import time
import uuid
from typing import Optional

import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.ui.png import activation_grid, encode_png_gray
from deeplearning4j_tpu.ui.storage import StatsStorageRouter


class ConvolutionalListener(TrainingListener):
    def __init__(self, router: StatsStorageRouter,
                 session_id: Optional[str] = None,
                 frequency: int = 10, max_channels: int = 64):
        self.router = router
        self.session_id = session_id or f"sess_{uuid.uuid4().hex[:10]}"
        self.frequency = max(1, frequency)
        self.max_channels = max_channels
        self._example: Optional[np.ndarray] = None

    def set_example(self, features) -> "ConvolutionalListener":
        """Pin the example to visualize (first row used); the fit loop
        does not hand listeners the batch, so one must be pinned."""
        self._example = np.asarray(features)[:1]
        return self

    def iteration_done(self, model, iteration: int, epoch: int, loss,
                      etl_ms: float, batch_size: int):
        if iteration % self.frequency != 0 or self._example is None:
            return
        if not hasattr(model, "feed_forward"):
            return
        acts = model.feed_forward(self._example, train=False)
        images = {}
        names = getattr(model, "layer_names",
                        [f"layer_{i}" for i in range(len(acts))])
        for name, act in zip(names, acts):
            a = np.asarray(act[0], np.float64)   # drop batch dim
            if a.ndim not in (1, 2, 3):
                continue
            grid = activation_grid(a, self.max_channels)
            # keep tiles readable: upscale tiny mosaics
            scale = max(1, 128 // max(grid.shape))
            if scale > 1:
                grid = np.kron(grid, np.ones((scale, scale)))
            images[name] = base64.b64encode(
                encode_png_gray(grid)).decode()
        self.router.put_update({
            "session_id": self.session_id,
            "worker_id": "w0",
            "timestamp": time.time(),
            "iteration": iteration,
            "type": "activations",
            "activations_png": images,
        })
