"""Auto-populating t-SNE listener (reference: the Play UI's TsneModule,
which only accepted manual coordinate uploads — VERDICT r3 #9 asks the
dashboard to be self-serve).

Attach next to the StatsListener; every ``frequency`` iterations it
embeds a held-out example batch through the live model, runs t-SNE on a
chosen activation layer in a BACKGROUND thread (t-SNE is seconds of CPU
— training never blocks on it), and pushes the coordinates to the
UIServer's t-SNE tab."""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener


class TsneListener(TrainingListener):
    def __init__(self, server, frequency: int = 50,
                 layer_index: int = -2, max_points: int = 300,
                 perplexity: float = 20.0, n_iter: int = 250):
        self.server = server
        self.frequency = max(1, frequency)
        self.layer_index = layer_index
        self.max_points = max_points
        self.perplexity = perplexity
        self.n_iter = n_iter
        self._feats: Optional[np.ndarray] = None
        self._labels = None
        self._worker: Optional[threading.Thread] = None

    def set_example(self, features, labels=None) -> "TsneListener":
        self._feats = np.asarray(features)[:self.max_points]
        if labels is not None:
            self._labels = [str(l) for l in
                            np.asarray(labels)[:self.max_points]]
        return self

    def iteration_done(self, model, iteration, epoch, loss, etl_ms,
                       batch_size):
        if self._feats is None or iteration % self.frequency:
            return
        if self._worker is not None and self._worker.is_alive():
            return                      # previous embedding still running
        ff = getattr(model, "feed_forward", None)
        if ff is None:                  # ComputationGraph: final output
            acts = np.asarray(model.output(self._feats))
        else:
            acts = np.asarray(ff(self._feats)[self.layer_index])
        acts = acts.reshape(acts.shape[0], -1)

        def run():
            from deeplearning4j_tpu.manifold.tsne import Tsne
            coords = Tsne(n_components=2, perplexity=self.perplexity,
                          n_iter=self.n_iter).fit_transform(acts)
            self.server.upload_tsne(coords, self._labels)

        self._worker = threading.Thread(target=run, daemon=True)
        self._worker.start()
