"""UIServer: embedded training dashboard.

Analog of the reference's PlayUIServer (deeplearning4j-play/.../
PlayUIServer.java:53, SURVEY §2.12): attach a StatsStorage, serve the
train overview (score chart, throughput), per-layer mean-magnitude
charts, system info, and receive remote-routed records
(RemoteReceiverModule analog at POST /remote). Zero dependencies: a
ThreadingHTTPServer + one self-contained HTML page drawing charts on a
<canvas>.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu.ui.storage import StatsStorage

_PAGE = """<!doctype html>
<html><head><title>deeplearning4j_tpu training UI</title><style>
body{font-family:sans-serif;margin:20px;background:#fafafa}
h2{margin:8px 0} .card{background:#fff;border:1px solid #ddd;
border-radius:6px;padding:12px;margin-bottom:14px}
canvas{width:100%;height:220px} td,th{padding:2px 10px;text-align:left}
</style></head><body>
<h2>Training overview</h2>
<div class=card><b>Score vs iteration</b><canvas id=score></canvas></div>
<div class=card><b>Samples/sec</b><canvas id=tput></canvas></div>
<div class=card><b>Per-layer mean |param|</b><canvas id=pm></canvas></div>
<div class=card><b>Session</b><table id=info></table></div>
<script>
function draw(cv, series, labels){
  const c = cv.getContext('2d');
  const W = cv.width = cv.clientWidth, H = cv.height = cv.clientHeight;
  c.clearRect(0,0,W,H);
  let vals = series.flat().filter(v=>isFinite(v));
  if(!vals.length) return;
  const lo = Math.min(...vals), hi = Math.max(...vals)||1;
  const colors=['#1668b8','#c2410c','#15803d','#7c3aed','#be123c',
                '#0e7490','#a16207','#4d7c0f'];
  series.forEach((s,si)=>{
    c.strokeStyle=colors[si%colors.length]; c.beginPath();
    s.forEach((v,i)=>{
      const x=i/(Math.max(s.length-1,1))*(W-40)+30;
      const y=H-15-(v-lo)/(hi-lo||1)*(H-30);
      i?c.lineTo(x,y):c.moveTo(x,y)});
    c.stroke();
    if(labels&&labels[si]){c.fillStyle=colors[si%colors.length];
      c.fillText(labels[si],35,12+12*si)}});
  c.fillStyle='#333';
  c.fillText(hi.toPrecision(4),2,12); c.fillText(lo.toPrecision(4),2,H-4);
}
async function tick(){
  const sessions = await (await fetch('api/sessions')).json();
  if(!sessions.length) return;
  const s = sessions[sessions.length-1];
  const d = await (await fetch('api/overview?session='+s)).json();
  draw(document.getElementById('score'), [d.scores]);
  draw(document.getElementById('tput'), [d.samples_per_sec]);
  const names = Object.keys(d.param_mean_magnitude||{});
  draw(document.getElementById('pm'),
       names.map(n=>d.param_mean_magnitude[n]), names);
  const info = d.static_info||{};
  const tbl = document.getElementById('info');
  tbl.replaceChildren(...Object.entries(info).map(([k,v])=>{
    const tr=document.createElement('tr');
    const th=document.createElement('th'); th.textContent=k;
    const td=document.createElement('td'); td.textContent=JSON.stringify(v);
    tr.append(th,td); return tr;}));
}
tick(); setInterval(tick, 2000);
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "DL4JTpuUI/1.0"
    storage: StatsStorage = None   # set by UIServer

    def log_message(self, *a):   # silence request logging
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        u = urlparse(self.path)
        if u.path in ("/", "/train", "/train/overview"):
            body = _PAGE.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if u.path == "/api/sessions":
            self._json(self.storage.list_session_ids())
            return
        if u.path == "/api/overview":
            q = parse_qs(u.query)
            sess = q.get("session", [None])[0]
            if not sess:
                ids = self.storage.list_session_ids()
                sess = ids[-1] if ids else None
            self._json(self._overview(sess))
            return
        if u.path == "/api/updates":
            q = parse_qs(u.query)
            sess = q.get("session", [None])[0]
            self._json(self.storage.get_all_updates(sess) if sess else [])
            return
        self._json({"error": "not found"}, 404)

    def do_POST(self):
        # RemoteReceiverModule analog: accept remote-routed records
        if urlparse(self.path).path != "/remote":
            self._json({"error": "not found"}, 404)
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n) or b"{}")
            record = payload.get("record", {})
            if "session_id" not in record:
                raise ValueError("record missing session_id")
            if payload.get("kind") == "static":
                self.storage.put_static_info(record)
            else:
                self.storage.put_update(record)
        except (ValueError, KeyError, json.JSONDecodeError) as e:
            self._json({"error": str(e)}, 400)
            return
        self._json({"ok": True})

    def _overview(self, session_id: Optional[str]) -> dict:
        if not session_id:
            return {}
        ups = self.storage.get_all_updates(session_id)
        pm: dict = {}
        for u in ups:
            for lname, st in (u.get("param_stats") or {}).items():
                pm.setdefault(lname, []).append(st.get("mean_magnitude"))
        return {
            "session": session_id,
            "iterations": [u.get("iteration") for u in ups],
            "scores": [u.get("score") for u in ups],
            "samples_per_sec": [u.get("samples_per_sec") or 0.0
                                for u in ups],
            "etl_ms": [u.get("etl_ms") for u in ups],
            "param_mean_magnitude": pm,
            "static_info": self.storage.get_static_info(session_id),
        }


class UIServer:
    """reference: api/UIServer.getInstance().attach(statsStorage). Serves
    on localhost; ``url`` gives the address for RemoteUIStatsStorageRouter
    peers."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000):
        self.port = port
        self.storage: Optional[StatsStorage] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def get_instance(cls, port: int = 9000) -> "UIServer":
        if cls._instance is None:
            cls._instance = cls(port)
        return cls._instance

    def attach(self, storage: StatsStorage):
        self.storage = storage
        if self._httpd is not None:
            self._httpd.RequestHandlerClass.storage = storage
        return self

    def start(self):
        if self._httpd is not None:
            return self
        if self.storage is None:
            raise RuntimeError(
                "attach(stats_storage) before start() — the UI has "
                "nothing to serve otherwise")
        handler = type("BoundHandler", (_Handler,),
                       {"storage": self.storage})
        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port),
                                          handler)
        self.port = self._httpd.server_address[1]   # resolves port 0
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
